"""Cluster-wide causal tracing: context propagation + span collection.

PR 4's TRACE verb summarizes a sync cycle per peer, but every span stops at
the process boundary: the initiator's walk spans and the donor's serve
spans cannot be stitched together. This module is the Dapper-style answer
(PAPERS.md) adapted to the text protocol:

- a **trace context** ``(trace_id, span_id, flags)`` travels as one compact
  trailing token ``tc=<trace16>-<span16>-<flags2>`` on the cluster verbs
  (TREELEVEL / HASHPAGE / SNAPMETA / SNAPCHUNK) and as a ``tc`` field on
  the replication batch envelope;
- every node keeps a process-wide **SpanCollector** ring: the initiator's
  ``span()`` sites record into it whenever a trace is active (each span
  allocates a fresh span id and parents to the enclosing one), and the
  native server relays traced serves as TRACESPAN notifications so the
  donor's side of a request lands in *its* collector under the *same*
  trace id;
- the ``TRACEDUMP`` verb dumps raw spans; :func:`chrome_trace_events`
  assembles dumps from several nodes into one Chrome trace-event JSON
  (load in Perfetto / chrome://tracing), flagging orphans — a span whose
  parent never arrived (dropped/truncated by a hostile link) is marked
  ``orphan`` and parented to nothing rather than mis-parented.

Clock caveat (the classic Dapper one): donor spans are placed on the
timeline by the donor's wall clock; cross-host skew shifts them visually
but never corrupts parent/child attribution, which rides on ids alone.
"""

from __future__ import annotations

import contextvars
import os
import struct
import threading
import time
from dataclasses import dataclass
from typing import Iterable, Optional

__all__ = [
    "TraceContext",
    "SpanRecord",
    "SpanCollector",
    "get_collector",
    "new_context",
    "parse_token",
    "trace_scope",
    "current",
    "current_token",
    "chrome_trace_events",
    "stitch",
]

FLAG_SAMPLED = 0x01


def _new_id() -> int:
    """Random non-zero 64-bit id (os.urandom: no shared-seed collisions
    across forked test processes)."""
    while True:
        (v,) = struct.unpack("<Q", os.urandom(8))
        if v:
            return v


@dataclass(frozen=True)
class TraceContext:
    """One hop of a causal trace: the trace's id plus the CURRENT span id
    (the parent any child span or outbound request stitches under)."""

    trace_id: int
    span_id: int
    flags: int = FLAG_SAMPLED

    def token(self) -> str:
        return f"tc={self.trace_id:016x}-{self.span_id:016x}-{self.flags:02x}"

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _new_id(), self.flags)


def new_context() -> TraceContext:
    """Fresh trace root. The root's span id IS the trace id — the root is
    a context, not a recorded span, and assembly treats a parent equal to
    the trace id as "child of the root", never as an orphan."""
    tid = _new_id()
    return TraceContext(tid, tid)


# Propagation master switch (config: [observability] trace_propagation).
# Off: cycles allocate no context, clients send no tokens, span() records
# nothing into the collector — the PR-4 surface exactly.
_propagation = True


def set_propagation(on: bool) -> None:
    global _propagation
    _propagation = bool(on)


def propagation_enabled() -> bool:
    return _propagation


def parse_token(tok: str) -> Optional[TraceContext]:
    """Strictly parse a ``tc=`` wire token; None for anything malformed
    (a corrupted token must drop the span, never corrupt stitching)."""
    if (
        len(tok) != 39
        or not tok.startswith("tc=")
        or tok[19] != "-"
        or tok[36] != "-"
    ):
        return None
    try:
        trace_id = int(tok[3:19], 16)
        span_id = int(tok[20:36], 16)
        flags = int(tok[37:39], 16)
    except ValueError:
        return None
    if trace_id == 0 or span_id == 0:
        return None
    return TraceContext(trace_id, span_id, flags)


# ------------------------------------------------------------- propagation

_current: contextvars.ContextVar[Optional[TraceContext]] = (
    contextvars.ContextVar("mkv_trace_ctx", default=None)
)


def current() -> Optional[TraceContext]:
    return _current.get()


def current_token() -> Optional[str]:
    ctx = _current.get()
    return ctx.token() if ctx is not None else None


class trace_scope:
    """Install ``ctx`` as the thread's active trace for the block. span()
    sites inside record into the collector; clients with a trace provider
    stamp outbound cluster verbs with the active token."""

    def __init__(self, ctx: TraceContext) -> None:
        self._ctx = ctx
        self._token = None

    @property
    def ctx(self) -> TraceContext:
        """The scope's context, readable before/after the block — callers
        that summarize AFTER __exit__ (the sync cycle's finally) take the
        trace id from here rather than the already-reset contextvar."""
        return self._ctx

    def __enter__(self) -> TraceContext:
        self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _current.reset(self._token)


def begin_span() -> Optional[tuple]:
    """span() entry hook: when a trace is active, allocate a child context
    and install it (nested spans and outbound requests parent to it).
    Returns opaque state for :func:`end_span`, or None (untraced)."""
    cur = _current.get()
    if cur is None:
        return None
    child = cur.child()
    reset = _current.set(child)
    return (cur, child, reset, time.time_ns())


def end_span(
    state: tuple,
    name: str,
    dur_ns: int,
    error: Optional[str] = None,
    cycle: int = 0,
) -> None:
    """span() exit hook: restore the parent context and record the span."""
    cur, child, reset, ts_ns = state
    _current.reset(reset)
    get_collector().record(
        trace_id=child.trace_id,
        span_id=child.span_id,
        parent_id=cur.span_id,
        name=name,
        role="initiator",
        ts_ns=ts_ns,
        dur_ns=dur_ns,
        cycle=cycle,
        error=error or "",
    )


# --------------------------------------------------------------- collector

@dataclass
class SpanRecord:
    trace_id: int
    span_id: int
    parent_id: int  # 0 = root (no parent)
    name: str
    role: str  # "initiator" | "donor" | "applier"
    ts_ns: int  # wall-clock start (unix ns, recorder's clock)
    dur_ns: int
    node: str = ""  # "host:port" when known, "" = this process
    cycle: int = 0  # anti-entropy cycle id when one was active
    error: str = ""


class SpanCollector:
    """Bounded FIFO of finished spans (thread-safe). One per process —
    multi-node-per-process tests share it, so spans carry a ``node`` tag
    where the recorder knows it."""

    def __init__(self, capacity: int = 8192) -> None:
        self._mu = threading.Lock()
        self._capacity = capacity
        self._spans: list[SpanRecord] = []

    def set_capacity(self, capacity: int) -> None:
        with self._mu:
            self._capacity = max(16, capacity)
            if len(self._spans) > self._capacity:
                del self._spans[: len(self._spans) - self._capacity]

    def record(
        self,
        trace_id: int,
        span_id: int,
        parent_id: int,
        name: str,
        role: str,
        ts_ns: int,
        dur_ns: int,
        node: str = "",
        cycle: int = 0,
        error: str = "",
    ) -> None:
        rec = SpanRecord(
            trace_id=trace_id,
            span_id=span_id,
            parent_id=parent_id,
            name=name,
            role=role,
            ts_ns=ts_ns,
            dur_ns=dur_ns,
            node=node,
            cycle=cycle,
            error=error,
        )
        with self._mu:
            self._spans.append(rec)
            if len(self._spans) > self._capacity:
                del self._spans[: len(self._spans) - self._capacity]

    def spans(self, n: int = 0) -> list[SpanRecord]:
        """Newest ``n`` spans (0 = all), oldest first."""
        with self._mu:
            if n <= 0:
                return list(self._spans)
            return list(self._spans[-n:])

    def __len__(self) -> int:
        with self._mu:
            return len(self._spans)

    def clear(self) -> None:
        with self._mu:
            self._spans.clear()

    def wire_dump(self, n: int = 0) -> str:
        """The TRACEDUMP response: ``SPANS <count>`` then one
        space-separated ``k=v`` row per span, closed by ``END`` (the
        PEERS/TRACE table shape, so clients reuse their field-table
        parser). Span names never contain spaces; error text is squeezed."""
        rows = []
        for s in self.spans(n):
            row = (
                f"trace={s.trace_id:016x} span={s.span_id:016x} "
                f"parent={s.parent_id:016x} name={s.name} role={s.role} "
                f"ts_ns={s.ts_ns} dur_ns={s.dur_ns} "
                f"node={s.node or '-'} cycle={s.cycle}"
            )
            if s.error:
                row += f" error={s.error.replace(' ', '_')[:80]}"
            rows.append(row)
        body = "".join(r + "\r\n" for r in rows)
        return f"SPANS {len(rows)}\r\n{body}END\r\n"


_collector = SpanCollector()


def get_collector() -> SpanCollector:
    return _collector


# --------------------------------------------------------------- assembly

def _parse_row(row: dict, default_node: str) -> Optional[SpanRecord]:
    """One TRACEDUMP k=v row -> SpanRecord; None for malformed rows (a
    truncation fault mid-dump must drop the row, never abort assembly)."""
    try:
        return SpanRecord(
            trace_id=int(row["trace"], 16),
            span_id=int(row["span"], 16),
            parent_id=int(row["parent"], 16),
            name=row["name"],
            role=row.get("role", "initiator"),
            ts_ns=int(row["ts_ns"]),
            dur_ns=int(row["dur_ns"]),
            node=(
                row.get("node", "-")
                if row.get("node", "-") != "-"
                else default_node
            ),
            cycle=int(row.get("cycle", 0)),
            error=row.get("error", ""),
        )
    except (KeyError, ValueError):
        return None


def stitch(
    dumps: Iterable[tuple[str, list[dict]]],
) -> dict[int, list[SpanRecord]]:
    """Merge TRACEDUMP row tables from several nodes into
    ``{trace_id: [spans]}``. ``dumps`` is ``(node_name, rows)`` pairs; a
    row without its own node tag inherits the dump's node name. Duplicate
    (trace, span) pairs — the same node dumped twice — keep the first."""
    out: dict[int, list[SpanRecord]] = {}
    seen: set[tuple[int, int]] = set()
    for node, rows in dumps:
        for row in rows:
            rec = _parse_row(row, node)
            if rec is None:
                continue
            key = (rec.trace_id, rec.span_id)
            if key in seen:
                continue
            seen.add(key)
            out.setdefault(rec.trace_id, []).append(rec)
    for spans in out.values():
        spans.sort(key=lambda s: s.ts_ns)
    return out


def orphan_spans(spans: list[SpanRecord]) -> set[int]:
    """Span ids within one trace whose parent span never arrived (dropped
    frame, truncated dump, dead peer). They are FLAGGED — rendered at the
    trace root with an ``orphan`` arg — never re-parented under a guess."""
    ids = {s.span_id for s in spans}
    return {
        s.span_id
        for s in spans
        if s.parent_id != 0
        and s.parent_id != s.trace_id  # child of the trace root
        and s.parent_id not in ids
    }


def main(argv: Optional[list[str]] = None) -> int:
    """``python -m merklekv_tpu trace --nodes a:port,b:port [--cycles N]``:
    pull TRACEDUMP from every node, stitch spans by trace id, and write
    one Perfetto-loadable Chrome trace-event JSON (stdout or ``--out``)."""
    import argparse
    import json
    import sys

    from merklekv_tpu.client import MerkleKVClient, MerkleKVError

    p = argparse.ArgumentParser(
        prog="merklekv_tpu trace",
        description="assemble cross-node causal traces into Chrome "
        "trace-event JSON (load in Perfetto / chrome://tracing)",
    )
    p.add_argument(
        "--nodes", required=True,
        help="comma-separated host:port list to pull TRACEDUMP from",
    )
    p.add_argument(
        "--cycles", type=int, default=0,
        help="keep only the newest N traces (anti-entropy cycles); "
        "0 = all",
    )
    p.add_argument("--out", help="write JSON here instead of stdout")
    p.add_argument("--timeout", type=float, default=5.0)
    args = p.parse_args(argv)

    dumps: list[tuple[str, list[dict]]] = []
    for node in [n.strip() for n in args.nodes.split(",") if n.strip()]:
        host, _, port = node.rpartition(":")
        try:
            with MerkleKVClient(host, int(port), timeout=args.timeout) as c:
                dumps.append((node, c.trace_dump(0)))
        except (MerkleKVError, OSError, ValueError) as e:
            print(f"# {node}: dump failed ({e})", file=sys.stderr)
    traces = stitch(dumps)
    if args.cycles > 0 and len(traces) > args.cycles:
        newest = sorted(
            traces, key=lambda t: max(s.ts_ns for s in traces[t])
        )[-args.cycles:]
        traces = {t: traces[t] for t in newest}
    doc = chrome_trace_events(traces)
    payload = json.dumps(doc, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
        n_spans = sum(len(s) for s in traces.values())
        print(f"wrote {args.out}: {len(traces)} traces, {n_spans} spans")
    else:
        print(payload)
    return 0


def chrome_trace_events(
    traces: dict[int, list[SpanRecord]],
) -> dict:
    """Chrome trace-event JSON (Perfetto / chrome://tracing loadable).

    Layout: one pid per node (process_name metadata carries the node
    address), complete ("X") events in microseconds; parent/child nesting
    is carried by the ``parent`` arg (ids, not timestamps — skewed donor
    clocks shift placement, not attribution). Orphans get
    ``args.orphan = true``."""
    events: list[dict] = []
    pids: dict[str, int] = {}

    def pid_for(node: str) -> int:
        name = node or "local"
        if name not in pids:
            pids[name] = len(pids) + 1
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pids[name],
                    "tid": 0,
                    "args": {"name": name},
                }
            )
        return pids[name]

    for trace_id, spans in traces.items():
        orphans = orphan_spans(spans)
        for s in spans:
            args = {
                "trace_id": f"{trace_id:016x}",
                "span_id": f"{s.span_id:016x}",
                "parent": f"{s.parent_id:016x}" if s.parent_id else "",
                "role": s.role,
            }
            if s.cycle:
                args["cycle"] = s.cycle
            if s.error:
                args["error"] = s.error
            if s.span_id in orphans:
                args["orphan"] = True
            events.append(
                {
                    "name": s.name,
                    "cat": s.role,
                    "ph": "X",
                    "ts": s.ts_ns / 1e3,
                    "dur": max(s.dur_ns, 1) / 1e3,
                    "pid": pid_for(s.node),
                    "tid": 1,
                    "args": args,
                }
            )
    return {"traceEvents": events, "displayTimeUnit": "ms"}

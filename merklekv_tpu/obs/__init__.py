"""Observability plane: metrics registry, Prometheus exporter, sync traces.

Subsumes and extends ``utils/tracing.py`` (which keeps its ``span`` /
``get_metrics`` API, now backed by this package's registry):

- ``obs.metrics`` — counters, fixed-log-bucket histograms (percentiles
  derivable from buckets), callback gauges;
- ``obs.exporter`` — per-node HTTP ``/metrics`` (Prometheus text
  exposition) + ``/healthz``, bridging native STATS into one namespace;
- ``obs.trace``  — anti-entropy cycle ids (stamped into every span) and
  the per-peer ring buffer behind the ``TRACE <n>`` wire verb;
- ``obs.top``    — the ``python -m merklekv_tpu top`` terminal dashboard.

See docs/OBSERVABILITY.md for the metric catalog and scrape setup.
"""

from merklekv_tpu.obs.exporter import MetricsExporter, render_prometheus
from merklekv_tpu.obs.metrics import (
    BUCKET_BOUNDS,
    Histogram,
    Metrics,
    bucket_index,
    get_metrics,
)
from merklekv_tpu.obs.trace import (
    CycleTrace,
    PeerTrace,
    SyncTraceBuffer,
    current_cycle_id,
    cycle_scope,
    get_trace_buffer,
    next_cycle_id,
)

__all__ = [
    "BUCKET_BOUNDS",
    "bucket_index",
    "Histogram",
    "Metrics",
    "get_metrics",
    "MetricsExporter",
    "render_prometheus",
    "CycleTrace",
    "PeerTrace",
    "SyncTraceBuffer",
    "current_cycle_id",
    "cycle_scope",
    "get_trace_buffer",
    "next_cycle_id",
]

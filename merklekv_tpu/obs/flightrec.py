"""Flight recorder: the always-on black box behind post-mortem forensics.

The live observability plane (metrics, traces, lag SLO) answers "what is
the node doing NOW"; this module answers "what was the node doing in the
seconds before it died" — the Dapper lesson that always-on, low-overhead
recording is what turns an unreproducible kill -9 / rc=124 / SIGSEGV into
a diagnosable timeline. Three parts:

- :class:`FlightRecorder` — a process-wide bounded ring of **structured
  events**: state transitions (degradation-ladder changes, peer health
  flips, sync-cycle outcomes, bootstrap phases, storage full/recovery
  latches, replication skew clamps, slow commands relayed from the native
  server) stamped with wall + monotonic nanoseconds and a sequence
  number. Recording is one lock acquire + a deque append — cheap enough
  to stay on everywhere, always.

- :class:`MetricSampler` — a background thread snapshotting counter
  values and gauges every ``[observability] flight_sample_s`` (default
  1 s) into a fixed ~15-minute ring, so "what changed in the 60 s before
  death" is always answerable from the spill. Watch-listed native
  counters (admission rejections, event drops) additionally materialize
  as flight events when their deltas are non-zero.

- :class:`FlightSpiller` — a periodically rewritten, CRC-framed spill
  file under ``[observability] flight_dir``, written tmp+fsync+rename so
  a kill -9 at ANY instant leaves the previous complete spill on disk.
  :func:`read_spill` tolerates truncation at every byte offset (it
  returns the parseable prefix), and ``python -m merklekv_tpu blackbox``
  merges several nodes' spills into one cluster timeline
  (obs/blackbox.py).

Fatal paths: :func:`install_fault_handlers` arms ``faulthandler`` so a
SIGSEGV/SIGABRT/SIGBUS leaves Python tracebacks beside the spill (the
native layer's crash marker — ``mkv_install_crash_marker`` — chains ahead
of it and stamps the signal + wall time), and :meth:`FlightRecorder.dump`
is the direct path watchdogs call before ``os._exit``.

Scope: the recorder is PROCESS-wide (like the metrics registry) — one
node per process in production, so the ring IS the node's black box.
Co-located test nodes sharing a process share one ring; their spills are
then copies of the same stream, which the blackbox analyzer detects by
full event identity (pid + seq + timestamps) and reports once instead of
double-counting.
"""

from __future__ import annotations

import json
import os
import re
import struct
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = [
    "FlightEvent",
    "FlightRecorder",
    "MetricSampler",
    "FlightSpiller",
    "SpillDoc",
    "get_recorder",
    "record",
    "read_spill",
    "write_spill",
    "install_fault_handlers",
    "SPILL_MAGIC",
]

# Spill file magic: identifies the format + version. A file without it is
# "not a spill" (blackbox reports it as unreadable, rc 1) rather than a
# truncated one (rc 0 with a prefix).
SPILL_MAGIC = b"MKVFLT1\n"

# One spill frame: u32 payload length, u32 CRC32(payload), payload (JSON
# bytes). The whole file is rewritten atomically, so framing exists for
# disk-corruption tolerance and for the direct fatal-dump path (which may
# be cut mid-write by the very death it is recording).
_FRAME_HDR = struct.Struct("<II")
# Sanity bound on one frame: a length field beyond this reads as
# corruption, not as an allocation request.
_MAX_FRAME = 8 << 20


@dataclass
class FlightEvent:
    """One recorded state transition."""

    seq: int
    wall_ns: int
    mono_ns: int
    kind: str
    fields: dict = field(default_factory=dict)

    def wire_row(self) -> str:
        """Space-separated ``k=v`` fields (the PEERS/TRACE table shape, so
        clients reuse their field-table parser). Free-text values are
        squeezed to single tokens."""
        parts = [
            f"seq={self.seq}",
            f"wall_ns={self.wall_ns}",
            f"kind={self.kind}",
        ]
        for k, v in self.fields.items():
            if k in ("seq", "wall_ns", "kind"):
                # A field legitimately named like a header key must not
                # shadow it in the client's k=v dict.
                k = f"f.{k}"
            # Squeeze ALL whitespace, not just spaces: an embedded newline
            # (a multi-line OSError message in a reason field) would split
            # the row and desync the client's field-table framing.
            sv = re.sub(r"\s+", "_", str(v))[:120]
            parts.append(f"{k}={sv}")
        return " ".join(parts)

    def to_json(self) -> dict:
        return {
            "t": "event",
            "seq": self.seq,
            "wall_ns": self.wall_ns,
            "mono_ns": self.mono_ns,
            "kind": self.kind,
            "f": self.fields,
        }


class FlightRecorder:
    """Process-wide bounded event ring (thread-safe).

    Always on: recording costs one lock + one deque append, and the ring
    bounds memory at ``capacity`` events regardless of rate. The newest
    events are what the FLIGHT verb streams and what the spill persists.
    """

    def __init__(self, capacity: int = 2048) -> None:
        self._mu = threading.Lock()
        self._ring: deque[FlightEvent] = deque(maxlen=max(16, capacity))
        self._seq = 0
        self._dropped = 0

    def set_capacity(self, capacity: int) -> None:
        with self._mu:
            old = list(self._ring)
            self._ring = deque(old, maxlen=max(16, capacity))

    def record(self, kind: str, /, **fields) -> FlightEvent:
        """Append one event; never raises (a broken field repr drops the
        field, not the event — the recorder must not be able to kill the
        subsystem that called it). ``kind`` is positional-only so a field
        may legitimately be named ``kind`` too."""
        clean = {}
        for k, v in fields.items():
            try:
                if isinstance(v, (int, float, bool)):
                    clean[k] = v
                else:
                    clean[k] = str(v)
            except Exception:
                continue
        # Trace join point: while a causal trace context is active on this
        # thread (anti-entropy cycle, bootstrap), stamp its trace id so the
        # blackbox analyzer can link this event to the same cycle's events
        # on OTHER nodes' spills.
        if "trace" not in clean:
            try:
                from merklekv_tpu.obs import tracewire

                tok = tracewire.current_token()
                if tok:
                    clean["trace"] = tok[3:19]  # trace id only
            except Exception:
                pass
        with self._mu:
            self._seq += 1
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            ev = FlightEvent(
                seq=self._seq,
                wall_ns=time.time_ns(),
                mono_ns=time.monotonic_ns(),
                kind=kind,
                fields=clean,
            )
            self._ring.append(ev)
        return ev

    def last(self, n: int = 0) -> list[FlightEvent]:
        """Newest ``n`` events (0 = all), oldest first."""
        with self._mu:
            evs = list(self._ring)
        return evs[-n:] if n > 0 else evs

    def dropped(self) -> int:
        with self._mu:
            return self._dropped

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()
            self._dropped = 0

    def wire_dump(self, n: int) -> str:
        """The FLIGHT verb's response: ``EVENTS <rows>`` then one ``k=v``
        row per event, NEWEST first, closed by ``END``."""
        evs = list(reversed(self.last(max(1, n))))
        body = "".join(ev.wire_row() + "\r\n" for ev in evs)
        return f"EVENTS {len(evs)}\r\n{body}END\r\n"

    def dump(self, path: str, samples: Optional[list] = None,
             node: str = "", note: str = "") -> bool:
        """Direct spill write for fatal paths (watchdogs, exit hooks):
        best effort, never raises."""
        try:
            write_spill(
                path,
                self.last(0),
                samples or [],
                node=node,
                note=note,
            )
            return True
        except Exception:
            return False


_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _recorder


def record(kind: str, /, **fields) -> None:
    """Module-level shorthand: record into the process-wide ring."""
    _recorder.record(kind, **fields)


# ------------------------------------------------------------------ sampler


@dataclass
class Sample:
    """One metric snapshot: cumulative integer values at ``wall_ns``."""

    wall_ns: int
    values: dict

    def to_json(self) -> dict:
        return {"t": "sample", "wall_ns": self.wall_ns, "v": self.values}


# Native counters whose per-sample DELTAS materialize as flight events —
# these are request-path rejections the python plane never sees one by one
# (they happen in the native accept loop / read path), but whose bursts
# are exactly what a post-mortem needs on the timeline.
WATCHED_NATIVE = {
    "busy_rejected_connections": "admission_reject",
    "pipeline_rejected": "pipeline_reject",
    "events_dropped": "events_dropped",
    "shed_commands": "writes_shed",
    "readonly_commands": "writes_refused_readonly",
}


class MetricSampler:
    """Continuous time-series sampler feeding the spill.

    Every ``interval_s`` it snapshots the metrics registry's counters, the
    flattened gauge values, and (when ``stats_fn`` is given) the native
    STATS integer lines, keeping ``window_s`` worth of samples in a fixed
    ring. Sampling runs off the request path entirely; its cost is one
    registry snapshot + one STATS render per second.
    """

    def __init__(
        self,
        interval_s: float = 1.0,
        stats_fn: Optional[Callable[[], str]] = None,
        window_s: float = 900.0,
        recorder: Optional[FlightRecorder] = None,
    ) -> None:
        self._interval = max(0.05, float(interval_s))
        self._stats_fn = stats_fn
        self._recorder = recorder if recorder is not None else _recorder
        cap = max(2, int(window_s / self._interval))
        self._mu = threading.Lock()
        self._ring: deque[Sample] = deque(maxlen=cap)
        self._prev_watch: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricSampler":
        if self._thread is None:
            self.sample_once()  # a just-started node already has a sample
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="mkv-flight-sampler"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.sample_once()
            except Exception:
                # A broken gauge or a dying server handle must not kill
                # the sampler — the spill keeps its last good samples.
                from merklekv_tpu.utils.tracing import get_metrics

                get_metrics().inc("flight.sample_errors")

    def sample_once(self) -> Sample:
        """One snapshot + watched-delta event derivation (tests call this
        directly instead of sleeping out the ticker)."""
        from merklekv_tpu.utils.tracing import get_metrics

        values: dict = {}
        m = get_metrics()
        snap = m.snapshot()
        for name, v in snap["counters"].items():
            values[name] = int(v)
        for name, g in m.gauges_snapshot().items():
            v = g.get("value")
            if isinstance(v, dict):
                for label, lv in v.items():
                    if isinstance(lv, (int, float)):
                        values[f"{name}.{label}"] = int(lv)
            elif isinstance(v, (int, float)):
                values[name] = int(v)
        if self._stats_fn is not None:
            try:
                for line in self._stats_fn().splitlines():
                    name, sep, val = line.strip().partition(":")
                    if not sep:
                        continue
                    try:
                        values[f"native.{name}"] = int(val)
                    except ValueError:
                        continue  # uptime_human etc.
            except Exception:
                pass
        sample = Sample(wall_ns=time.time_ns(), values=values)
        with self._mu:
            self._ring.append(sample)
        # Watched native counters: a non-zero delta becomes a flight event
        # (the rejection itself happened in the native accept/read path,
        # invisible to python until now).
        for stat, kind in WATCHED_NATIVE.items():
            cur = values.get(f"native.{stat}")
            if cur is None:
                continue
            prev = self._prev_watch.get(stat)
            self._prev_watch[stat] = cur
            if prev is not None and cur > prev:
                self._recorder.record(kind, count=cur - prev, total=cur)
        return sample

    def samples(self, n: int = 0) -> list[Sample]:
        """Newest ``n`` samples (0 = all), oldest first."""
        with self._mu:
            out = list(self._ring)
        return out[-n:] if n > 0 else out


# -------------------------------------------------------------------- spill


@dataclass
class SpillDoc:
    """A parsed spill: whatever prefix of the file was intact."""

    path: str
    meta: dict = field(default_factory=dict)
    events: list[FlightEvent] = field(default_factory=list)
    samples: list[Sample] = field(default_factory=list)
    truncated: bool = False
    error: str = ""  # why parsing stopped early ("" = clean EOF)

    @property
    def node(self) -> str:
        return str(self.meta.get("node", "") or
                   os.path.basename(self.path))


def _frames(meta: dict, events: list[FlightEvent],
            samples: list[Sample]) -> list[bytes]:
    out = [json.dumps({"t": "meta", **meta},
                      separators=(",", ":")).encode()]
    for ev in events:
        out.append(json.dumps(ev.to_json(), separators=(",", ":")).encode())
    for s in samples:
        out.append(json.dumps(s.to_json(), separators=(",", ":")).encode())
    return out


def write_spill(
    path: str,
    events: list[FlightEvent],
    samples: list[Sample],
    node: str = "",
    note: str = "",
) -> None:
    """Write one complete spill atomically: tmp + fsync + rename, so a
    kill -9 at any instant leaves either the previous complete spill or
    this one — never a torn file under the final name."""
    meta = {
        "node": node,
        "pid": os.getpid(),
        "written_wall_ns": time.time_ns(),
        "written_mono_ns": time.monotonic_ns(),
        "events": len(events),
        "samples": len(samples),
    }
    if note:
        meta["note"] = note
    body = bytearray(SPILL_MAGIC)
    for payload in _frames(meta, events, samples):
        body += _FRAME_HDR.pack(len(payload), zlib.crc32(payload))
        body += payload
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        # Loop the write: a short write (disk nearly full) must raise, not
        # fall through to the rename — renaming a torn tmp over the
        # previous COMPLETE spill would destroy the history exactly when
        # the black box is most needed.
        view = memoryview(bytes(body))
        while view:
            n = os.write(fd, view)
            if n <= 0:
                raise OSError("short write on flight spill")
            view = view[n:]
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)


def read_spill(path: str) -> SpillDoc:
    """Parse a spill, tolerating truncation at EVERY byte offset and
    interior corruption: parsing stops at the first bad frame and the doc
    carries the intact prefix (``truncated``/``error`` describe why).
    Raises ``ValueError`` only when the file is not a spill at all
    (missing magic) and ``OSError`` when unreadable."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(SPILL_MAGIC):
        if SPILL_MAGIC.startswith(data):
            # So short it is a prefix of the magic itself: a torn fatal
            # dump, not a foreign file.
            return SpillDoc(path=path, truncated=True,
                            error="truncated inside file magic")
        raise ValueError(f"{path}: not a flight spill (bad magic)")
    doc = SpillDoc(path=path)
    off = len(SPILL_MAGIC)
    while off < len(data):
        if off + _FRAME_HDR.size > len(data):
            doc.truncated = True
            doc.error = "truncated frame header"
            break
        length, crc = _FRAME_HDR.unpack_from(data, off)
        if length > _MAX_FRAME:
            doc.truncated = True
            doc.error = f"implausible frame length {length}"
            break
        start = off + _FRAME_HDR.size
        end = start + length
        if end > len(data):
            doc.truncated = True
            doc.error = "truncated frame payload"
            break
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            doc.truncated = True
            doc.error = "frame crc mismatch"
            break
        try:
            obj = json.loads(payload)
        except ValueError:
            doc.truncated = True
            doc.error = "frame payload not json"
            break
        t = obj.get("t")
        if t == "meta":
            doc.meta = {k: v for k, v in obj.items() if k != "t"}
        elif t == "event":
            doc.events.append(
                FlightEvent(
                    seq=int(obj.get("seq", 0)),
                    wall_ns=int(obj.get("wall_ns", 0)),
                    mono_ns=int(obj.get("mono_ns", 0)),
                    kind=str(obj.get("kind", "")),
                    fields=dict(obj.get("f", {})),
                )
            )
        elif t == "sample":
            doc.samples.append(
                Sample(
                    wall_ns=int(obj.get("wall_ns", 0)),
                    values=dict(obj.get("v", {})),
                )
            )
        # Unknown frame types skip silently: forward compatibility.
        off = end
    return doc


class FlightSpiller:
    """Periodic spill writer: every ``interval_s`` the current ring +
    sample window are rewritten to ``<dir>/flight.bin`` atomically. The
    first spill is written inline at :meth:`start` so even a node that
    dies seconds after boot leaves a record."""

    FILENAME = "flight.bin"

    def __init__(
        self,
        directory: str,
        recorder: Optional[FlightRecorder] = None,
        sampler: Optional[MetricSampler] = None,
        interval_s: float = 10.0,
        node: str = "",
    ) -> None:
        self._dir = directory
        self._recorder = recorder if recorder is not None else _recorder
        self._sampler = sampler
        self._interval = max(0.1, float(interval_s))
        self._node = node
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def path(self) -> str:
        return os.path.join(self._dir, self.FILENAME)

    def start(self) -> "FlightSpiller":
        if self._thread is None:
            # The inline first spill is STRICT: an unwritable flight dir
            # raises here so the caller can disable the spiller loudly,
            # instead of a background thread retrying a doomed write
            # forever while the operator never sees a diagnostic.
            self.spill_once(strict=True)
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="mkv-flight-spill"
            )
            self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final:
            self.spill_once()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.spill_once()

    def spill_once(self, strict: bool = False) -> bool:
        from merklekv_tpu.utils.tracing import get_metrics

        try:
            write_spill(
                self.path,
                self._recorder.last(0),
                self._sampler.samples(0) if self._sampler else [],
                node=self._node,
            )
            get_metrics().inc("flight.spills")
            return True
        except OSError:
            # A full disk must not kill the PERIODIC spiller (the node is
            # already degrading through the storage plane); the previous
            # complete spill stays valid on disk. strict (the start()
            # probe) re-raises so a misconfigured dir fails loudly.
            get_metrics().inc("flight.spill_errors")
            if strict:
                raise
            return False


# --------------------------------------------------------------- fatal paths

_fault_file = None  # keep the traceback fd alive for faulthandler


def install_fault_handlers(directory: str) -> Optional[str]:
    """Arm ``faulthandler`` so SIGSEGV/SIGABRT/SIGBUS/SIGFPE leave Python
    tracebacks at ``<dir>/crash-<pid>.txt``. Returns the traceback path
    (None when faulthandler could not be armed). The native crash marker
    (``mkv_install_crash_marker``) is installed AFTER this by the caller
    so its handler runs first and chains into faulthandler's."""
    global _fault_file
    import faulthandler

    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"crash-{os.getpid()}.txt")
        _fault_file = open(path, "w")
        faulthandler.enable(file=_fault_file, all_threads=True)
        return path
    except (OSError, ValueError, RuntimeError):
        return None

"""``python -m merklekv_tpu blackbox`` — offline post-mortem analyzer.

Reads one or more flight spills (files, or directories containing
``flight.bin`` + crash markers), merges every node's events into ONE
causally-ordered cluster timeline, and flags anomalies — the offline
complement of the live ``top``/``trace`` surfaces:

- **ordering**: events merge by wall clock; events sharing a trace id
  (stamped while an anti-entropy/bootstrap trace context was active, or
  relayed through SLOWCMD during a traced serve) are additionally LINKED
  across nodes — clock skew can shuffle their absolute placement but
  never their attribution to the same causal cycle. Envelope ``hseq``
  high-water marks ride in the samples (``replication.lag_events.*``),
  so per-peer convergence state is readable at every sample tick.

- **anomalies**: degradation-ladder flips, storage full latches,
  peer-health flips, sync-cycle errors, slow-command bursts (>= 3 within
  10 s), skew-clamp bursts, admission-rejection bursts, device-tree
  staleness breaches (wedged update pump), device-backend ladder
  step-downs / fallback-serving heartbeats / scrub-caught corruption
  (with the environment|code classified kind), and lag spikes
  from the sampled ``replication.lag_events.*`` series.

- **fatal context**: ``fatal.txt`` crash markers (native signal stamps)
  and ``crash-<pid>.txt`` faulthandler tracebacks found beside a spill
  surface as synthetic timeline events, so "what killed it" and "what it
  was doing" read side by side.

Exit code 0 when every input parsed (truncated tails are reported, not
fatal — the atomic spill rewrite means a kill -9 leaves a COMPLETE file;
truncation only appears on fatal-path direct dumps or disk corruption);
1 when an input was unreadable or not a spill at all.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

from merklekv_tpu.obs.flightrec import (
    FlightEvent,
    FlightSpiller,
    SpillDoc,
    read_spill,
)

__all__ = ["collect_inputs", "load_docs", "merge_timeline", "find_anomalies",
           "main"]

# Anomaly windows/thresholds (documented in OBSERVABILITY.md).
SLOW_BURST_N = 3
SLOW_BURST_WINDOW_NS = 10 * 1_000_000_000
LAG_SPIKE_EVENTS = 100


@dataclass
class TimelineEntry:
    node: str
    event: FlightEvent


@dataclass
class Anomaly:
    wall_ns: int
    node: str
    kind: str
    detail: str


@dataclass
class Report:
    docs: list[SpillDoc] = field(default_factory=list)
    timeline: list[TimelineEntry] = field(default_factory=list)
    anomalies: list[Anomaly] = field(default_factory=list)
    trace_links: dict[str, list[str]] = field(default_factory=dict)
    errors: list[str] = field(default_factory=list)  # unreadable inputs


def collect_inputs(paths: list[str]) -> tuple[list[str], list[str]]:
    """Resolve CLI arguments into (spill files, crash-marker files). A
    directory contributes its ``flight.bin`` plus any ``fatal.txt`` /
    ``crash-*.txt`` markers; a file is taken as a spill directly."""
    spills: list[str] = []
    markers: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            cand = os.path.join(p, FlightSpiller.FILENAME)
            if os.path.exists(cand):
                spills.append(cand)
            for name in sorted(os.listdir(p)):
                if name == "fatal.txt" or (
                    name.startswith("crash-") and name.endswith(".txt")
                ):
                    markers.append(os.path.join(p, name))
        else:
            spills.append(p)
    return spills, markers


_MARKER_RE = re.compile(
    r"fatal signal (\d+) pid (\d+) wall_ns (\d+)"
)


def _marker_events(path: str) -> list[FlightEvent]:
    """Synthetic events from a crash-marker / faulthandler file."""
    out: list[FlightEvent] = []
    try:
        with open(path, errors="replace") as f:
            text = f.read()
    except OSError:
        return out
    for m in _MARKER_RE.finditer(text):
        out.append(
            FlightEvent(
                seq=0,
                wall_ns=int(m.group(3)),
                mono_ns=0,
                kind="fatal_signal",
                fields={"signal": int(m.group(1)), "pid": int(m.group(2)),
                        "file": os.path.basename(path)},
            )
        )
    if not out and "Current thread" in text:
        # A faulthandler traceback without a native marker: stamp it at
        # the file's mtime (best available clock).
        try:
            wall = int(os.path.getmtime(path) * 1e9)
        except OSError:
            wall = 0
        out.append(
            FlightEvent(
                seq=0, wall_ns=wall, mono_ns=0, kind="crash_traceback",
                fields={"file": os.path.basename(path)},
            )
        )
    return out


def load_docs(paths: list[str]) -> Report:
    report = Report()
    spills, markers = collect_inputs(paths)
    if not spills:
        report.errors.append("no spill files found in the given paths")
        return report
    for sp in spills:
        try:
            doc = read_spill(sp)
        except (OSError, ValueError) as e:
            report.errors.append(f"{sp}: {e}")
            continue
        report.docs.append(doc)
    # Attribute crash markers to the node whose spill shares their
    # directory (every node's markers live beside its flight.bin) — the
    # directory basename is just "flight" for everyone and would collapse
    # all nodes' fatal events onto one bogus name.
    node_by_dir = {
        os.path.dirname(os.path.abspath(doc.path)): doc.node
        for doc in report.docs
    }
    marker_entries: list[TimelineEntry] = []
    for mp in markers:
        mdir = os.path.dirname(os.path.abspath(mp))
        node = node_by_dir.get(
            mdir, os.path.basename(os.path.dirname(mp)) or mp
        )
        for ev in _marker_events(mp):
            marker_entries.append(TimelineEntry(node=node, event=ev))
    report.timeline = merge_timeline(report.docs, marker_entries)
    report.anomalies = find_anomalies(report.docs, report.timeline)
    report.trace_links = link_traces(report.timeline)
    return report


def merge_timeline(
    docs: list[SpillDoc],
    extra: Optional[list[TimelineEntry]] = None,
) -> list[TimelineEntry]:
    """All nodes' events merged into one timeline.

    K-way merge of per-node streams: each node's events are first put in
    SEQUENCE order (the ring's own total order), then streams interleave
    by wall clock — so cross-node placement follows the clocks, but a
    node whose wall clock stepped backwards mid-run (NTP correction) can
    never have its own events reordered on the merged view
    (storage_recovered can't print before its storage_full).

    Two spills of the SAME process ring (co-located nodes sharing one
    process share the process-wide recorder) are deduplicated by full
    event identity (pid, seq, wall_ns, mono_ns, kind): the first doc's
    attribution wins and the analyzer reports each event once instead of
    double-counting every anomaly. Distinct rings — even in one process —
    never collide on wall+mono nanosecond stamps."""
    import heapq

    streams: list[tuple[str, list[TimelineEntry]]] = []
    seen_ring: set[tuple] = set()
    for doc in docs:
        pid = int(doc.meta.get("pid", 0) or 0)
        evs = sorted(doc.events, key=lambda ev: ev.seq)
        kept = []
        for ev in evs:
            if pid and ev.seq:
                key = (pid, ev.seq, ev.wall_ns, ev.mono_ns, ev.kind)
                if key in seen_ring:
                    continue  # same process ring spilled twice
                seen_ring.add(key)
            kept.append(TimelineEntry(node=doc.node, event=ev))
        if kept:
            streams.append((doc.node, kept))
    for e in extra or []:
        streams.append((e.node, [e]))
    heap = []
    for si, (node, evs) in enumerate(streams):
        heapq.heappush(heap, (evs[0].event.wall_ns, node, si, 0))
    out: list[TimelineEntry] = []
    while heap:
        _, _, si, i = heapq.heappop(heap)
        evs = streams[si][1]
        out.append(evs[i])
        if i + 1 < len(evs):
            heapq.heappush(
                heap, (evs[i + 1].event.wall_ns, streams[si][0], si, i + 1)
            )
    return out


def link_traces(timeline: list[TimelineEntry]) -> dict[str, list[str]]:
    """trace id -> nodes that recorded events under it. Links spanning
    >= 2 nodes are the cross-node causal joins (one sync cycle's initiator
    and donors, one bootstrap's joiner and donor)."""
    seen: dict[str, list[str]] = {}
    for e in timeline:
        tid = str(e.event.fields.get("trace", "") or "")
        if not tid:
            continue
        nodes = seen.setdefault(tid, [])
        if e.node not in nodes:
            nodes.append(e.node)
    return {t: ns for t, ns in seen.items() if len(ns) >= 2}


def find_anomalies(
    docs: list[SpillDoc], timeline: list[TimelineEntry]
) -> list[Anomaly]:
    out: list[Anomaly] = []

    def add(e: TimelineEntry, kind: str, detail: str) -> None:
        out.append(
            Anomaly(wall_ns=e.event.wall_ns, node=e.node, kind=kind,
                    detail=detail)
        )

    slow_recent: dict[str, list[int]] = {}
    burst_flagged: dict[str, int] = {}
    for e in timeline:
        ev = e.event
        f = ev.fields
        if ev.kind == "degradation" and str(f.get("new")) != "live":
            add(e, "degradation",
                f"{f.get('prev')} -> {f.get('new')} ({f.get('reason')})")
        elif ev.kind == "storage_full":
            add(e, "storage_full", str(f.get("reason", "")))
        elif ev.kind == "peer_health" and str(f.get("new")) in (
            "down", "degraded"
        ):
            add(e, "peer_flip", f"{f.get('peer')} -> {f.get('new')}")
        elif ev.kind == "sync_cycle" and str(f.get("outcome")) in (
            "error", "degraded"
        ):
            add(e, "sync_failure",
                f"cycle {f.get('cycle')} outcome={f.get('outcome')}")
        elif ev.kind == "skew_clamp":
            add(e, "skew_clamp",
                f"{f.get('count')} events from {f.get('srcs')}")
        elif ev.kind == "tree_staleness":
            # The device-update pump breached its [device] max_staleness
            # contract (or stalled outright) — a wedged device queue.
            add(e, "tree_staleness",
                f"pump lag {f.get('lag_ms')}ms / "
                f"{f.get('lag_versions')} versions "
                f"(window {f.get('window_ms')}ms)")
        elif ev.kind == "device_degraded":
            # The device degradation ladder stepped down a rung; the
            # classified kind says whether it was backend weather
            # (environment) or a code failure that should page.
            add(e, "device_degraded",
                f"rung {f.get('from_rung')} -> {f.get('to_rung')} "
                f"({f.get('kind')} @ {f.get('where')})")
        elif ev.kind == "device_fallback":
            # Heartbeat (one per 10s window): a previously ready mirror is
            # serving off the NATIVE fallback — invalidated and not yet
            # re-warmed. Visible here so fallback serving is never silent.
            add(e, "device_fallback",
                f"serving native fallback (ladder rung {f.get('rung')})")
        elif ev.kind == "device_corruption":
            # The integrity scrub caught the served device tree diverging
            # from the engine — silent corruption; invalidate+rebuild was
            # triggered.
            add(e, "device_corruption",
                f"scrub mismatch at leaf {f.get('leaf_index')} "
                f"(rung {f.get('rung')})")
        elif ev.kind == "partition_degraded":
            # One partition's replica left live (partitioned cluster
            # mode): the partition-scope summary below folds these across
            # nodes to tell a partition-local incident (one replica
            # group) from a cluster-wide one (every partition at once).
            add(e, "partition_degraded",
                f"partition {f.get('partition')} -> {f.get('level')} "
                f"({f.get('reason')})")
        elif ev.kind == "partition_healed":
            add(e, "partition_healed",
                f"partition {f.get('partition')} back to live")
        elif ev.kind in ("admission_reject", "pipeline_reject",
                         "events_dropped"):
            add(e, "rejection_burst", f"{ev.kind} +{f.get('count')}")
        elif ev.kind == "fatal_signal":
            add(e, "fatal_signal",
                f"signal {f.get('signal')} pid {f.get('pid')}")
        elif ev.kind == "watchdog-timeout" or (
            ev.kind == "multichip_phase"
            and str(f.get("phase")) == "watchdog-timeout"
        ):
            add(e, "watchdog", str(f.get("stuck_in", "")))
        elif (
            ev.kind == "multichip_phase"
            and str(f.get("phase")) in ("device-count", "device-enumerate")
            and str(f.get("have", "")).isdigit()
            and str(f.get("want", "")).isdigit()
            and int(f["have"]) < int(f["want"])
        ):
            # Device-complement shortfall (MULTICHIP_r01's failure mode):
            # surfaced as ENVIRONMENT weather so triage reads the cause
            # directly instead of treating the round as a code regression
            # (the probe's JSON record carries the matching error_kind).
            add(e, "environment",
                f"device shortfall: have {f['have']}, want {f['want']}")
        elif ev.kind == "slow_command":
            win = slow_recent.setdefault(e.node, [])
            win.append(ev.wall_ns)
            while win and ev.wall_ns - win[0] > SLOW_BURST_WINDOW_NS:
                win.pop(0)
            if (
                len(win) >= SLOW_BURST_N
                and ev.wall_ns - burst_flagged.get(e.node, -(1 << 62))
                > SLOW_BURST_WINDOW_NS
            ):
                burst_flagged[e.node] = ev.wall_ns
                add(e, "slow_burst",
                    f"{len(win)} slow commands within 10s "
                    f"(latest {f.get('verb')} {f.get('dur_us')}us)")
    # Lag spikes from the sampled time series: any replication.lag_events.*
    # value crossing the spike threshold at a sample tick.
    for doc in docs:
        spiked: set[str] = set()
        for s in doc.samples:
            for name, v in s.values.items():
                if not name.startswith("replication.lag_events."):
                    continue
                try:
                    lag = int(v)
                except (TypeError, ValueError):
                    continue
                if lag >= LAG_SPIKE_EVENTS and name not in spiked:
                    spiked.add(name)
                    out.append(
                        Anomaly(
                            wall_ns=s.wall_ns,
                            node=doc.node,
                            kind="lag_spike",
                            detail=f"{name.rsplit('.', 1)[-1]}: "
                                   f"{lag} events behind",
                        )
                    )
    out.sort(key=lambda a: a.wall_ns)
    return out


# Anomaly kinds that count toward partition-incident scoping: the ones a
# sick replica produces about ITSELF (a peer_flip is the observer's view
# of someone else's failure and would smear the blame across partitions).
_PARTITION_SCOPED_KINDS = (
    "degradation",
    "partition_degraded",
    "storage_full",
    "fatal_signal",
    "rejection_burst",
)


def partition_incident_scope(report: Report) -> Optional[str]:
    """One-line verdict: is this incident partition-local or cluster-wide?

    Nodes advertise their partition on node_start/map_change flight
    events; anomalies a replica raised about itself fold by that
    partition. One affected partition = a partition-local incident (the
    containment story working); most/all partitions at once = a
    cluster-wide cause (deploy, fabric, shared disk). None when no spill
    names a partition (unpartitioned deployment)."""
    node_part: dict[str, int] = {}
    for doc in report.docs:
        for ev in doc.events:
            if ev.kind in ("node_start", "map_change"):
                p = ev.fields.get("partition")
                if p is not None and str(p).lstrip("-").isdigit():
                    node_part[doc.node] = int(p)
    if not node_part:
        return None
    known = sorted(set(node_part.values()))
    hit = sorted(
        {
            node_part[a.node]
            for a in report.anomalies
            if a.kind in _PARTITION_SCOPED_KINDS and a.node in node_part
        }
    )
    if not hit:
        return (
            f"partitions {known}: no replica-local anomalies "
            "(healthy or observer-only flips)"
        )
    if len(hit) == 1:
        return (
            f"PARTITION-LOCAL incident: partition {hit[0]} only "
            f"(of {known}) — containment held"
        )
    if len(hit) >= max(2, len(known)):
        return (
            f"CLUSTER-WIDE incident: every observed partition affected "
            f"({hit}) — look for a shared cause"
        )
    return f"multi-partition incident: partitions {hit} of {known}"


def _fmt_wall(wall_ns: int) -> str:
    if wall_ns <= 0:
        return "????-??-?? ??:??:??.???"
    t = wall_ns / 1e9
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t)) + (
        ".%03d" % (int(wall_ns // 1_000_000) % 1000)
    )


def render_text(report: Report, limit: int = 0) -> str:
    lines: list[str] = []
    for doc in report.docs:
        w = doc.meta.get("written_wall_ns", 0)
        lines.append(
            f"spill {doc.path}: node={doc.node} events={len(doc.events)} "
            f"samples={len(doc.samples)} written={_fmt_wall(int(w or 0))}"
            + (f" TRUNCATED ({doc.error})" if doc.truncated else "")
        )
    for err in report.errors:
        lines.append(f"unreadable: {err}")
    lines.append("")
    lines.append(f"== merged timeline ({len(report.timeline)} events) ==")
    shown = report.timeline[-limit:] if limit > 0 else report.timeline
    if limit > 0 and len(report.timeline) > limit:
        lines.append(f"... ({len(report.timeline) - limit} earlier events "
                     f"omitted; --limit 0 for all)")
    for e in shown:
        ev = e.event
        fields = " ".join(
            f"{k}={v}" for k, v in ev.fields.items() if k != "trace"
        )
        trace = ev.fields.get("trace")
        lines.append(
            f"{_fmt_wall(ev.wall_ns)} [{e.node}] {ev.kind}"
            + (f" {fields}" if fields else "")
            + (f" trace={trace}" if trace else "")
        )
    lines.append("")
    if report.trace_links:
        lines.append(f"== cross-node trace links ({len(report.trace_links)}) ==")
        for tid, nodes in sorted(report.trace_links.items()):
            lines.append(f"trace {tid}: {' <-> '.join(nodes)}")
        lines.append("")
    lines.append(f"== anomalies ({len(report.anomalies)}) ==")
    for a in report.anomalies:
        lines.append(
            f"{_fmt_wall(a.wall_ns)} [{a.node}] {a.kind}: {a.detail}"
        )
    if not report.anomalies:
        lines.append("(none)")
    scope = partition_incident_scope(report)
    if scope is not None:
        lines.append("")
        lines.append("== partition scope ==")
        lines.append(scope)
    return "\n".join(lines)


def render_json(report: Report) -> str:
    return json.dumps(
        {
            "spills": [
                {
                    "path": d.path,
                    "node": d.node,
                    "events": len(d.events),
                    "samples": len(d.samples),
                    "truncated": d.truncated,
                    "error": d.error,
                }
                for d in report.docs
            ],
            "errors": report.errors,
            "timeline": [
                {
                    "wall_ns": e.event.wall_ns,
                    "node": e.node,
                    "seq": e.event.seq,
                    "kind": e.event.kind,
                    "fields": e.event.fields,
                }
                for e in report.timeline
            ],
            "trace_links": report.trace_links,
            "partition_scope": partition_incident_scope(report),
            "anomalies": [
                {
                    "wall_ns": a.wall_ns,
                    "node": a.node,
                    "kind": a.kind,
                    "detail": a.detail,
                }
                for a in report.anomalies
            ],
        },
        indent=None,
        separators=(",", ":"),
    )


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="merklekv_tpu blackbox",
        description="merge flight-recorder spills from one or more nodes "
        "into a causally-ordered cluster timeline and flag anomalies",
    )
    p.add_argument(
        "paths", nargs="+",
        help="spill files, or node flight directories (flight.bin + crash "
        "markers)",
    )
    p.add_argument("--json", action="store_true", help="machine-readable")
    p.add_argument(
        "--limit", type=int, default=200,
        help="newest timeline events to print (0 = all; text mode only)",
    )
    args = p.parse_args(argv)
    report = load_docs(args.paths)
    if args.json:
        print(render_json(report))
    else:
        print(render_text(report, limit=args.limit))
    return 1 if report.errors else 0


if __name__ == "__main__":
    sys.exit(main())

"""Correlated sync traces: cycle ids + a per-cycle ring buffer.

Every anti-entropy cycle (pairwise or multi-peer) allocates a process-wide
monotonic **cycle id** and installs it in a contextvar for its duration —
``utils.tracing.span()`` stamps the id into every span record emitted on
that thread, so a cycle's walk / repair / journaling spans correlate in the
log stream without threading an argument through every call.

The cycle's outcome is summarized into a ``CycleTrace`` (one ``PeerTrace``
per peer: wire bytes, walk rounds, repairs, outcome) and appended to a
bounded ring buffer; the ``TRACE <n>`` wire verb dumps the newest ``n``
cycles — the per-peer sync attribution PR 3 proved out with ad-hoc byte
counters, now always on and queryable.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

__all__ = [
    "PeerTrace",
    "CycleTrace",
    "SyncTraceBuffer",
    "get_trace_buffer",
    "next_cycle_id",
    "current_cycle_id",
    "cycle_scope",
]

_cycle_counter = itertools.count(1)
_current_cycle: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "mkv_sync_cycle", default=None
)


def next_cycle_id() -> int:
    return next(_cycle_counter)


def current_cycle_id() -> Optional[int]:
    return _current_cycle.get()


class cycle_scope:
    """Context manager installing ``cycle_id`` as the thread's current
    cycle (spans emitted inside stamp it)."""

    def __init__(self, cycle_id: int) -> None:
        self._id = cycle_id
        self._token = None

    def __enter__(self) -> int:
        self._token = _current_cycle.set(self._id)
        return self._id

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _current_cycle.reset(self._token)


@dataclass
class PeerTrace:
    peer: str  # "host:port"
    mode: str = ""  # transfer strategy ("noop"/"bisect"/"hash-paged"/...)
    outcome: str = "ok"  # "ok" | "noop" | "degraded" | "error" | "skipped"
    bytes_sent: int = 0
    bytes_received: int = 0
    rounds: int = 0
    divergent: int = 0
    repairs: int = 0  # keys set + deleted against this peer's state
    error: str = ""


@dataclass
class CycleTrace:
    cycle_id: int
    kind: str  # "pairwise" | "multi"
    started_unix: float = field(default_factory=time.time)
    seconds: float = 0.0
    peers: list[PeerTrace] = field(default_factory=list)
    # Causal trace id of the cycle (0 = untraced). Carried explicitly
    # because the summary is appended AFTER the cycle's trace scope has
    # exited — the flight recorder stamps it so a donor's traced serves
    # and the initiator's sync_cycle event join across nodes' spills.
    trace_id: int = 0


class SyncTraceBuffer:
    """Bounded FIFO of the newest CycleTraces (thread-safe)."""

    def __init__(self, capacity: int = 128) -> None:
        self._mu = threading.Lock()
        self._capacity = capacity
        self._cycles: list[CycleTrace] = []

    def set_capacity(self, capacity: int) -> None:
        with self._mu:
            self._capacity = max(1, capacity)
            if len(self._cycles) > self._capacity:
                del self._cycles[: len(self._cycles) - self._capacity]

    def append(self, cycle: CycleTrace) -> None:
        with self._mu:
            self._cycles.append(cycle)
            if len(self._cycles) > self._capacity:
                del self._cycles[: len(self._cycles) - self._capacity]
        # Flight recorder: every anti-entropy cycle outcome lands on the
        # black-box timeline (the worst peer outcome is the headline; the
        # TRACE ring keeps the full per-peer detail).
        try:
            from merklekv_tpu.obs.flightrec import record

            rank = {"error": 4, "degraded": 3, "skipped": 2, "ok": 1,
                    "noop": 0}
            worst = max(
                (p.outcome for p in cycle.peers),
                key=lambda o: rank.get(o, 0),
                default="noop",
            )
            fields = dict(
                cycle=cycle.cycle_id,
                mode=cycle.kind,
                peers=len(cycle.peers),
                outcome=worst,
                repairs=sum(p.repairs for p in cycle.peers),
                seconds=round(cycle.seconds, 4),
            )
            if cycle.trace_id:
                fields["trace"] = f"{cycle.trace_id:016x}"
            record("sync_cycle", **fields)
        except Exception:
            pass  # the trace ring must never fail on recorder trouble

    def last(self, n: int) -> list[CycleTrace]:
        """Newest ``n`` cycles, newest first."""
        with self._mu:
            return list(reversed(self._cycles[-max(0, n):]))

    def __len__(self) -> int:
        with self._mu:
            return len(self._cycles)

    def clear(self) -> None:
        with self._mu:
            self._cycles.clear()

    def wire_dump(self, n: int) -> str:
        """The TRACE verb's response: ``TRACES <rows>`` then one
        space-separated ``k=v`` line per (cycle, peer), newest cycle first,
        closed by ``END`` (the PEERS/CLIENT LIST table shape, so clients
        reuse their field-table parser)."""
        now = time.time()
        rows: list[str] = []
        for cyc in self.last(n):
            for p in cyc.peers:
                rows.append(
                    f"cycle={cyc.cycle_id} kind={cyc.kind} peer={p.peer} "
                    f"mode={p.mode or '-'} outcome={p.outcome} "
                    f"bytes_sent={p.bytes_sent} "
                    f"bytes_received={p.bytes_received} rounds={p.rounds} "
                    f"divergent={p.divergent} repairs={p.repairs} "
                    f"seconds={cyc.seconds:.6f} "
                    f"age_s={max(0.0, now - cyc.started_unix):.1f}"
                    + (
                        f" error={p.error.replace(' ', '_')[:80]}"
                        if p.error
                        else ""
                    )
                )
        body = "".join(r + "\r\n" for r in rows)
        return f"TRACES {len(rows)}\r\n{body}END\r\n"


_buffer = SyncTraceBuffer()


def get_trace_buffer() -> SyncTraceBuffer:
    return _buffer

"""Metrics core: counters, fixed-log-bucket histograms, callback gauges.

This is the process-wide registry behind ``utils/tracing.get_metrics()``
(which re-exports it for backward compatibility) and the Prometheus
exporter (obs/exporter.py). Design constraints, in order:

- **hot-path cheap**: a counter bump or histogram observation is one lock
  acquire + O(1) integer work — no allocation, no string formatting. The
  native server keeps its own lock-free atomic histogram (stats.h) for the
  command path; this registry covers the Python control plane.
- **percentiles without reservoirs**: histograms use fixed log2 buckets
  (1 µs .. ~33 s), so p50/p90/p99/max are derivable from bucket counts at
  read time and two scrapes can be subtracted to get windowed quantiles.
- **gauges are callbacks**: the registry never caches keyspace size / WAL
  bytes / mirror staleness — each scrape reads the live value, and a
  subsystem that goes away unregisters (or its callback failure drops the
  gauge from that scrape, never the scrape itself).
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Optional, Union

__all__ = [
    "BUCKET_BOUNDS",
    "bucket_index",
    "Histogram",
    "Metrics",
    "get_metrics",
]

# Histogram bucket upper bounds in SECONDS: 1 µs * 2^i. 26 bounds cover
# 1 µs .. ~33.5 s; anything slower lands in the +Inf overflow bucket.
# Powers of two keep bucket_index a cheap log2 and make the native
# command-latency histogram (stats.h, µs buckets) line up bound-for-bound.
BUCKET_BOUNDS: tuple[float, ...] = tuple(1e-6 * (1 << i) for i in range(26))

# Size/count histograms (batch sizes, row counts) reuse the same log2
# machinery by storing observations scaled by SIZE_SCALE: bound i then
# reads as 2^i UNITS (1, 2, 4, ... ~33.5M). Consumers (the exporter, the
# bench JSON) multiply bounds/sums back by 1/SIZE_SCALE.
SIZE_SCALE = 1e-6


def bucket_index(seconds: float) -> int:
    """Index of the first bound >= ``seconds`` (len(BUCKET_BOUNDS) for the
    +Inf overflow bucket). Negative/zero observations land in bucket 0."""
    if seconds <= BUCKET_BOUNDS[0]:
        return 0
    if seconds > BUCKET_BOUNDS[-1]:
        return len(BUCKET_BOUNDS)
    # ceil(log2(v / 1µs)); float error at exact bounds is corrected below.
    i = max(0, math.ceil(math.log2(seconds * 1e6)))
    while i > 0 and seconds <= BUCKET_BOUNDS[i - 1]:
        i -= 1
    while i < len(BUCKET_BOUNDS) and seconds > BUCKET_BOUNDS[i]:
        i += 1
    return i


class Histogram:
    """Fixed-log-bucket latency histogram (thread-safe).

    Buckets are non-cumulative internally; ``snapshot()`` returns raw
    counts, ``cumulative()`` the Prometheus ``le`` view, ``quantile(q)``
    the upper bound of the bucket holding the q-th observation — an upper
    estimate, within one power of two of the true value by construction.
    """

    __slots__ = ("_mu", "_counts", "_sum", "_count", "_max")

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self._sum = 0.0
        self._count = 0
        self._max = 0.0

    def observe(self, seconds: float) -> None:
        i = bucket_index(seconds)
        with self._mu:
            self._counts[i] += 1
            self._sum += seconds
            self._count += 1
            if seconds > self._max:
                self._max = seconds

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
                "max": self._max,
            }

    def cumulative(self) -> list[tuple[float, int]]:
        """(le_bound_seconds, cumulative_count) pairs; the final pair is
        (inf, total)."""
        snap = self.snapshot()
        out, running = [], 0
        for bound, c in zip(BUCKET_BOUNDS, snap["counts"]):
            running += c
            out.append((bound, running))
        out.append((math.inf, running + snap["counts"][-1]))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Upper bound of the bucket containing the q-th observation, or
        None when empty. q in [0, 1]."""
        snap = self.snapshot()
        if snap["count"] == 0:
            return None
        rank = max(1, math.ceil(q * snap["count"]))
        running = 0
        for bound, c in zip(BUCKET_BOUNDS, snap["counts"]):
            running += c
            if running >= rank:
                return bound
        return snap["max"]  # overflow bucket: report the observed max


# A gauge callback returns a number, or a {label_value: number} dict for a
# labeled gauge family (e.g. per-peer health).
GaugeFn = Callable[[], Union[int, float, dict]]


class Metrics:
    """Process-wide registry: counters + span aggregates + histograms +
    gauges. The counter/span surface is unchanged from the pre-obs
    ``utils.tracing.Metrics`` (tests and the METRICS wire verb depend on
    ``snapshot()['counters']`` / ``['spans']``); histograms and gauges are
    additive."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._counters: dict[str, int] = {}
        self._span_count: dict[str, int] = {}
        self._span_total_s: dict[str, float] = {}
        self._histograms: dict[str, Histogram] = {}
        self._size_names: set[str] = set()
        self._gauges: dict[str, tuple[GaugeFn, str, str]] = {}

    # -- counters -----------------------------------------------------------
    def inc(self, name: str, delta: int = 1) -> None:
        with self._mu:
            self._counters[name] = self._counters.get(name, 0) + delta

    # -- histograms ---------------------------------------------------------
    def histogram(self, name: str) -> Histogram:
        """Get-or-create; the Histogram has its own lock, so observation
        after the first lookup never touches the registry lock."""
        with self._mu:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            return h

    def observe(self, name: str, seconds: float) -> None:
        self.histogram(name).observe(seconds)

    def observe_size(self, name: str, units: float) -> None:
        """Size/count observation (e.g. replication batch size): same log2
        buckets, bound i = 2^i units. The name is tagged so the exporter
        renders the family unitless (``mkv_<name>``) with unit-valued
        bounds instead of a ``_seconds`` family."""
        with self._mu:
            self._size_names.add(name)
        self.histogram(name).observe(units * SIZE_SCALE)

    def is_size_histogram(self, name: str) -> bool:
        with self._mu:
            return name in self._size_names

    def observe_span(self, name: str, seconds: float) -> None:
        """Span aggregate (count + total) AND the span's latency histogram —
        every span() site gets percentile-capable buckets for free."""
        with self._mu:
            self._span_count[name] = self._span_count.get(name, 0) + 1
            self._span_total_s[name] = (
                self._span_total_s.get(name, 0.0) + seconds
            )
        self.histogram(f"span.{name}").observe(seconds)

    # -- gauges -------------------------------------------------------------
    def register_gauge(
        self, name: str, fn: GaugeFn, help: str = "", label: str = ""
    ) -> None:
        """Register (or replace) a callback gauge. ``label`` names the
        label key when ``fn`` returns a dict (one sample per entry)."""
        with self._mu:
            self._gauges[name] = (fn, help, label)

    def unregister_gauge(self, name: str, fn: Optional[GaugeFn] = None) -> None:
        """Remove a gauge. With ``fn`` given, remove only if the current
        registration IS that callback — so a stopped node cannot strip a
        successor node's same-named gauge (registration is last-wins)."""
        with self._mu:
            cur = self._gauges.get(name)
            if cur is None:
                return
            if fn is None or cur[0] is fn:
                self._gauges.pop(name, None)

    def gauges_snapshot(self) -> dict:
        """{name: {"value": num | {label: num}, "help": str, "label": str}}
        — each callback invoked now; a failing callback drops ITS gauge
        from this snapshot, never the snapshot itself."""
        with self._mu:
            gauges = dict(self._gauges)
        out = {}
        for name, (fn, help_, label) in gauges.items():
            try:
                out[name] = {"value": fn(), "help": help_, "label": label}
            except Exception:
                continue
        return out

    # -- snapshots ----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._mu:
            snap = {
                "counters": dict(self._counters),
                "spans": {
                    name: {
                        "count": self._span_count[name],
                        "total_s": round(self._span_total_s[name], 6),
                        "avg_s": round(
                            self._span_total_s[name] / self._span_count[name],
                            6,
                        ),
                    }
                    for name in self._span_count
                },
            }
            hists = dict(self._histograms)
            snap["size_histograms"] = sorted(self._size_names)
        snap["histograms"] = {
            name: h.snapshot() for name, h in hists.items()
        }
        return snap

    def reset(self) -> None:
        """Clear counters/spans/histograms. Gauges survive: they are live
        callbacks owned by running subsystems, not accumulated state."""
        with self._mu:
            self._counters.clear()
            self._span_count.clear()
            self._span_total_s.clear()
            self._histograms.clear()
            self._size_names.clear()


_metrics = Metrics()


def get_metrics() -> Metrics:
    return _metrics

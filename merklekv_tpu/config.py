"""Configuration: TOML file + CLI/env overrides.

Schema mirrors the reference's Config (/root/reference/src/config.rs:48-109):
top-level host/port/storage_path/engine/sync_interval_seconds, a
[replication] table, and an [anti_entropy] table. Secrets come env-first
(CLIENT_ID / CLIENT_PASSWORD, reference replication.rs:101-112). Parsing
uses stdlib tomllib — no third-party config crate needed.
"""

from __future__ import annotations

import os
import tomllib
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ReplicationConfig:
    enabled: bool = False
    # Broker endpoint for WAN replication; "local" selects the in-process
    # event bus (tests / single-host clusters).
    mqtt_broker: str = "localhost"
    mqtt_port: int = 1883
    # "framed": the self-hosted length-framed TcpBroker (default fabric).
    # "mqtt": real MQTT 3.1.1 frames — joins an existing mosquitto-style
    # deployment, like the reference (replication.rs:115-143).
    transport: str = "framed"
    topic_prefix: str = "merkle_kv"
    client_id: str = ""
    username: str = ""
    password: str = ""
    peer_list: list[str] = field(default_factory=list)

    def resolve_env(self) -> None:
        self.client_id = os.environ.get("CLIENT_ID", self.client_id)
        self.password = os.environ.get("CLIENT_PASSWORD", self.password)


@dataclass
class AntiEntropyConfig:
    enabled: bool = False
    interval_seconds: float = 60.0
    peers: list[str] = field(default_factory=list)  # "host:port"
    # "cpu" forces the host diff path; "auto" uses the TPU engine when the
    # keyspace is large enough to amortize a device round-trip.
    engine: str = "auto"
    # true: each cycle gathers ALL peers' leaf hashes and arbitrates per key
    # in one fused [R, N] diff program; false: pairwise local := peer syncs.
    multi_peer: bool = False


@dataclass
class DeviceConfig:
    # Shard the serving Merkle tree's leaf level over ALL local JAX devices
    # (GSPMD over a "key" mesh). Single-device trees are the default; on a
    # multi-chip host this spreads HBM and the rebuild across chips.
    sharded_mirror: bool = False


@dataclass
class Config:
    host: str = "127.0.0.1"
    port: int = 7379
    storage_path: str = "merklekv_data"
    engine: str = "mem"
    sync_interval_seconds: float = 60.0
    replication: ReplicationConfig = field(default_factory=ReplicationConfig)
    anti_entropy: AntiEntropyConfig = field(default_factory=AntiEntropyConfig)
    device: DeviceConfig = field(default_factory=DeviceConfig)

    @classmethod
    def load(cls, path: str) -> "Config":
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict) -> "Config":
        cfg = cls()
        for k in ("host", "storage_path", "engine"):
            if k in raw:
                setattr(cfg, k, str(raw[k]))
        if "port" in raw:
            cfg.port = int(raw["port"])
        ae = raw.get("anti_entropy", {})
        if "sync_interval_seconds" in raw:
            # Reference semantics (config.rs:48-74): the top-level interval
            # is the sync cadence. Here it seeds the anti-entropy loop's
            # interval; an explicit [anti_entropy].interval_seconds wins.
            cfg.sync_interval_seconds = float(raw["sync_interval_seconds"])
            if "interval_seconds" not in ae:
                cfg.anti_entropy.interval_seconds = cfg.sync_interval_seconds
        rep = raw.get("replication", {})
        for k in ("mqtt_broker", "transport", "topic_prefix", "client_id",
                  "username", "password"):
            if k in rep:
                setattr(cfg.replication, k, str(rep[k]))
        if "enabled" in rep:
            cfg.replication.enabled = bool(rep["enabled"])
        if "mqtt_port" in rep:
            cfg.replication.mqtt_port = int(rep["mqtt_port"])
        if "peer_list" in rep:
            cfg.replication.peer_list = [str(p) for p in rep["peer_list"]]
        if "enabled" in ae:
            cfg.anti_entropy.enabled = bool(ae["enabled"])
        if "interval_seconds" in ae:
            cfg.anti_entropy.interval_seconds = float(ae["interval_seconds"])
        if "peers" in ae:
            cfg.anti_entropy.peers = [str(p) for p in ae["peers"]]
        if "engine" in ae:
            cfg.anti_entropy.engine = str(ae["engine"])
        if "multi_peer" in ae:
            cfg.anti_entropy.multi_peer = bool(ae["multi_peer"])
        dev = raw.get("device", {})
        if "sharded_mirror" in dev:
            cfg.device.sharded_mirror = bool(dev["sharded_mirror"])
        cfg.replication.resolve_env()
        return cfg


def load_or_default(path: Optional[str]) -> Config:
    if path:
        return Config.load(path)
    cfg = Config()
    cfg.replication.resolve_env()
    return cfg

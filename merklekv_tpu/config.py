"""Configuration: TOML file + CLI/env overrides.

Schema mirrors the reference's Config (/root/reference/src/config.rs:48-109):
top-level host/port/storage_path/engine/sync_interval_seconds, a
[replication] table, and an [anti_entropy] table. Secrets come env-first
(CLIENT_ID / CLIENT_PASSWORD, reference replication.rs:101-112). Parsing
uses stdlib tomllib — no third-party config crate needed.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

try:
    import tomllib  # Python >= 3.11
except ModuleNotFoundError:  # pragma: no cover - depends on interpreter
    tomllib = None


def _parse_toml_value(text: str):
    text = text.strip()
    if text.startswith("[") and text.endswith("]"):
        inner = text[1:-1].strip()
        if not inner:
            return []
        # Split on top-level commas (strings in our configs never contain
        # commas, but guard quoted segments anyway).
        items, depth, quote, cur = [], 0, "", ""
        for ch in inner:
            if quote:
                cur += ch
                if ch == quote:
                    quote = ""
                continue
            if ch in "\"'":
                quote = ch
                cur += ch
            elif ch == "[":
                depth += 1
                cur += ch
            elif ch == "]":
                depth -= 1
                cur += ch
            elif ch == "," and depth == 0:
                items.append(cur)
                cur = ""
            else:
                cur += ch
        if cur.strip():
            items.append(cur)
        return [_parse_toml_value(i) for i in items]
    if (text.startswith('"') and text.endswith('"')) or (
        text.startswith("'") and text.endswith("'")
    ):
        return text[1:-1]
    if text == "true":
        return True
    if text == "false":
        return False
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    return text  # bare string (lenient; our schema coerces with str())


def _minitoml_loads(text: str) -> dict:
    """Fallback parser for the TOML subset this schema uses (scalar keys,
    [section] tables, single-line arrays, # comments) — Python 3.10 has no
    stdlib tomllib and this environment must not grow dependencies."""
    root: dict = {}
    table = root
    for raw_line in text.splitlines():
        line = raw_line.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            # "[section]  # comment" is valid TOML; section names in this
            # schema never contain '#', so a plain split is safe here.
            head = line.split("#", 1)[0].strip()
            if not head.endswith("]"):
                raise ValueError(f"malformed TOML line: {raw_line!r}")
            table = root
            for part in head[1:-1].strip().split("."):
                table = table.setdefault(part.strip(), {})
            continue
        key, sep, value = line.partition("=")
        if not sep:
            raise ValueError(f"malformed TOML line: {raw_line!r}")
        # Strip trailing comments outside quotes.
        out, quote = "", ""
        for ch in value:
            if quote:
                out += ch
                if ch == quote:
                    quote = ""
            elif ch in "\"'":
                quote = ch
                out += ch
            elif ch == "#":
                break
            else:
                out += ch
        table[key.strip().strip('"').strip("'")] = _parse_toml_value(out)
    return root


def _toml_load(f) -> dict:
    if tomllib is not None:
        return tomllib.load(f)
    return _minitoml_loads(f.read().decode("utf-8"))


@dataclass
class ServerConfig:
    """[server]: overload protection / admission control (cluster/
    overload.py + the native server's accept/dispatch path).

    All watermarks default OFF (0) — a bare node behaves exactly like the
    seed. When set, the node walks the degradation ladder (live ->
    shedding -> read_only -> draining) instead of exhausting threads, RAM,
    or disk. See docs/FAULT_MODEL.md "Resource exhaustion" and
    docs/DEPLOYMENT.md for capacity planning.
    """

    # Epoll worker-pool width (the I/O plane): every accepted connection
    # is owned by exactly one of io_threads event-loop workers. 0 (the
    # default) sizes the pool to hardware concurrency; 1 keeps a single
    # loop. See docs/DEPLOYMENT.md "I/O plane sizing".
    io_threads: int = 0
    # SO_REUSEPORT accept sharding: "auto" (default) gives every io worker
    # its own listening socket where the kernel supports it — the kernel
    # deals connections across workers and the single accept thread stops
    # being the connection-storm bottleneck; "on" insists (falls back with
    # a note where unsupported); "off" keeps the single accept loop.
    # Admission control is enforced identically on both paths.
    reuseport: str = "auto"
    # Zero-copy serving (default on): GET/MGET hand the engine's
    # refcounted value block straight to writev — zero copies after
    # ingest. false restores the copy-out-of-the-engine compat path
    # (wire-identical; the bench A/B baseline).
    zero_copy: bool = True
    # Request-line byte cap (0 = the 1 MiB default). Size it ABOVE the
    # largest value a SET may carry plus ~key/verb headroom; see
    # docs/DEPLOYMENT.md "Large-value serving".
    max_line_bytes: int = 0
    # Accepted-connection cap: past it, excess accepts are answered
    # "ERROR BUSY connections retry" and closed without ever entering the
    # worker pool. 0 = unlimited.
    max_connections: int = 0
    # One connection's in-flight pipelined-command budget: a client that
    # buffers more unanswered complete lines than this is answered BUSY
    # and closed. 0 = unlimited (default — deep pipelining is a
    # legitimate throughput pattern; cap it per deployment).
    max_pipeline: int = 0
    # Engine resident-bytes watermarks (approximate keys+values bytes,
    # O(1) to read). soft: shed writes with a retryable BUSY (reads stay
    # open); hard: flip read-only. 0 disables each.
    memory_soft_bytes: int = 0
    memory_hard_bytes: int = 0
    # Hysteresis: a watermark only releases once the signal falls below
    # watermark * recovery_ratio — no BUSY/OK flapping at the boundary.
    recovery_ratio: float = 0.85
    # Overload-monitor poll cadence.
    watermark_interval_seconds: float = 0.25


@dataclass
class ReplicationConfig:
    enabled: bool = False
    # Broker endpoint for WAN replication; "local" selects the in-process
    # event bus (tests / single-host clusters).
    mqtt_broker: str = "localhost"
    mqtt_port: int = 1883
    # "framed": the self-hosted length-framed TcpBroker (default fabric).
    # "mqtt": real MQTT 3.1.1 frames — joins an existing mosquitto-style
    # deployment, like the reference (replication.rs:115-143).
    transport: str = "framed"
    topic_prefix: str = "merkle_kv"
    client_id: str = ""
    username: str = ""
    password: str = ""
    peer_list: list[str] = field(default_factory=list)
    # Outbound frame caps: a drained batch is coalesced per key and
    # published as envelope frames of at most batch_max_events events /
    # ~batch_max_bytes payload each. <= 1 disables batching — every event
    # goes out as a legacy single-event payload (the format peers that
    # predate the batch envelope decode; also the per-event baseline the
    # replicated_write_throughput bench A/Bs against).
    batch_max_events: int = 512
    batch_max_bytes: int = 1 << 20
    # LWW clock-skew guard: an applied replication event whose timestamp
    # is further than this beyond the local clock is CLAMPED to
    # now + max_skew_ms (counted, per-peer attributed) — a peer with a
    # misconfigured clock can delay convergence on a key by at most the
    # skew bound instead of fencing it forever. 0 disables clamping.
    max_skew_ms: int = 300_000

    def resolve_env(self) -> None:
        self.client_id = os.environ.get("CLIENT_ID", self.client_id)
        self.password = os.environ.get("CLIENT_PASSWORD", self.password)


@dataclass
class AntiEntropyConfig:
    enabled: bool = False
    interval_seconds: float = 60.0
    peers: list[str] = field(default_factory=list)  # "host:port"
    # "cpu" forces the host diff path; "auto" uses the TPU engine when the
    # keyspace is large enough to amortize a device round-trip.
    engine: str = "auto"
    # true: each cycle gathers ALL peers' leaf hashes and arbitrates per key
    # in one fused [R, N] diff program; false: pairwise local := peer syncs.
    multi_peer: bool = False
    # Pairwise transfer strategy when roots differ: "auto" runs the
    # subtree-bisection walk (TREELEVEL descent, wire bytes ∝
    # divergence·log n) once the local keyspace reaches bisect_threshold
    # keys and keeps the paged hash scan below it (fewer round trips on a
    # small keyspace, and the multi-peer fan-out path always gathers
    # hashes); "bisect" always walks; "page" always scans.
    mode: str = "auto"
    bisect_threshold: int = 8192


@dataclass
class StorageConfig:
    """[storage]: the durable subsystem (merklekv_tpu/storage/).

    Off by default — a bare node stays the in-memory engine the seed
    shipped. When enabled, the node journals every observed write to a
    CRC-framed WAL under ``<storage_path>/node-<port>/``, compacts into
    Merkle-root-stamped snapshots, and recovers (verified) on restart.
    See docs/PERSISTENCE.md.
    """

    enabled: bool = False
    # "always": fsync inside every append (max durability, ~1 fsync per
    # drained batch); "interval": fsync every fsync_interval_seconds;
    # "never": OS writeback only.
    fsync: str = "interval"
    fsync_interval_seconds: float = 0.05
    # Rotate WAL segments at this size.
    segment_bytes: int = 4 << 20
    # Background compaction (snapshot + truncate old segments) triggers
    # when this many WAL bytes accumulate since the last snapshot; 0
    # disables the trigger (explicit/shutdown snapshots only).
    compact_trigger_bytes: int = 32 << 20
    # Keep this many snapshots; older WAL segments only survive while a
    # retained snapshot still needs them for replay.
    snapshots_retained: int = 2
    # "repair": a snapshot failing root verification is rejected and
    # recovery falls back (older snapshot, else full WAL replay);
    # "strict": refuse to start instead.
    verify: str = "repair"
    # Root stamping/verification path: "auto" uses the device bulk rebuild
    # for keyspaces >= device_min_keys, "cpu" pins host hashing (no jax
    # import), "tpu" always tries the device.
    merkle_engine: str = "auto"
    device_min_keys: int = 4096
    # Write a final snapshot on clean shutdown (fast, verified restarts).
    snapshot_on_shutdown: bool = True
    # Disk-free watermarks, checked on the store's ticker (statvfs on the
    # data dir). Free bytes below soft: shed writes (retryable BUSY);
    # below hard: read-only. 0 disables each; a live ENOSPC/EIO from the
    # WAL always flips read-only regardless (reactive handling is not
    # configurable). soft must be >= hard — it is the EARLIER warning.
    disk_free_soft_bytes: int = 0
    disk_free_hard_bytes: int = 0


@dataclass
class BootstrapConfig:
    """[bootstrap]: elastic-membership node bootstrap (cluster/bootstrap.py).

    When enabled, a node that starts with an empty keyspace — or recovers
    through interior WAL corruption — fetches a peer's newest Merkle-stamped
    snapshot over SNAPMETA/SNAPCHUNK, verifies the stamped root locally
    BEFORE serving a single read, then closes the post-stamp gap with a
    bisect delta walk. Donors come from [anti_entropy].peers. Peers that
    cannot serve a snapshot degrade the joiner to the plain anti-entropy
    walk. See docs/PERSISTENCE.md "Snapshot shipping".
    """

    enabled: bool = False
    # Raw snapshot bytes requested per SNAPCHUNK (the resume granularity on
    # a hostile link). Clamped to [4096, 262144]; the donor additionally
    # clamps to its own response-buffer budget.
    chunk_bytes: int = 131072
    # Integrity/transport retries per chunk offset before failing over to
    # the next donor.
    chunk_retries: int = 4


@dataclass
class ObservabilityConfig:
    """[observability]: the metrics plane (merklekv_tpu/obs/).

    ``http_port`` > 0 starts a per-node HTTP exporter serving Prometheus
    text exposition at ``/metrics`` (+ ``/healthz``) — registry counters,
    histograms, gauges, and the native STATS block bridged into one
    namespace. 0 (default) disables the endpoint; the METRICS wire verb
    and the TRACE ring buffer work either way. See docs/OBSERVABILITY.md.
    """

    http_port: int = 0  # 0 = disabled; -1 = ephemeral (tests)
    http_host: str = "127.0.0.1"
    # Ring-buffer capacity of the TRACE verb's cycle store.
    trace_cycles: int = 128
    # Causal trace propagation (obs/tracewire.py): anti-entropy cycles
    # allocate a trace context and cluster verbs carry the tc= token so
    # donor spans stitch into the initiator's trace. Off reverts to the
    # process-local TRACE surface only.
    trace_propagation: bool = True
    # Span-collector ring capacity (spans, not cycles) behind TRACEDUMP.
    trace_spans: int = 8192
    # Convergence-lag SLO plane (obs/lag.py): /healthz readiness flips to
    # "lagging" when a frame applies more than lag_ms_threshold behind its
    # publish clock (or any lag residue exists), and to "diverged" when
    # residue persists past diverged_after_s without an anti-entropy
    # convergence clearing it.
    lag_ms_threshold: float = 1000.0
    diverged_after_s: float = 120.0
    # PROFILE verb capture directory ("" = <storage_path>/profiles or a
    # temp dir on storage-less nodes).
    profile_dir: str = ""
    # Flight recorder (post-mortem black box, obs/flightrec.py). Always on
    # in-memory (event ring + FLIGHT verb); the durable spill only writes
    # when a directory resolves — flight_dir "" means <node data dir>/flight
    # on durable nodes and NO spill on storage-less ones (an embedded test
    # node must not litter the filesystem).
    flight_enabled: bool = True
    flight_dir: str = ""
    # Event-ring capacity (state transitions + slow commands).
    flight_events: int = 2048
    # Metric-sampler cadence: counters + gauges + native STATS snapshot
    # every flight_sample_s into a ~15 min ring, so "what changed in the
    # 60 s before death" is always answerable from the spill.
    flight_sample_s: float = 1.0
    # Spill rewrite cadence (atomic tmp+rename; kill -9 always leaves the
    # previous complete spill).
    flight_spill_s: float = 10.0
    # Slow-command log threshold in MICROSECONDS: native dispatch records
    # verb/latency/connection for commands at or over it. 0 disables.
    slow_command_us: int = 10_000


@dataclass
class DeviceConfig:
    # Serving-tree shard plane (parallel/sharded_state.py): "off" keeps the
    # single-device tree; "auto" shards the keyspace-ordered leaf array
    # across the largest power-of-two subset of the LOCAL devices (per-shard
    # subtree rebuilds in parallel, shard roots combined via all_gather); an
    # explicit power-of-two N pins the mesh width (clamped, with a warning,
    # to the device complement). TREELEVEL/HASH answers are bit-identical
    # at every setting — see docs/DEPLOYMENT.md "Mesh sizing".
    sharding: str = "off"
    # Deprecated alias ([device] sharded_mirror = true == sharding = "auto"):
    # the pre-sharding-knob GSPMD toggle, honored one release for configs
    # that predate the explicit SPMD backend.
    sharded_mirror: bool = False
    # Freshness contract of the device-update pump (cluster/mirror.py):
    # the served tree trails the live engine by at most this wall window.
    # Writes never wait on the device plane; the pump drains staged events
    # into scatter batches on its own cadence, publishing immediately when
    # idle and coalescing into bigger dispatches under load. See
    # docs/DEPLOYMENT.md "Tree freshness sizing".
    max_staleness_ms: float = 200.0
    # Optional version-count bound: a staged backlog deeper than this many
    # engine mutations skips the pump's coalesce delay and drains at once
    # (0 = wall-window-only). Also the lag past which anti-entropy walkers
    # escalate a stale donor tree to a forced refresh.
    max_staleness_versions: int = 0
    # Fault containment (merklekv_tpu/device/): every device program call
    # runs deadline-guarded on a dedicated executor; a dispatch wedged past
    # this bound is ABANDONED (typed error, never a hung pump/query
    # thread). Must comfortably exceed the backend's worst first-use
    # COMPILE time — an undersized deadline reads a legitimate compile as
    # a hang and degrades the mesh for nothing (docs/DEPLOYMENT.md
    # "Device fault containment"). 0 disables the executor bound.
    dispatch_deadline_ms: float = 60_000.0
    # Consecutive environment-classified drain failures at one ladder rung
    # before stepping down (sharded(N) -> ... -> single-device -> CPU).
    degrade_after_failures: int = 2
    # Integrity scrub: every interval, cross-check a sampled leaf range of
    # the SERVED device tree against CPU golden hashes recomputed from the
    # engine — silent device corruption triggers invalidate+rebuild
    # instead of serving a wrong root into anti-entropy. 0 disables.
    scrub_interval_s: float = 30.0
    scrub_keys: int = 256


@dataclass
class ClusterConfig:
    """[cluster]: partitioned cluster mode (cluster/partmap.py, router).

    Off by default (``partitions = 0``) — a bare node serves the whole
    keyspace exactly like the seed. When set, this node owns exactly ONE
    partition of a ``partitions``-way hashed keyspace: the native dispatch
    answers data verbs for foreign keys with the retryable ``ERROR MOVED
    <pid> <epoch>``, the replication topic becomes partition-local
    (``<topic_prefix>/p<pid>``), anti-entropy peers default to the
    partition's sibling replicas from the map, and the node serves the
    full map over the ``PARTMAP`` verb. See docs/DEPLOYMENT.md
    "Partition sizing" and docs/PROTOCOL.md "Partitioned cluster mode".
    """

    # Total partitions in the cluster (0 = unpartitioned).
    partitions: int = 0
    # The ONE partition this node owns (required when partitions > 0).
    partition_id: int = -1
    # Full replica table, "0=host:port,host:port;1=host:port;...":
    # every partition exactly once. Required when partitions > 0 — the
    # node serves it via PARTMAP (smart clients/routers bootstrap from
    # it) and derives sibling anti-entropy peers from its own group.
    partition_map: str = ""
    # Map generation: bump when installing a rebalanced map. Rides in
    # every MOVED answer so stale clients know to refresh.
    map_epoch: int = 1


@dataclass
class Config:
    host: str = "127.0.0.1"
    port: int = 7379
    storage_path: str = "merklekv_data"
    engine: str = "mem"
    sync_interval_seconds: float = 60.0
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    server: ServerConfig = field(default_factory=ServerConfig)
    replication: ReplicationConfig = field(default_factory=ReplicationConfig)
    anti_entropy: AntiEntropyConfig = field(default_factory=AntiEntropyConfig)
    device: DeviceConfig = field(default_factory=DeviceConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    bootstrap: BootstrapConfig = field(default_factory=BootstrapConfig)
    observability: ObservabilityConfig = field(
        default_factory=ObservabilityConfig
    )

    @classmethod
    def load(cls, path: str) -> "Config":
        with open(path, "rb") as f:
            raw = _toml_load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict) -> "Config":
        cfg = cls()
        for k in ("host", "storage_path", "engine"):
            if k in raw:
                setattr(cfg, k, str(raw[k]))
        if "port" in raw:
            cfg.port = int(raw["port"])
        ae = raw.get("anti_entropy", {})
        if "sync_interval_seconds" in raw:
            # Reference semantics (config.rs:48-74): the top-level interval
            # is the sync cadence. Here it seeds the anti-entropy loop's
            # interval; an explicit [anti_entropy].interval_seconds wins.
            cfg.sync_interval_seconds = float(raw["sync_interval_seconds"])
            if "interval_seconds" not in ae:
                cfg.anti_entropy.interval_seconds = cfg.sync_interval_seconds
        srv = raw.get("server", {})
        for k in (
            "io_threads",
            "max_connections",
            "max_pipeline",
            "memory_soft_bytes",
            "memory_hard_bytes",
            "max_line_bytes",
        ):
            if k in srv:
                setattr(cfg.server, k, int(srv[k]))
        if cfg.server.io_threads < 0:
            raise ValueError(
                "[server] io_threads must be >= 0 (0 = hardware "
                f"concurrency), got {cfg.server.io_threads}"
            )
        if "reuseport" in srv:
            cfg.server.reuseport = str(srv["reuseport"])
        if cfg.server.reuseport not in ("auto", "on", "off"):
            raise ValueError(
                "[server] reuseport must be auto|on|off, got "
                f"{cfg.server.reuseport!r}"
            )
        if "zero_copy" in srv:
            cfg.server.zero_copy = bool(srv["zero_copy"])
        if cfg.server.max_line_bytes < 0:
            raise ValueError(
                "[server] max_line_bytes must be >= 0 (0 = the 1 MiB "
                f"default), got {cfg.server.max_line_bytes}"
            )
        if "recovery_ratio" in srv:
            cfg.server.recovery_ratio = float(srv["recovery_ratio"])
        if "watermark_interval_seconds" in srv:
            cfg.server.watermark_interval_seconds = float(
                srv["watermark_interval_seconds"]
            )
        if cfg.server.max_connections < 0:
            raise ValueError(
                "[server] max_connections must be >= 0 (0 = unlimited), "
                f"got {cfg.server.max_connections}"
            )
        if cfg.server.max_pipeline < 0:
            raise ValueError(
                "[server] max_pipeline must be >= 0 (0 = unlimited), "
                f"got {cfg.server.max_pipeline}"
            )
        if cfg.server.memory_soft_bytes < 0 or cfg.server.memory_hard_bytes < 0:
            raise ValueError("[server] memory watermarks must be >= 0")
        if (
            cfg.server.memory_soft_bytes
            and cfg.server.memory_hard_bytes
            and cfg.server.memory_soft_bytes > cfg.server.memory_hard_bytes
        ):
            raise ValueError(
                "[server] memory_soft_bytes must be <= memory_hard_bytes "
                f"(soft sheds first), got {cfg.server.memory_soft_bytes} > "
                f"{cfg.server.memory_hard_bytes}"
            )
        if not 0.0 < cfg.server.recovery_ratio < 1.0:
            raise ValueError(
                "[server] recovery_ratio must be in (0, 1), got "
                f"{cfg.server.recovery_ratio}"
            )
        if cfg.server.watermark_interval_seconds <= 0:
            raise ValueError(
                "[server] watermark_interval_seconds must be > 0, got "
                f"{cfg.server.watermark_interval_seconds}"
            )
        rep = raw.get("replication", {})
        for k in ("mqtt_broker", "transport", "topic_prefix", "client_id",
                  "username", "password"):
            if k in rep:
                setattr(cfg.replication, k, str(rep[k]))
        if "enabled" in rep:
            cfg.replication.enabled = bool(rep["enabled"])
        if "mqtt_port" in rep:
            cfg.replication.mqtt_port = int(rep["mqtt_port"])
        if "peer_list" in rep:
            cfg.replication.peer_list = [str(p) for p in rep["peer_list"]]
        if "batch_max_events" in rep:
            cfg.replication.batch_max_events = int(rep["batch_max_events"])
        if "batch_max_bytes" in rep:
            cfg.replication.batch_max_bytes = int(rep["batch_max_bytes"])
        if "max_skew_ms" in rep:
            cfg.replication.max_skew_ms = int(rep["max_skew_ms"])
        if cfg.replication.max_skew_ms < 0:
            raise ValueError(
                "[replication] max_skew_ms must be >= 0 (0 = no clamping), "
                f"got {cfg.replication.max_skew_ms}"
            )
        if cfg.replication.batch_max_bytes < 1024:
            raise ValueError(
                "[replication] batch_max_bytes must be >= 1024, got "
                f"{cfg.replication.batch_max_bytes}"
            )
        if "enabled" in ae:
            cfg.anti_entropy.enabled = bool(ae["enabled"])
        if "interval_seconds" in ae:
            cfg.anti_entropy.interval_seconds = float(ae["interval_seconds"])
        if "peers" in ae:
            cfg.anti_entropy.peers = [str(p) for p in ae["peers"]]
        if "engine" in ae:
            cfg.anti_entropy.engine = str(ae["engine"])
        if "multi_peer" in ae:
            cfg.anti_entropy.multi_peer = bool(ae["multi_peer"])
        if "mode" in ae:
            cfg.anti_entropy.mode = str(ae["mode"])
        if "bisect_threshold" in ae:
            cfg.anti_entropy.bisect_threshold = int(ae["bisect_threshold"])
        if cfg.anti_entropy.mode not in ("auto", "bisect", "page"):
            raise ValueError(
                f"[anti_entropy] mode must be auto|bisect|page, "
                f"got {cfg.anti_entropy.mode!r}"
            )
        dev = raw.get("device", {})
        if "sharded_mirror" in dev:
            cfg.device.sharded_mirror = bool(dev["sharded_mirror"])
        if "sharding" in dev:
            # auto|off|N (TOML may carry the N as an integer or a string).
            cfg.device.sharding = str(dev["sharding"]).strip().lower()
        elif cfg.device.sharded_mirror:
            cfg.device.sharding = "auto"  # deprecated-alias promotion
        if cfg.device.sharding not in ("auto", "off"):
            try:
                n_shards = int(cfg.device.sharding)
            except ValueError:
                n_shards = -1
            if n_shards < 1 or n_shards & (n_shards - 1):
                raise ValueError(
                    "[device] sharding must be auto|off|power-of-two, got "
                    f"{cfg.device.sharding!r}"
                )
        if "max_staleness_ms" in dev:
            cfg.device.max_staleness_ms = float(dev["max_staleness_ms"])
        if "max_staleness_versions" in dev:
            cfg.device.max_staleness_versions = int(
                dev["max_staleness_versions"]
            )
        if cfg.device.max_staleness_ms <= 0:
            raise ValueError(
                "[device] max_staleness_ms must be > 0, got "
                f"{cfg.device.max_staleness_ms}"
            )
        if cfg.device.max_staleness_versions < 0:
            raise ValueError(
                "[device] max_staleness_versions must be >= 0 (0 = wall "
                f"window only), got {cfg.device.max_staleness_versions}"
            )
        if "dispatch_deadline_ms" in dev:
            cfg.device.dispatch_deadline_ms = float(
                dev["dispatch_deadline_ms"]
            )
        if cfg.device.dispatch_deadline_ms < 0:
            raise ValueError(
                "[device] dispatch_deadline_ms must be >= 0 (0 = "
                f"unbounded), got {cfg.device.dispatch_deadline_ms}"
            )
        if "degrade_after_failures" in dev:
            cfg.device.degrade_after_failures = int(
                dev["degrade_after_failures"]
            )
        if cfg.device.degrade_after_failures < 1:
            raise ValueError(
                "[device] degrade_after_failures must be >= 1, got "
                f"{cfg.device.degrade_after_failures}"
            )
        if "scrub_interval_s" in dev:
            cfg.device.scrub_interval_s = float(dev["scrub_interval_s"])
        if cfg.device.scrub_interval_s < 0:
            raise ValueError(
                "[device] scrub_interval_s must be >= 0 (0 = off), got "
                f"{cfg.device.scrub_interval_s}"
            )
        if "scrub_keys" in dev:
            cfg.device.scrub_keys = int(dev["scrub_keys"])
        if cfg.device.scrub_keys < 1:
            raise ValueError(
                "[device] scrub_keys must be >= 1, got "
                f"{cfg.device.scrub_keys}"
            )
        obs = raw.get("observability", {})
        if "http_port" in obs:
            cfg.observability.http_port = int(obs["http_port"])
        if "http_host" in obs:
            cfg.observability.http_host = str(obs["http_host"])
        if "trace_cycles" in obs:
            cfg.observability.trace_cycles = int(obs["trace_cycles"])
        if "trace_propagation" in obs:
            cfg.observability.trace_propagation = bool(
                obs["trace_propagation"]
            )
        if "trace_spans" in obs:
            cfg.observability.trace_spans = int(obs["trace_spans"])
        if "lag_ms_threshold" in obs:
            cfg.observability.lag_ms_threshold = float(
                obs["lag_ms_threshold"]
            )
        if "diverged_after_s" in obs:
            cfg.observability.diverged_after_s = float(
                obs["diverged_after_s"]
            )
        if "profile_dir" in obs:
            cfg.observability.profile_dir = str(obs["profile_dir"])
        if "flight_enabled" in obs:
            cfg.observability.flight_enabled = bool(obs["flight_enabled"])
        if "flight_dir" in obs:
            cfg.observability.flight_dir = str(obs["flight_dir"])
        if "flight_events" in obs:
            cfg.observability.flight_events = int(obs["flight_events"])
        if "flight_sample_s" in obs:
            cfg.observability.flight_sample_s = float(obs["flight_sample_s"])
        if "flight_spill_s" in obs:
            cfg.observability.flight_spill_s = float(obs["flight_spill_s"])
        if "slow_command_us" in obs:
            cfg.observability.slow_command_us = int(obs["slow_command_us"])
        if cfg.observability.flight_events < 16:
            raise ValueError(
                "[observability] flight_events must be >= 16, got "
                f"{cfg.observability.flight_events}"
            )
        if cfg.observability.flight_sample_s <= 0:
            raise ValueError(
                "[observability] flight_sample_s must be > 0, got "
                f"{cfg.observability.flight_sample_s}"
            )
        if cfg.observability.flight_spill_s <= 0:
            raise ValueError(
                "[observability] flight_spill_s must be > 0, got "
                f"{cfg.observability.flight_spill_s}"
            )
        if cfg.observability.slow_command_us < 0:
            raise ValueError(
                "[observability] slow_command_us must be >= 0 (0 = off), "
                f"got {cfg.observability.slow_command_us}"
            )
        if cfg.observability.lag_ms_threshold <= 0:
            raise ValueError(
                "[observability] lag_ms_threshold must be > 0, got "
                f"{cfg.observability.lag_ms_threshold}"
            )
        if cfg.observability.diverged_after_s <= 0:
            raise ValueError(
                "[observability] diverged_after_s must be > 0, got "
                f"{cfg.observability.diverged_after_s}"
            )
        if cfg.observability.http_port < -1:
            raise ValueError(
                "[observability] http_port must be -1 (ephemeral), 0 "
                f"(disabled), or a TCP port, got {cfg.observability.http_port}"
            )
        st = raw.get("storage", {})
        for k in ("enabled", "snapshot_on_shutdown"):
            if k in st:
                setattr(cfg.storage, k, bool(st[k]))
        for k in ("fsync", "verify", "merkle_engine"):
            if k in st:
                setattr(cfg.storage, k, str(st[k]))
        for k in (
            "segment_bytes",
            "compact_trigger_bytes",
            "snapshots_retained",
            "device_min_keys",
            "disk_free_soft_bytes",
            "disk_free_hard_bytes",
        ):
            if k in st:
                setattr(cfg.storage, k, int(st[k]))
        if (
            cfg.storage.disk_free_soft_bytes < 0
            or cfg.storage.disk_free_hard_bytes < 0
        ):
            raise ValueError("[storage] disk-free watermarks must be >= 0")
        if (
            cfg.storage.disk_free_soft_bytes
            and cfg.storage.disk_free_hard_bytes
            and cfg.storage.disk_free_soft_bytes
            < cfg.storage.disk_free_hard_bytes
        ):
            raise ValueError(
                "[storage] disk_free_soft_bytes must be >= "
                "disk_free_hard_bytes (soft is the earlier warning), got "
                f"{cfg.storage.disk_free_soft_bytes} < "
                f"{cfg.storage.disk_free_hard_bytes}"
            )
        if "fsync_interval_seconds" in st:
            cfg.storage.fsync_interval_seconds = float(
                st["fsync_interval_seconds"]
            )
        if cfg.storage.fsync not in ("always", "interval", "never"):
            raise ValueError(
                f"[storage] fsync must be always|interval|never, "
                f"got {cfg.storage.fsync!r}"
            )
        if cfg.storage.verify not in ("repair", "strict"):
            raise ValueError(
                f"[storage] verify must be repair|strict, "
                f"got {cfg.storage.verify!r}"
            )
        if cfg.storage.merkle_engine not in ("auto", "cpu", "tpu"):
            raise ValueError(
                f"[storage] merkle_engine must be auto|cpu|tpu, "
                f"got {cfg.storage.merkle_engine!r}"
            )
        boot = raw.get("bootstrap", {})
        if "enabled" in boot:
            cfg.bootstrap.enabled = bool(boot["enabled"])
        if "chunk_bytes" in boot:
            cfg.bootstrap.chunk_bytes = int(boot["chunk_bytes"])
        if "chunk_retries" in boot:
            cfg.bootstrap.chunk_retries = int(boot["chunk_retries"])
        if not 4096 <= cfg.bootstrap.chunk_bytes <= 262144:
            raise ValueError(
                "[bootstrap] chunk_bytes must be in [4096, 262144], got "
                f"{cfg.bootstrap.chunk_bytes}"
            )
        if cfg.bootstrap.chunk_retries < 1:
            raise ValueError(
                "[bootstrap] chunk_retries must be >= 1, got "
                f"{cfg.bootstrap.chunk_retries}"
            )
        cl = raw.get("cluster", {})
        if "partitions" in cl:
            cfg.cluster.partitions = int(cl["partitions"])
        if "partition_id" in cl:
            cfg.cluster.partition_id = int(cl["partition_id"])
        if "partition_map" in cl:
            cfg.cluster.partition_map = str(cl["partition_map"])
        if "map_epoch" in cl:
            cfg.cluster.map_epoch = int(cl["map_epoch"])
        if cfg.cluster.partitions < 0:
            raise ValueError(
                "[cluster] partitions must be >= 0 (0 = unpartitioned), "
                f"got {cfg.cluster.partitions}"
            )
        if cfg.cluster.partitions > 0:
            if not 0 <= cfg.cluster.partition_id < cfg.cluster.partitions:
                raise ValueError(
                    "[cluster] partition_id must be in "
                    f"[0, {cfg.cluster.partitions}), got "
                    f"{cfg.cluster.partition_id}"
                )
            if cfg.cluster.map_epoch < 1:
                raise ValueError(
                    "[cluster] map_epoch must be >= 1, got "
                    f"{cfg.cluster.map_epoch}"
                )
            if not cfg.cluster.partition_map:
                raise ValueError(
                    "[cluster] partition_map is required when partitions "
                    "> 0 (the node serves it via PARTMAP and derives its "
                    "sibling peers from it)"
                )
            # Full validation (coverage, addresses) via the one parser
            # every routing consumer shares.
            from merklekv_tpu.cluster.partmap import parse_map_spec

            parse_map_spec(
                cfg.cluster.partition_map,
                cfg.cluster.partitions,
                cfg.cluster.map_epoch,
            )
        cfg.replication.resolve_env()
        return cfg


def load_or_default(path: Optional[str]) -> Config:
    if path:
        return Config.load(path)
    cfg = Config()
    cfg.replication.resolve_env()
    return cfg

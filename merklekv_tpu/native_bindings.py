"""ctypes bindings to the native host runtime (libmerklekv.so).

The C++ layer owns the hot path: sharded storage engines, the CRLF protocol
parser, and the TCP server (merklekv_tpu/native/). This module is the
control-plane handle the Python side uses to
  - share one engine between the native server and the replication /
    anti-entropy / TPU-Merkle subsystems,
  - drain the change-event queue feeding replication and incremental
    device updates,
  - register the cluster callback that routes SYNC / REPLICATE commands
    into Python.

Reference analog: the Rust server owns everything in-process
(/root/reference/src/main.rs:125-150); here the native runtime and the JAX
data plane meet through this seam.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from dataclasses import dataclass
from typing import Callable, Optional

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libmerklekv.so")
_SERVER_BIN = os.path.join(_NATIVE_DIR, "merklekv-server")

_CLUSTER_CB = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_void_p,
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_char),
    ctypes.c_int,
)

_lib: Optional[ctypes.CDLL] = None


def ensure_built() -> None:
    """Build the native library if missing or stale (any source newer)."""
    srcs = [
        os.path.join(_NATIVE_DIR, f)
        for f in os.listdir(_NATIVE_DIR)
        if f.endswith((".cc", ".h", "Makefile"))
    ]
    if os.path.exists(_LIB_PATH):
        lib_mtime = os.path.getmtime(_LIB_PATH)
        if all(os.path.getmtime(s) <= lib_mtime for s in srcs):
            return
    subprocess.run(
        ["make", "-C", _NATIVE_DIR, "-j", str(os.cpu_count() or 2)],
        check=True,
        capture_output=True,
    )


def server_binary() -> str:
    """Path to the standalone merklekv-server binary (built on demand)."""
    ensure_built()
    return _SERVER_BIN


def install_crash_marker(path: str) -> None:
    """Arm the native fatal-signal crash marker: a SIGSEGV/SIGABRT/SIGBUS/
    SIGFPE appends one ``fatal signal <n> pid <p> wall_ns <t>`` line to
    ``path`` (async-signal-safe), then chains to the previously installed
    handler — call AFTER ``faulthandler.enable`` so Python tracebacks
    still dump. Part of the flight-recorder fatal-dump plane
    (docs/OBSERVABILITY.md "Post-mortem forensics")."""
    _load().mkv_install_crash_marker(path.encode())


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    ensure_built()
    lib = ctypes.CDLL(_LIB_PATH)

    lib.mkv_free.argtypes = [ctypes.c_void_p]
    lib.mkv_engine_create.restype = ctypes.c_void_p
    lib.mkv_engine_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.mkv_engine_destroy.argtypes = [ctypes.c_void_p]

    P = ctypes.POINTER
    lib.mkv_engine_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        P(ctypes.c_void_p), P(ctypes.c_int),
    ]
    lib.mkv_engine_set.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.mkv_engine_set_with_ts.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int, ctypes.c_ulonglong,
    ]
    lib.mkv_engine_get_ts.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        P(ctypes.c_ulonglong),
    ]
    lib.mkv_engine_get_with_ts.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        P(ctypes.c_void_p), P(ctypes.c_int), P(ctypes.c_ulonglong),
    ]
    lib.mkv_engine_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.mkv_engine_del_with_ts.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_ulonglong,
    ]
    lib.mkv_engine_del_quiet.argtypes = lib.mkv_engine_del.argtypes
    lib.mkv_engine_set_if_newer.argtypes = lib.mkv_engine_set_with_ts.argtypes
    lib.mkv_engine_del_if_newer.argtypes = lib.mkv_engine_del_with_ts.argtypes
    lib.mkv_engine_apply_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
        P(ctypes.c_void_p),
    ]
    lib.mkv_engine_tombstone_ts.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, P(ctypes.c_ulonglong),
    ]
    lib.mkv_engine_tombstones.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        P(ctypes.c_void_p), P(ctypes.c_int),
    ]
    lib.mkv_engine_key_timestamps.argtypes = [
        ctypes.c_void_p, P(ctypes.c_void_p), P(ctypes.c_int),
    ]
    lib.mkv_engine_exists.argtypes = lib.mkv_engine_del.argtypes
    lib.mkv_engine_dbsize.restype = ctypes.c_longlong
    lib.mkv_engine_dbsize.argtypes = [ctypes.c_void_p]
    lib.mkv_engine_memory_usage.restype = ctypes.c_longlong
    lib.mkv_engine_memory_usage.argtypes = [ctypes.c_void_p]
    lib.mkv_engine_tomb_evictions.restype = ctypes.c_longlong
    lib.mkv_engine_tomb_evictions.argtypes = [ctypes.c_void_p]
    lib.mkv_engine_slab_stats.restype = None
    lib.mkv_engine_slab_stats.argtypes = [
        ctypes.c_void_p, P(ctypes.c_ulonglong),
    ]
    lib.mkv_engine_version.restype = ctypes.c_ulonglong
    lib.mkv_engine_version.argtypes = [ctypes.c_void_p]
    lib.mkv_engine_log_version_refused.argtypes = [ctypes.c_void_p]
    lib.mkv_engine_truncate.argtypes = [ctypes.c_void_p]
    lib.mkv_engine_compact.argtypes = [ctypes.c_void_p]
    lib.mkv_engine_sync.argtypes = [ctypes.c_void_p]
    lib.mkv_engine_increment.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_longlong,
        P(ctypes.c_longlong), P(ctypes.c_void_p), P(ctypes.c_int),
    ]
    lib.mkv_engine_decrement.argtypes = lib.mkv_engine_increment.argtypes
    lib.mkv_engine_append.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
        P(ctypes.c_void_p), P(ctypes.c_int), P(ctypes.c_void_p), P(ctypes.c_int),
    ]
    lib.mkv_engine_prepend.argtypes = lib.mkv_engine_append.argtypes
    lib.mkv_engine_scan.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        P(ctypes.c_void_p), P(ctypes.c_int),
    ]
    lib.mkv_engine_snapshot.argtypes = [
        ctypes.c_void_p, P(ctypes.c_void_p), P(ctypes.c_longlong),
    ]
    lib.mkv_engine_merkle_root.argtypes = [ctypes.c_void_p, ctypes.c_char_p]

    lib.mkv_server_create.restype = ctypes.c_void_p
    lib.mkv_server_create.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.mkv_server_configure_io.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
    ]
    lib.mkv_server_io_threads.restype = ctypes.c_longlong
    lib.mkv_server_io_threads.argtypes = [ctypes.c_void_p]
    lib.mkv_server_configure_accept.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mkv_server_reuseport.argtypes = [ctypes.c_void_p]
    lib.mkv_server_set_zero_copy.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mkv_server_set_max_line.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong,
    ]
    lib.mkv_server_start.argtypes = [ctypes.c_void_p]
    lib.mkv_server_port.argtypes = [ctypes.c_void_p]
    lib.mkv_server_stopping.argtypes = [ctypes.c_void_p]
    lib.mkv_server_stop.argtypes = [ctypes.c_void_p]
    lib.mkv_server_wait.argtypes = [ctypes.c_void_p]
    lib.mkv_server_destroy.argtypes = [ctypes.c_void_p]
    lib.mkv_server_set_cluster_cb.argtypes = [
        ctypes.c_void_p, _CLUSTER_CB, ctypes.c_void_p,
    ]
    lib.mkv_server_enable_events.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mkv_server_enable_latency.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mkv_server_set_serving.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mkv_server_serving.argtypes = [ctypes.c_void_p]
    lib.mkv_server_set_limits.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
    ]
    lib.mkv_server_set_degradation.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
    ]
    lib.mkv_server_degradation.argtypes = [ctypes.c_void_p]
    lib.mkv_server_events_depth.restype = ctypes.c_longlong
    lib.mkv_server_events_depth.argtypes = [ctypes.c_void_p]
    lib.mkv_server_set_slow_threshold.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong,
    ]
    lib.mkv_server_set_partition.argtypes = [
        ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_longlong,
        ctypes.c_longlong,
    ]
    lib.mkv_server_set_partition_map.argtypes = [
        ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_longlong, P(ctypes.c_uint),
        P(ctypes.c_uint), P(ctypes.c_ulonglong),
    ]
    lib.mkv_server_set_partition_fence.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_ulonglong,
    ]
    lib.mkv_server_clear_partition_fence.argtypes = [ctypes.c_void_p]
    lib.mkv_install_crash_marker.argtypes = [ctypes.c_char_p]
    lib.mkv_server_drain_events.argtypes = [
        ctypes.c_void_p, ctypes.c_int, P(ctypes.c_void_p), P(ctypes.c_longlong),
    ]
    lib.mkv_server_events_dropped.restype = ctypes.c_longlong
    lib.mkv_server_events_dropped.argtypes = [ctypes.c_void_p]
    lib.mkv_server_wait_events.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mkv_server_stats.argtypes = [
        ctypes.c_void_p, P(ctypes.c_void_p), P(ctypes.c_int),
    ]
    _lib = lib
    return lib


def _take_buffer(lib: ctypes.CDLL, ptr: ctypes.c_void_p, length: int) -> bytes:
    try:
        return ctypes.string_at(ptr, length)
    finally:
        lib.mkv_free(ptr)


class NativeError(RuntimeError):
    pass


class NativeEngine:
    """Handle to a native storage engine (sharded in-memory or durable log)."""

    def __init__(self, kind: str = "mem", path: str = "") -> None:
        self._lib = _load()
        self._h = self._lib.mkv_engine_create(kind.encode(), path.encode())
        if not self._h:
            raise NativeError(f"engine create failed: {kind}")

    def close(self) -> None:
        if self._h:
            self._lib.mkv_engine_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- kv ops -------------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        out = ctypes.c_void_p()
        out_len = ctypes.c_int()
        if not self._lib.mkv_engine_get(
            self._h, key, len(key), ctypes.byref(out), ctypes.byref(out_len)
        ):
            return None
        return _take_buffer(self._lib, out, out_len.value)

    def set(self, key: bytes, value: bytes) -> None:
        if not self._lib.mkv_engine_set(self._h, key, len(key), value, len(value)):
            raise NativeError("set failed")

    def set_with_ts(self, key: bytes, value: bytes, ts: int) -> None:
        """Install a value with an explicit last-write timestamp (unix ns) —
        LWW repair paths propagate ordering metadata with the value."""
        if not self._lib.mkv_engine_set_with_ts(
            self._h, key, len(key), value, len(value), ts
        ):
            raise NativeError("set_with_ts failed")

    def get_ts(self, key: bytes) -> Optional[int]:
        """Last-write unix-ns timestamp of a present key, else None."""
        ts = ctypes.c_ulonglong()
        if not self._lib.mkv_engine_get_ts(
            self._h, key, len(key), ctypes.byref(ts)
        ):
            return None
        return int(ts.value)

    def get_with_ts(self, key: bytes) -> Optional[tuple[bytes, int]]:
        """(value, last-write ts) read under ONE shard lock — the pairing a
        LWW consumer needs (a separate get + get_ts can interleave with a
        racing write)."""
        out = ctypes.c_void_p()
        out_len = ctypes.c_int()
        ts = ctypes.c_ulonglong()
        if not self._lib.mkv_engine_get_with_ts(
            self._h, key, len(key),
            ctypes.byref(out), ctypes.byref(out_len), ctypes.byref(ts),
        ):
            return None
        return _take_buffer(self._lib, out, out_len.value), int(ts.value)

    def delete(self, key: bytes) -> bool:
        """User-intent delete: records a tombstone stamped now, so the
        deletion participates in cluster LWW."""
        return bool(self._lib.mkv_engine_del(self._h, key, len(key)))

    def delete_with_ts(self, key: bytes, ts: int) -> bool:
        """Delete with an explicit tombstone timestamp (replication apply,
        tombstone adoption from a peer)."""
        return bool(self._lib.mkv_engine_del_with_ts(self._h, key, len(key), ts))

    def delete_quiet(self, key: bytes) -> bool:
        """Mirror delete — NO tombstone. Pairwise anti-entropy copies a
        peer's absence; recording that as a deletion-at-now would later kill
        disjoint writes cluster-wide through multi-peer LWW."""
        return bool(self._lib.mkv_engine_del_quiet(self._h, key, len(key)))

    def set_if_newer(self, key: bytes, value: bytes, ts: int) -> bool:
        """Install iff ts is not older than the entry AND any tombstone
        (value wins timestamp ties). Returns whether it applied."""
        return bool(
            self._lib.mkv_engine_set_if_newer(
                self._h, key, len(key), value, len(value), ts
            )
        )

    def delete_if_newer(self, key: bytes, ts: int) -> bool:
        """Delete iff ts is strictly newer than the live entry; records the
        tombstone. Returns whether it applied."""
        return bool(self._lib.mkv_engine_del_if_newer(self._h, key, len(key), ts))

    def apply_batch(
        self, ops: list[tuple[bytes, Optional[bytes], int]]
    ) -> list[bool]:
        """Run a whole replication frame of LWW-conditional ops in ONE FFI
        crossing: each op is ``(key, value, ts)`` with ``value=None``
        meaning delete_if_newer and anything else set_if_newer. Returns one
        applied flag per op (same order). The native side groups ops per
        shard so a k-op frame pays one lock per touched shard, not k."""
        if not ops:
            return []
        parts = [struct.pack("<I", len(ops))]
        for key, value, ts in ops:
            is_del = value is None
            v = b"" if is_del else value
            parts.append(struct.pack("<BQI", 1 if is_del else 0, ts, len(key)))
            parts.append(key)
            parts.append(struct.pack("<I", len(v)))
            parts.append(v)
        buf = b"".join(parts)
        out = ctypes.c_void_p()
        n = self._lib.mkv_engine_apply_batch(
            self._h, buf, len(buf), ctypes.byref(out)
        )
        if n < 0:
            raise NativeError("apply_batch: malformed op buffer")
        flags = _take_buffer(self._lib, out, n)
        return [bool(b) for b in flags]

    def tombstone_ts(self, key: bytes) -> Optional[int]:
        ts = ctypes.c_ulonglong()
        if not self._lib.mkv_engine_tombstone_ts(
            self._h, key, len(key), ctypes.byref(ts)
        ):
            return None
        return int(ts.value)

    def _read_kv_ts(self, fn, *args) -> list[tuple[bytes, int]]:
        """Call a C export returning the shared (u32 count, then u32 klen +
        key + u64 ts per item) wire shape and decode it."""
        out = ctypes.c_void_p()
        out_len = ctypes.c_int()
        fn(*args, ctypes.byref(out), ctypes.byref(out_len))
        buf = _take_buffer(self._lib, out, out_len.value)
        (n,) = struct.unpack_from("<I", buf, 0)
        items, off = [], 4
        for _ in range(n):
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = buf[off : off + klen]
            off += klen
            (ts,) = struct.unpack_from("<Q", buf, off)
            off += 8
            items.append((k, ts))
        return items

    def tombstones(self, prefix: bytes = b"") -> list[tuple[bytes, int]]:
        """Sorted (key, delete-ts) tombstones — the deletion half of the
        anti-entropy exchange."""
        return self._read_kv_ts(
            self._lib.mkv_engine_tombstones, self._h, prefix, len(prefix)
        )

    def key_timestamps(self) -> list[tuple[bytes, int]]:
        """(key, last-write-ts) for every live key in one native call,
        shard order — the bulk export multi-peer LWW arbitration consumes
        (it builds a map; sorting would be wasted work)."""
        return self._read_kv_ts(self._lib.mkv_engine_key_timestamps, self._h)

    def exists(self, key: bytes) -> bool:
        return bool(self._lib.mkv_engine_exists(self._h, key, len(key)))

    def dbsize(self) -> int:
        return self._lib.mkv_engine_dbsize(self._h)

    def memory_usage(self) -> int:
        return self._lib.mkv_engine_memory_usage(self._h)

    def slab_stats(self) -> dict[str, int]:
        """Value-slab accounting snapshot: ``bytes`` (live payload bytes,
        INCLUDING blocks pinned only by in-flight responses), ``blocks``,
        ``pinned_bytes`` (the in-flight-only subset), ``allocs`` (lifetime)
        and ``alloc_failures`` (writes refused by the MKV_MAX_SLAB_BYTES
        arena limit). Zeros for engines without block storage."""
        out = (ctypes.c_ulonglong * 5)()
        self._lib.mkv_engine_slab_stats(self._h, out)
        return {
            "bytes": int(out[0]),
            "blocks": int(out[1]),
            "pinned_bytes": int(out[2]),
            "allocs": int(out[3]),
            "alloc_failures": int(out[4]),
        }

    def version(self) -> int:
        """Engine mutation version (bumped per write). Only the sharded
        ("mem") and log engines track real versions; other kinds fall back
        to a bump-per-CALL counter, so cross-read comparisons (the mirror
        staleness gauge) are only meaningful on version-tracking engines."""
        return int(self._lib.mkv_engine_version(self._h))

    def tomb_evictions(self) -> int:
        """Deletion records dropped by the bounded tombstone map — each one
        is a delete the cluster can no longer defend against resurrection
        by a stale replica (surfaced via STATS as tombstone_evictions)."""
        return self._lib.mkv_engine_tomb_evictions(self._h)

    def log_version_refused(self) -> bool:
        """True when a durable log refused to open because its on-disk
        format version is newer than this binary supports (the file is left
        untouched; the engine runs empty with logging disabled)."""
        return bool(self._lib.mkv_engine_log_version_refused(self._h))

    def truncate(self) -> None:
        self._lib.mkv_engine_truncate(self._h)

    def sync(self) -> None:
        self._lib.mkv_engine_sync(self._h)

    def compact(self) -> bool:
        """Rewrite the durable log as a snapshot of live state plus
        tombstones (deletion LWW knowledge survives compaction). False for
        engines without a log."""
        return bool(self._lib.mkv_engine_compact(self._h))

    def increment(self, key: bytes, amount: int = 1) -> int:
        return self._numeric(self._lib.mkv_engine_increment, key, amount)

    def decrement(self, key: bytes, amount: int = 1) -> int:
        return self._numeric(self._lib.mkv_engine_decrement, key, amount)

    def _numeric(self, fn, key: bytes, amount: int) -> int:
        val = ctypes.c_longlong()
        err = ctypes.c_void_p()
        err_len = ctypes.c_int()
        if fn(
            self._h, key, len(key), amount,
            ctypes.byref(val), ctypes.byref(err), ctypes.byref(err_len),
        ):
            return val.value
        raise NativeError(_take_buffer(self._lib, err, err_len.value).decode())

    def append(self, key: bytes, value: bytes) -> bytes:
        return self._splice(self._lib.mkv_engine_append, key, value)

    def prepend(self, key: bytes, value: bytes) -> bytes:
        return self._splice(self._lib.mkv_engine_prepend, key, value)

    def _splice(self, fn, key: bytes, value: bytes) -> bytes:
        out = ctypes.c_void_p()
        out_len = ctypes.c_int()
        err = ctypes.c_void_p()
        err_len = ctypes.c_int()
        if fn(
            self._h, key, len(key), value, len(value),
            ctypes.byref(out), ctypes.byref(out_len),
            ctypes.byref(err), ctypes.byref(err_len),
        ):
            return _take_buffer(self._lib, out, out_len.value)
        raise NativeError(_take_buffer(self._lib, err, err_len.value).decode())

    def scan(self, prefix: bytes = b"") -> list[bytes]:
        out = ctypes.c_void_p()
        out_len = ctypes.c_int()
        self._lib.mkv_engine_scan(
            self._h, prefix, len(prefix), ctypes.byref(out), ctypes.byref(out_len)
        )
        buf = _take_buffer(self._lib, out, out_len.value)
        (n,) = struct.unpack_from("<I", buf, 0)
        keys, off = [], 4
        for _ in range(n):
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            keys.append(buf[off : off + klen])
            off += klen
        return keys

    def snapshot(self) -> list[tuple[bytes, bytes]]:
        """Whole keyspace sorted by key — the TPU Merkle rebuild input."""
        out = ctypes.c_void_p()
        out_len = ctypes.c_longlong()
        self._lib.mkv_engine_snapshot(
            self._h, ctypes.byref(out), ctypes.byref(out_len)
        )
        buf = _take_buffer(self._lib, out, out_len.value)
        (n,) = struct.unpack_from("<I", buf, 0)
        items, off = [], 4
        for _ in range(n):
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = buf[off : off + klen]
            off += klen
            (vlen,) = struct.unpack_from("<I", buf, off)
            off += 4
            v = buf[off : off + vlen]
            off += vlen
            items.append((k, v))
        return items

    def merkle_root(self) -> Optional[bytes]:
        out = ctypes.create_string_buffer(32)
        if self._lib.mkv_engine_merkle_root(self._h, out):
            return out.raw
        return None


@dataclass
class ChangeEventRaw:
    """One drained native change record (op kinds match events.h)."""

    op: int
    has_value: bool
    ts_ns: int
    seq: int
    key: bytes
    value: bytes


OP_SET, OP_DEL, OP_INCR, OP_DECR, OP_APPEND, OP_PREPEND = 1, 2, 3, 4, 5, 6
OP_TRUNCATE = 7  # staged for device-mirror invalidation, never replicated


class NativeServer:
    """Embedded native TCP server bound to a NativeEngine."""

    def __init__(
        self,
        engine: NativeEngine,
        host: str = "127.0.0.1",
        port: int = 7379,
        version: str = "0.1.0",
        exit_on_shutdown: bool = False,
        io_threads: int = 0,
        pipelined: bool = True,
        reuseport: str = "auto",
        zero_copy: bool = True,
        max_line: int = 0,
    ) -> None:
        # Validate BEFORE mkv_server_create: a raise past that point would
        # leak the native handle (there is no __del__ to reclaim it).
        if reuseport not in ("auto", "on", "off"):
            raise ValueError(
                f"reuseport must be auto|on|off, got {reuseport!r}"
            )
        self._lib = _load()
        self._engine = engine  # keep alive
        self._h = self._lib.mkv_server_create(
            engine._h, host.encode(), port, version.encode(),
            1 if exit_on_shutdown else 0,
        )
        self._cb_ref = None
        if not self._h:
            raise NativeError("server create failed")
        # I/O-plane shape, fixed before start: io_threads 0 = hardware
        # concurrency, 1 = a single epoll loop; pipelined=False restores
        # the one-write-per-response compat discipline (the bench A/B
        # baseline approximating the old thread-per-connection server).
        self._lib.mkv_server_configure_io(
            self._h, io_threads, 1 if pipelined else 0
        )
        # Accept sharding: "auto" uses SO_REUSEPORT where the kernel
        # supports it (each io worker owns its own listener), "on" insists
        # (falls back with a note where unsupported), "off" keeps the
        # single accept loop. Admission control is identical either way.
        self._lib.mkv_server_configure_accept(
            self._h, {"off": -1, "auto": 0, "on": 1}[reuseport]
        )
        # Zero-copy serving A/B (default on): off restores the copy-out-
        # of-the-engine GET/MGET path — wire-identical, bench baseline.
        if not zero_copy:
            self._lib.mkv_server_set_zero_copy(self._h, 0)
        # Request-line cap (0 keeps the 1 MiB default); a SET of a ~1 MiB
        # value needs line headroom beyond the value itself.
        if max_line > 0:
            self._lib.mkv_server_set_max_line(self._h, max_line)

    def start(self) -> None:
        if not self._lib.mkv_server_start(self._h):
            raise NativeError("bind/listen failed")

    @property
    def io_threads(self) -> int:
        """Resolved epoll worker-pool width (0 before start)."""
        if not self._h:
            return 0
        return int(self._lib.mkv_server_io_threads(self._h))

    @property
    def reuseport(self) -> bool:
        """True once start() actually sharded the accept path (every io
        worker owns its own SO_REUSEPORT listener)."""
        if not self._h:
            return False
        return bool(self._lib.mkv_server_reuseport(self._h))

    def set_zero_copy(self, on: bool = True) -> None:
        """Flip the zero-copy serving path (the bench flips it off for
        the compat A/B baseline; wire behavior is identical)."""
        if self._h:
            self._lib.mkv_server_set_zero_copy(self._h, 1 if on else 0)

    @property
    def port(self) -> int:
        return self._lib.mkv_server_port(self._h)

    @property
    def stopping(self) -> bool:
        return bool(self._lib.mkv_server_stopping(self._h))

    def stop(self) -> None:
        self._lib.mkv_server_stop(self._h)

    def wait(self) -> None:
        self._lib.mkv_server_wait(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.mkv_server_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def set_cluster_handler(
        self, handler: Optional[Callable[[str], Optional[str]]]
    ) -> None:
        """Route SYNC/REPLICATE lines to `handler`; return the full response
        text (CRLF included) or None to fall back to native defaults."""
        if handler is None:
            self._cb_ref = None
            self._lib.mkv_server_set_cluster_cb(
                self._h, ctypes.cast(None, _CLUSTER_CB), None
            )
            return

        def trampoline(_ctx, line, out_buf, out_cap):
            try:
                resp = handler(line.decode())
            except Exception as e:  # never let exceptions cross the FFI
                resp = f"ERROR {e}\r\n"
            if resp is None:
                return 0
            data = resp.encode()[: out_cap - 1]
            ctypes.memmove(out_buf, data, len(data))
            return len(data)

        self._cb_ref = _CLUSTER_CB(trampoline)  # keep trampoline alive
        self._lib.mkv_server_set_cluster_cb(self._h, self._cb_ref, None)

    def enable_events(self, on: bool = True) -> None:
        """Turn change-event staging on/off. Off by default — without a
        drainer the queue would pin keys+values for up to 2^20 writes."""
        self._lib.mkv_server_enable_events(self._h, 1 if on else 0)

    def enable_latency(self, on: bool = True) -> None:
        """Toggle the native command-latency histogram (on by default);
        bench.py flips it off to A/B the metrics plane's hot-path cost."""
        self._lib.mkv_server_enable_latency(self._h, 1 if on else 0)

    def set_serving(self, on: bool = True) -> None:
        """Bootstrap read gate: while off, data-plane reads and the
        anti-entropy serving verbs answer ``ERROR LOADING ...`` — a
        bootstrapping node serves zero reads before its shipped snapshot
        verifies (cluster/bootstrap.py flips this). Writes, PING and the
        management verbs stay available."""
        if self._h:
            self._lib.mkv_server_set_serving(self._h, 1 if on else 0)

    @property
    def serving(self) -> bool:
        if not self._h:
            return False
        return bool(self._lib.mkv_server_serving(self._h))

    def set_limits(
        self, max_connections: int = 0, max_pipeline: int = 0
    ) -> None:
        """Admission-control limits: past ``max_connections`` (0 =
        unlimited) excess accepts are answered ``ERROR BUSY connections``
        and closed before ever entering the io worker pool;
        ``max_pipeline`` bounds one connection's unanswered pipelined
        commands (0 = unlimited)."""
        if self._h:
            self._lib.mkv_server_set_limits(
                self._h, max_connections, max_pipeline
            )

    def set_degradation(self, level: int, reason: int = 0) -> None:
        """Push the node's degradation-ladder level (0=live 1=shedding
        2=read_only 3=draining; reason 0=none 1=memory 2=disk 3=draining
        4=admin). The native server enforces it: shedding answers write
        verbs ``ERROR BUSY <why> retry``, read_only/draining answer
        ``ERROR READONLY <why>``, draining also refuses new connections.
        Reads and the management/anti-entropy plane stay open."""
        if self._h:
            self._lib.mkv_server_set_degradation(self._h, level, reason)

    @property
    def degradation(self) -> int:
        """Current degradation-ladder level (0=live .. 3=draining)."""
        if not self._h:
            return 0
        return int(self._lib.mkv_server_degradation(self._h))

    def events_depth(self) -> int:
        """Staged-but-undrained change events (the replication/WAL feed's
        backlog; also on STATS as ``events_queue_depth``)."""
        if not self._h:
            return 0
        return int(self._lib.mkv_server_events_depth(self._h))

    def set_partition(self, epoch: int, count: int, owned: int) -> None:
        """Partitioned cluster mode: this node owns partition ``owned`` of
        a ``count``-way keyspace at map generation ``epoch``. While
        ``count`` > 0 the native dispatch refuses data verbs whose keys
        hash to a FOREIGN partition (and HASH/TREELEVEL requests pt=-
        addressed to one) with the retryable ``ERROR MOVED <pid>
        <epoch>`` — a stale map can never silently read or write the
        wrong node. ``count`` 0 disables the guard (the default)."""
        if self._h:
            self._lib.mkv_server_set_partition(self._h, epoch, count, owned)

    def set_partition_map(
        self,
        epoch: int,
        base: int,
        owned: int,
        assignments: list[tuple[int, int, int]],
    ) -> None:
        """Install a SPLIT-TREE partition map in the native guard (the
        live-rebalancing generalization of :meth:`set_partition`):
        partition ``p`` owns the hash-space cell ``assignments[p] =
        (root, depth, path)`` under ``base`` (see
        cluster/partmap.py — routing stays bit-identical across guard,
        clients, and router). A boot-shaped map degenerates to the
        legacy modulo guard natively."""
        if not self._h:
            return
        n = len(assignments)
        roots = (ctypes.c_uint * n)(*[a[0] for a in assignments])
        depths = (ctypes.c_uint * n)(*[a[1] for a in assignments])
        paths = (ctypes.c_ulonglong * n)(*[a[2] for a in assignments])
        self._lib.mkv_server_set_partition_map(
            self._h, epoch, base, n, owned, roots, depths, paths
        )

    def set_partition_fence(
        self, base: int, root: int, depth: int, path: int
    ) -> None:
        """Arm the rebalance write fence: writes whose key falls in the
        split-tree cell ``(root, depth, path)`` under ``base`` answer the
        retryable ``ERROR BUSY rebalance retry`` until
        :meth:`clear_partition_fence` — the (brief) flip window of a live
        split. Reads keep serving throughout."""
        if self._h:
            self._lib.mkv_server_set_partition_fence(
                self._h, base, root, depth, path
            )

    def clear_partition_fence(self) -> None:
        if self._h:
            self._lib.mkv_server_clear_partition_fence(self._h)

    def set_slow_threshold(self, us: int) -> None:
        """Slow-command log threshold in microseconds (0 = off): a
        dispatch taking at least this long is recorded in the native
        flight log (served by the FLIGHT verb on bare nodes) and relayed
        to the control plane as a SLOWCMD notification so the Python
        flight ring carries it too."""
        if self._h:
            self._lib.mkv_server_set_slow_threshold(self._h, us)

    def drain_events(self, max_events: int = 0) -> list[ChangeEventRaw]:
        out = ctypes.c_void_p()
        out_len = ctypes.c_longlong()
        self._lib.mkv_server_drain_events(
            self._h, max_events, ctypes.byref(out), ctypes.byref(out_len)
        )
        buf = _take_buffer(self._lib, out, out_len.value)
        (n,) = struct.unpack_from("<I", buf, 0)
        events, off = [], 4
        for _ in range(n):
            op, has_value = buf[off], bool(buf[off + 1])
            ts_ns, seq = struct.unpack_from("<QQ", buf, off + 2)
            off += 18
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            key = buf[off : off + klen]
            off += klen
            (vlen,) = struct.unpack_from("<I", buf, off)
            off += 4
            value = buf[off : off + vlen]
            off += vlen
            events.append(ChangeEventRaw(op, has_value, ts_ns, seq, key, value))
        return events

    def events_dropped(self) -> int:
        return self._lib.mkv_server_events_dropped(self._h)

    def wait_events(self, timeout_ms: int) -> bool:
        """Park until the change-event queue is non-empty (or the timeout
        elapses); returns whether events are pending. The drain threads use
        this instead of interval polling — the first staged write wakes
        them, so a single SET replicates in the notify latency, not half a
        poll interval, and an idle node stops burning poll wakeups."""
        if not self._h:
            return False
        return bool(self._lib.mkv_server_wait_events(self._h, timeout_ms))

    def stats_text(self) -> str:
        if not self._h:
            # A /metrics scrape can race server teardown (exporter handler
            # threads outlive node.stop() ordering mistakes); an empty
            # block beats driving the FFI through a dead handle.
            return ""
        out = ctypes.c_void_p()
        out_len = ctypes.c_int()
        self._lib.mkv_server_stats(self._h, ctypes.byref(out), ctypes.byref(out_len))
        return _take_buffer(self._lib, out, out_len.value).decode()

"""Cluster control plane: replication, change events, anti-entropy.

Host-side subsystems around the native server and the TPU Merkle data plane:

- ``change_event``: canonical replication record + CBOR/binary/JSON codecs
  (reference /root/reference/src/change_event.rs)
- ``applier``: pure LWW + idempotency application logic
  (reference replication.rs:272-318 and the LocalApplier test double)
- ``transport``: pub/sub event fabric — in-process bus and a TCP broker
  (reference: external MQTT broker, replication.rs:115-143)
- ``replicator``: drains native write events, publishes, applies remote
- ``sync``: anti-entropy manager — batched snapshot exchange + TPU diff
  (reference sync.rs, minus its per-key-TCP-connection hot loop)
- ``overload``: degradation ladder + watermark monitor (overload
  protection; the native server enforces the pushed level)
- ``node``: wires everything to a running native server
"""

from merklekv_tpu.cluster.change_event import (
    ChangeEvent,
    OpKind,
    coalesce_events,
    decode_any,
    decode_cbor,
    decode_binary,
    decode_events,
    decode_json,
    encode_batch_cbor,
    encode_cbor,
    encode_binary,
    encode_json,
)
from merklekv_tpu.cluster.applier import LWWApplier

__all__ = [
    "ChangeEvent",
    "OpKind",
    "LWWApplier",
    "coalesce_events",
    "decode_any",
    "decode_cbor",
    "decode_binary",
    "decode_events",
    "decode_json",
    "encode_batch_cbor",
    "encode_cbor",
    "encode_binary",
    "encode_json",
]

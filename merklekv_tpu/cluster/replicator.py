"""Replicator: native write events out, remote events applied in.

Reference analog: /root/reference/src/replication.rs — publish every
successful local write as a ChangeEvent on "{prefix}/events" (QoS-1 there;
QoS-0 here, upgraded by the transports' bounded outbox: events published
during a detected broker outage are buffered and flushed after the link
heals, so only the narrow undetected-death window is lossy — and
anti-entropy repairs that residue), subscribe and apply remote events with
loop prevention (src), idempotency (op_id), and per-key LWW.

Differences by design:
  - local writes are staged by the NATIVE server into an EventQueue
    (merklekv_tpu/native/events.h); a drain thread batches them out instead
    of awaiting an MQTT publish inside the request path (reference
    server.rs:925-938 holds the replicator lock per command);
  - applied remote writes go straight to the shared native engine, so they
    do NOT re-enter the server's event queue — no echo loop;
  - the drained batches also feed the TPU incremental Merkle path.

The pipeline is batch-native end to end: a drained batch is coalesced per
key and published as ONE versioned envelope frame (change_event.py,
``[replication] batch_max_events`` / ``batch_max_bytes``), the drain thread
parks on the native queue's notify instead of interval polling, and an
inbound frame runs its surviving ops through ONE native
``mkv_engine_apply_batch`` crossing, ONE device-mirror staging call, and
ONE grouped WAL append. ``batch_max_events <= 1`` publishes legacy
single-event payloads (mixed-version compat mode; also the per-event
baseline ``bench.py replicated_write_throughput`` A/Bs against).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import replace as dc_replace
from typing import Callable, Optional

from merklekv_tpu.cluster.applier import LWWApplier
from merklekv_tpu.cluster.change_event import (
    ChangeEvent,
    OpKind,
    coalesce_events,
    decode_events_meta,
    encode_batch_cbor,
    encode_cbor,
)
from merklekv_tpu.obs import tracewire
from merklekv_tpu.cluster.retry import REPLICATOR_PUBLISH, RetryPolicy
from merklekv_tpu.cluster.transport import Transport
from merklekv_tpu.utils.tracing import get_metrics
from merklekv_tpu.native_bindings import (
    OP_APPEND,
    OP_DECR,
    OP_DEL,
    OP_INCR,
    OP_PREPEND,
    OP_SET,
    OP_TRUNCATE,
    ChangeEventRaw,
    NativeEngine,
    NativeServer,
)

__all__ = ["Replicator"]

_OP_MAP = {
    OP_SET: OpKind.SET,
    OP_DEL: OpKind.DEL,
    OP_INCR: OpKind.INCR,
    OP_DECR: OpKind.DECR,
    OP_APPEND: OpKind.APPEND,
    OP_PREPEND: OpKind.PREPEND,
    OP_TRUNCATE: OpKind.TRUNCATE,
}


class Replicator:
    # Drain-thread park bound: the notify wakes it on the first staged
    # write, so this only caps how long a stop request can go unnoticed.
    IDLE_WAIT_MS = 200
    # Conservative per-event envelope overhead (op_id + field heads + ts)
    # used by the batch_max_bytes frame splitter.
    _EVENT_WIRE_OVERHEAD = 64
    # Bound on frames buffered while a bootstrap holds applies; past it,
    # frames are journaled + dropped from the buffer (anti-entropy repairs
    # the residue — same QoS-0 discipline as a publish drop).
    _MAX_HELD_FRAMES = 8192

    def __init__(
        self,
        engine: NativeEngine,
        server: NativeServer,
        transport: Transport,
        topic_prefix: str = "merkle_kv",
        node_id: str = "",
        batch_listener: Optional[Callable[[list[ChangeEvent]], None]] = None,
        mirror=None,  # Optional[DeviceTreeMirror]
        storage=None,  # Optional[DurableStore]: journals applied remote writes
        retry: Optional[RetryPolicy] = None,
        batch_max_events: int = 512,
        batch_max_bytes: int = 1 << 20,
        lag_tracker=None,  # Optional[obs.lag.ConvergenceTracker]
        max_skew_ms: int = 0,
    ) -> None:
        self._engine = engine
        self._server = server
        self._storage = storage
        self._transport = transport
        self._topic = f"{topic_prefix}/events"
        self.node_id = node_id or f"node-{uuid.uuid4().hex[:12]}"
        self._batch_listener = batch_listener
        self._mirror = mirror
        # <= 1 selects legacy per-event publishing: the wire format an
        # un-batched (older) peer understands, and the per-event baseline
        # the throughput bench A/Bs against.
        self._batch_max_events = max(0, batch_max_events)
        self._batch_max_bytes = max(1024, batch_max_bytes)
        # Publish retry under the shared cluster policy: one near-immediate
        # retry for a transient transport hiccup, then drop and count
        # (QoS-0 by design; anti-entropy repairs the residue).
        self._retry = retry if retry is not None else REPLICATOR_PUBLISH

        # Remote applies install the EVENT's timestamp through the engine's
        # LWW-conditional ops, so replication LWW, anti-entropy LWW, and the
        # store's persisted ordering are ONE ordering — a replayed event
        # older than a sync-repaired value is rejected at the shard lock,
        # not re-installed. A whole inbound frame crosses the FFI once
        # (apply_batch_fn); the applied residue feeds the device mirror and
        # the WAL as single batch calls in _on_message (applies bypass the
        # server's event queue — no echo loop — so this is the mirror's
        # only view of remote writes).
        def _store_ts(k: bytes) -> int:
            # LWW floor for the per-event fallback path: live entry ts or
            # tombstone ts, so a restarted applier (empty in-memory maps)
            # still rejects stale events against persisted state.
            return max(engine.get_ts(k) or 0, engine.tombstone_ts(k) or 0)

        self._applier = LWWApplier(
            engine.set,
            lambda k: engine.delete(k),
            set_ts_fn=lambda k, v, ts: engine.set_if_newer(k, v, ts),
            del_ts_fn=lambda k, ts: engine.delete_if_newer(k, ts),
            store_ts_fn=_store_ts,
            apply_batch_fn=engine.apply_batch,
        )
        self._applier_mu = threading.Lock()
        # Spans drain..mirror-staging: a flush() must not return while
        # another thread holds drained-but-unstaged events — once flush()
        # returns, every event acked before it is at least STAGED in the
        # mirror (the pump's publish_now() then makes it served, which is
        # what the force=true query path composes).
        self._flush_mu = threading.Lock()
        self._stop = threading.Event()
        self._drain_thread: Optional[threading.Thread] = None
        self.published = 0
        self.received = 0
        self.decode_errors = 0
        self.publish_errors = 0
        self.coalesced = 0
        self.buffered = 0
        # Convergence-lag plane (obs/lag.py): outbound frames carry a
        # publish HWM (cumulative events put on the wire — counted even for
        # frames the transport then drops, so a lost frame shows as peer
        # lag until anti-entropy converges); inbound frames feed the
        # per-peer lag gauges through this tracker.
        self._lag = lag_tracker
        self._pub_seq = 0
        # LWW clock-skew guard ([replication] max_skew_ms): an inbound
        # event stamped further than this beyond the local clock is
        # CLAMPED to now + skew BEFORE it is journaled or applied. Under
        # raw LWW a single poisoned future timestamp (one peer with a
        # misconfigured clock) fences its key against every honest writer
        # FOREVER; with the clamp the damage is bounded by the skew
        # window, after which normal writes win again. 0 disables.
        self._max_skew_ns = max(0, int(max_skew_ms)) * 1_000_000
        self.skew_clamped = 0
        # Bootstrap hold: while set, inbound frames JOURNAL (the WAL must
        # never gap) but defer their engine/mirror apply until the verified
        # snapshot is installed — then they replay in arrival order through
        # the same LWW path, so the write stream has no gap and no
        # unverified state ever serves.
        self._holding = False
        self._held: list[tuple[list[ChangeEvent], dict]] = []
        # Rebalance range-forward (double-apply): while armed, every event
        # whose key satisfies the predicate is ALSO published on the
        # forward topic — from both the local-drain side (flush) and the
        # remote-apply side (_on_message), so a write landing on any
        # replica of the donor group reaches the joiner no matter which
        # node this replicator runs on. Duplicates are harmless: the
        # joiner applies under the same LWW ts + op_id discipline as any
        # inbound frame.
        self._fwd_mu = threading.Lock()
        self._fwd_topic: Optional[str] = None
        self._fwd_pred: Optional[Callable[[bytes], bool]] = None
        self._fwd_seq = 0
        self.forwarded = 0
        # ONE pinned bound-method object for subscribe/unsubscribe:
        # transports remove subscriptions by callback IDENTITY, and
        # ``self._on_message`` evaluates to a FRESH bound method on every
        # attribute access — passing it twice hands the transport two
        # different objects, so the unsubscribe in stop() silently never
        # matched and a "disabled" replicator kept applying inbound frames.
        self._on_message_cb = self._on_message

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._server.enable_events(True)
        self._transport.subscribe(self._topic, self._on_message_cb)
        self._drain_thread = threading.Thread(
            target=self._drain_loop, daemon=True, name="mkv-replicator-drain"
        )
        self._drain_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._drain_thread is not None:
            self._drain_thread.join(timeout=5)
            self._drain_thread = None
        if self._storage is None:
            self._server.enable_events(False)
        # else: the WAL still needs every write staged — leave events on so
        # no write acked during this teardown bypasses the journal (the
        # store's own drain resumes the queue right after).
        self.flush()
        self._transport.unsubscribe(self._on_message_cb)

    # -- outbound -----------------------------------------------------------
    def flush(self) -> int:
        """Drain and publish pending native write events once."""
        with self._flush_mu:
            # Watermark BEFORE the drain: every engine mutation at or below
            # it either staged an event this drain collects, or will stage
            # one later with a higher watermark — so the mirror's staleness
            # accounting can only err conservative (see mirror.py).
            watermark = self._engine.version()
            raws = self._server.drain_events()
            if not raws:
                return 0
            events = [self._to_event(r) for r in raws]
            # Mirror first: once events leave the native queue they are the
            # mirror's only chance to see these keys — a publish failure
            # must not cost the mirror the batch. Staging is host-side and
            # cheap; the mirror's pump owns the device dispatch, so this
            # drain thread (and the write path behind it) never waits on
            # the device plane.
            if self._mirror is not None:
                try:
                    self._mirror.on_events(events, watermark=watermark)
                except Exception:
                    # Device trouble: a silently-dropped batch would serve a
                    # divergent root forever; invalidate so HASH falls back
                    # to the native path until a re-warm succeeds.
                    self._mirror.invalidate()
            # TRUNCATE stays local: it only invalidates device mirrors.
            publishable = [ev for ev in events if ev.op is not OpKind.TRUNCATE]
            if self._batch_max_events <= 1:
                published = self._publish_per_event(publishable)
            else:
                published = self._publish_frames(publishable)
            self.published += published
            if published:
                # Registry mirror of the instance counters so METRICS (and
                # the /metrics endpoint) can see replication flow without a
                # handle on this object.
                get_metrics().inc("replicator.published", published)
            self._range_forward(publishable)
            if self._batch_listener is not None:
                try:
                    self._batch_listener(events)
                except Exception:
                    pass
            return len(events)

    # -- rebalance range-forward --------------------------------------------
    def set_range_forward(
        self, topic: str, predicate: Callable[[bytes], bool]
    ) -> None:
        """Arm the double-apply: events whose encoded key satisfies
        ``predicate`` are additionally published on ``topic`` (the joiner's
        replication topic) until :meth:`clear_range_forward`."""
        with self._fwd_mu:
            self._fwd_topic = topic
            self._fwd_pred = predicate

    def clear_range_forward(self) -> None:
        with self._fwd_mu:
            self._fwd_topic = None
            self._fwd_pred = None

    def forward_events(self, topic: str, events: list[ChangeEvent]) -> int:
        """Publish ``events`` as envelope frames on an arbitrary ``topic``
        (rebalance transfer stream + commit-time sweep). The envelope src
        is this node — the joiner's echo filter keys on ITS OWN id, so
        forwarded frames always pass, while the per-event src fields keep
        their original writers for skew attribution."""
        published = 0
        for frame in self._split_frames(events):
            self._fwd_seq += len(frame)
            payload = encode_batch_cbor(
                frame,
                self.node_id,
                hwm_seq=self._fwd_seq,
                hwm_ts=time.time_ns(),
            )
            try:
                self._retry.run(
                    lambda: self._transport.publish(topic, payload),
                    retry_on=(Exception,),
                    should_stop=self._stop.is_set,
                )
                published += len(frame)
            except Exception:
                # Same QoS-0 discipline as the main topic: drop and count.
                # The rebalance flip only proceeds once donor and joiner
                # range roots MATCH, so a dropped forward frame can delay
                # the flip (re-verify retries) but never lose a key.
                self.publish_errors += 1
                get_metrics().inc("replicator.forward_errors")
        if published:
            self.forwarded += published
            get_metrics().inc("replicator.forwarded", published)
        return published

    def _range_forward(self, events: list[ChangeEvent]) -> None:
        """Forward the moving-range subset of one event batch, if armed."""
        with self._fwd_mu:
            topic, pred = self._fwd_topic, self._fwd_pred
        if topic is None or pred is None or not events:
            return
        moving = [
            ev
            for ev in events
            if ev.op is not OpKind.TRUNCATE
            and pred(ev.key.encode("utf-8", "surrogateescape"))
        ]
        if moving:
            self.forward_events(topic, moving)

    def _publish(self, payload: bytes) -> bool:
        try:
            self._retry.run(
                lambda: self._transport.publish(self._topic, payload),
                retry_on=(Exception,),
                should_stop=self._stop.is_set,
            )
            return True
        except Exception:
            # QoS-0 fabric: drop and count; anti-entropy repairs.
            self.publish_errors += 1
            get_metrics().inc("replicator.publish_errors")
            return False

    def _publish_per_event(self, events: list[ChangeEvent]) -> int:
        """Legacy mode (batch_max_events <= 1): one single-event payload per
        write — the format un-batched peers decode, and the per-event
        baseline the throughput bench measures against."""
        published = 0
        for ev in events:
            if self._publish(encode_cbor(ev)):
                published += 1
        return published

    def _publish_frames(self, events: list[ChangeEvent]) -> int:
        """Coalesce per key, split under the [replication] frame caps, and
        publish each frame as ONE envelope. A failed frame drops its whole
        event group (QoS-0 granularity is now the frame — documented in
        docs/FAULT_MODEL.md; anti-entropy repairs the residue)."""
        kept, dropped = coalesce_events(events)
        if dropped:
            self.coalesced += dropped
            get_metrics().inc("replicator.coalesced", dropped)
        published = 0
        metrics = get_metrics()
        # A traced flush (rare: read-your-writes flush inside a traced
        # cycle, tests) stamps the envelope so the apply side stitches.
        trace = tracewire.current_token()
        for frame in self._split_frames(kept):
            metrics.observe_size("replicator.batch_size", len(frame))
            # HWM counts events handed to the transport INCLUDING this
            # frame, publish-success or not: a dropped frame must read as
            # peer lag until anti-entropy repairs it (obs/lag.py).
            self._pub_seq += len(frame)
            payload = encode_batch_cbor(
                frame,
                self.node_id,
                hwm_seq=self._pub_seq,
                hwm_ts=time.time_ns(),
                trace=trace,
            )
            if self._publish(payload):
                published += len(frame)
        return published

    def _split_frames(
        self, events: list[ChangeEvent]
    ) -> list[list[ChangeEvent]]:
        frames: list[list[ChangeEvent]] = []
        cur: list[ChangeEvent] = []
        cur_bytes = 0
        for ev in events:
            # Key sized in encoded BYTES (a CJK or surrogateescape raw key
            # is up to ~4x its character count on the wire).
            size = (
                len(ev.key.encode("utf-8", "surrogateescape"))
                + len(ev.val or b"")
                + self._EVENT_WIRE_OVERHEAD
            )
            if cur and (
                len(cur) >= self._batch_max_events
                or cur_bytes + size > self._batch_max_bytes
            ):
                frames.append(cur)
                cur, cur_bytes = [], 0
            cur.append(ev)
            cur_bytes += size
        if cur:
            frames.append(cur)
        return frames

    def _drain_loop(self) -> None:
        while not self._stop.is_set():
            if self.flush() == 0:
                # Park on the native queue's notify: the first staged write
                # wakes the drain immediately (no 5 ms poll floor on idle
                # latency, no idle wakeup CPU); the timeout only bounds how
                # long a stop request waits.
                self._server.wait_events(self.IDLE_WAIT_MS)

    def _to_event(self, raw: ChangeEventRaw) -> ChangeEvent:
        return ChangeEvent(
            op=_OP_MAP[raw.op],
            key=raw.key.decode("utf-8", "surrogateescape"),
            val=raw.value if raw.has_value else None,
            ts=raw.ts_ns,
            src=self.node_id,
        )

    # -- bootstrap hold ------------------------------------------------------
    def hold_applies(self) -> None:
        """Enter bootstrap mode: inbound frames journal but defer apply."""
        with self._applier_mu:
            self._holding = True

    def release_applies(self) -> int:
        """Replay every held frame (arrival order) and resume live applies.
        Returns the number of frames replayed."""
        with self._applier_mu:
            frames, self._held = self._held, []
            self._holding = False
            replayed = 0
            for events, meta in frames:
                # Journaled at buffer time — replay must not re-journal.
                self._apply_frame(events, journal=False, meta=meta)
                replayed += len(events)
            if replayed:
                # Events, like replicator.buffered: after every release
                # buffered == buffer_replayed, and buffer_dropped counts
                # the journaled-but-never-held overflow separately.
                get_metrics().inc("replicator.buffer_replayed", replayed)
            return len(frames)

    # -- inbound ------------------------------------------------------------
    def _on_message(self, topic: str, payload: bytes) -> None:
        try:
            events, meta = decode_events_meta(payload)
        except ValueError:
            # Malformed messages (and unknown envelope versions) are
            # tolerated, like the reference's decoder fallthrough
            # (replication.rs:150-157) — counted, never applied partially.
            self.decode_errors += 1
            get_metrics().inc("replicator.decode_errors")
            return
        events = [ev for ev in events if ev.src != self.node_id]  # no echo
        if not events:
            return
        events = self._clamp_skew(events)
        self.received += len(events)
        get_metrics().inc("replicator.received", len(events))
        # Remote-apply side of the rebalance double-apply: a moving-range
        # write that landed on a SIBLING replica arrives here on the group
        # topic — relay it to the joiner too (the sibling doesn't forward;
        # only the donor node arms this). Runs before the hold check so a
        # frame buffered by a concurrent bootstrap still reaches the
        # joiner.
        self._range_forward(events)
        if self._lag is not None:
            # Record the publish HWM at DECODE time: a frame held by a
            # bootstrap (or stuck behind a slow apply) reads as lag until
            # its apply accounts for it.
            self._lag.on_frame(
                meta.get("src", ""),
                len(events),
                hseq=meta.get("hseq", 0),
                hts_ns=meta.get("hts", 0),
            )
        with self._applier_mu:
            if self._holding:
                # Journal NOW (recovery replay is LWW-conditional, so
                # journaling an event the replay later rejects is safe),
                # apply after the verified snapshot lands.
                if self._storage is not None:
                    self._storage.record_applied(
                        [
                            (
                                ev.key.encode("utf-8", "surrogateescape"),
                                None if ev.op is OpKind.DEL else ev.val,
                                ev.ts,
                            )
                            for ev in events
                            if ev.op is not OpKind.TRUNCATE
                        ]
                    )
                if len(self._held) < self._MAX_HELD_FRAMES:
                    self._held.append((events, meta))
                    self.buffered += len(events)
                    get_metrics().inc("replicator.buffered", len(events))
                else:
                    # Journaled but not replayable in RAM: anti-entropy
                    # repairs the residue (frame-loss semantics, counted;
                    # the lag plane keeps showing it until a converged
                    # anti-entropy cycle clears the residue).
                    get_metrics().inc("replicator.buffer_dropped",
                                      len(events))
                return
            self._apply_frame(events, journal=True, meta=meta)

    def _clamp_skew(self, events: list[ChangeEvent]) -> list[ChangeEvent]:
        """Clamp future-poisoned timestamps to now + max_skew_ms, counted
        with per-peer attribution (``replicator.skew_clamped.<src>``) so a
        misconfigured clock is findable, not just survived. Runs BEFORE
        journal/hold/apply — the WAL must never persist the poison."""
        if not self._max_skew_ns:
            return events
        limit = time.time_ns() + self._max_skew_ns
        clamped_by_src: dict[str, int] = {}
        out = events
        for i, ev in enumerate(events):
            if ev.ts > limit:
                if out is events:
                    out = list(events)
                out[i] = dc_replace(ev, ts=limit)
                clamped_by_src[ev.src] = clamped_by_src.get(ev.src, 0) + 1
        if clamped_by_src:
            total = sum(clamped_by_src.values())
            self.skew_clamped += total
            m = get_metrics()
            m.inc("replicator.skew_clamped", total)
            for src, n in clamped_by_src.items():
                m.inc(f"replicator.skew_clamped.{src or 'unknown'}", n)
            # Flight recorder: a poisoned clock upstream is a classic
            # slow-burn failure — the clamp burst belongs on the timeline.
            from merklekv_tpu.obs.flightrec import record

            record(
                "skew_clamp",
                count=total,
                srcs=",".join(sorted(s or "unknown" for s in clamped_by_src)),
            )
        return out

    def _apply_frame(
        self,
        events: list[ChangeEvent],
        journal: bool,
        meta: Optional[dict] = None,
    ) -> None:
        """Apply one inbound frame (callers hold ``_applier_mu``): ONE
        native batch crossing, then batch fan-out of the applied residue —
        ONE mirror staging call and (when ``journal``) ONE grouped WAL
        append per frame, the exact LWW ts riding with each op."""
        t0_ns = time.time_ns()
        applied = self._applier.apply_batch(events)
        if applied:
            pairs = [
                (
                    ev.key.encode("utf-8", "surrogateescape"),
                    None if ev.op is OpKind.DEL else ev.val,
                )
                for ev in applied
            ]
            if self._mirror is not None:
                self._mirror.apply_batch(pairs)
            if journal and self._storage is not None:
                self._storage.record_applied(
                    [
                        (key, val, ev.ts)
                        for (key, val), ev in zip(pairs, applied)
                    ]
                )
        meta = meta or {}
        if self._lag is not None:
            # Account the frame's FULL decoded event count (the publisher
            # counted them all in the HWM), applied or LWW-rejected alike.
            self._lag.on_applied(
                meta.get("src", ""),
                len(events),
                hts_ns=meta.get("hts", 0),
                oldest_event_ts_ns=min((ev.ts for ev in events), default=0),
            )
        tc = meta.get("tc")
        if tc:
            # Traced envelope: this apply stitches into the originating
            # write's trace as an applier-role span.
            ctx = tracewire.parse_token(tc)
            if ctx is not None:
                tracewire.get_collector().record(
                    trace_id=ctx.trace_id,
                    span_id=tracewire._new_id(),
                    parent_id=ctx.span_id,
                    name="replicate.apply",
                    role="applier",
                    ts_ns=t0_ns,
                    dur_ns=time.time_ns() - t0_ns,
                    node=self.node_id,
                )

    # -- introspection -------------------------------------------------------
    @property
    def applier(self) -> LWWApplier:
        return self._applier

"""Replicator: native write events out, remote events applied in.

Reference analog: /root/reference/src/replication.rs — publish every
successful local write as a ChangeEvent on "{prefix}/events" (QoS-1 there;
QoS-0 here, upgraded by the transports' bounded outbox: events published
during a detected broker outage are buffered and flushed after the link
heals, so only the narrow undetected-death window is lossy — and
anti-entropy repairs that residue), subscribe and apply remote events with
loop prevention (src), idempotency (op_id), and per-key LWW.

Differences by design:
  - local writes are staged by the NATIVE server into an EventQueue
    (merklekv_tpu/native/events.h); a drain thread batches them out instead
    of awaiting an MQTT publish inside the request path (reference
    server.rs:925-938 holds the replicator lock per command);
  - applied remote writes go straight to the shared native engine, so they
    do NOT re-enter the server's event queue — no echo loop;
  - the drained batches also feed the TPU incremental Merkle path.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Optional

from merklekv_tpu.cluster.applier import LWWApplier
from merklekv_tpu.cluster.change_event import (
    ChangeEvent,
    OpKind,
    decode_any,
    encode_cbor,
)
from merklekv_tpu.cluster.retry import REPLICATOR_PUBLISH, RetryPolicy
from merklekv_tpu.cluster.transport import Transport
from merklekv_tpu.utils.tracing import get_metrics
from merklekv_tpu.native_bindings import (
    OP_APPEND,
    OP_DECR,
    OP_DEL,
    OP_INCR,
    OP_PREPEND,
    OP_SET,
    OP_TRUNCATE,
    ChangeEventRaw,
    NativeEngine,
    NativeServer,
)

__all__ = ["Replicator"]

_OP_MAP = {
    OP_SET: OpKind.SET,
    OP_DEL: OpKind.DEL,
    OP_INCR: OpKind.INCR,
    OP_DECR: OpKind.DECR,
    OP_APPEND: OpKind.APPEND,
    OP_PREPEND: OpKind.PREPEND,
    OP_TRUNCATE: OpKind.TRUNCATE,
}


class Replicator:
    def __init__(
        self,
        engine: NativeEngine,
        server: NativeServer,
        transport: Transport,
        topic_prefix: str = "merkle_kv",
        node_id: str = "",
        drain_interval: float = 0.005,
        batch_listener: Optional[Callable[[list[ChangeEvent]], None]] = None,
        mirror=None,  # Optional[DeviceTreeMirror]
        storage=None,  # Optional[DurableStore]: journals applied remote writes
        retry: Optional[RetryPolicy] = None,
    ) -> None:
        self._engine = engine
        self._server = server
        self._storage = storage
        self._transport = transport
        self._topic = f"{topic_prefix}/events"
        self.node_id = node_id or f"node-{uuid.uuid4().hex[:12]}"
        self._drain_interval = drain_interval
        self._batch_listener = batch_listener
        self._mirror = mirror
        # Publish retry under the shared cluster policy: one near-immediate
        # retry for a transient transport hiccup, then drop and count
        # (QoS-0 by design; anti-entropy repairs the residue).
        self._retry = retry if retry is not None else REPLICATOR_PUBLISH

        # Remote applies install the EVENT's timestamp through the engine's
        # LWW-conditional ops (set_if_newer / del_if_newer), so replication
        # LWW, anti-entropy LWW, and the store's persisted ordering are ONE
        # ordering — a replayed event older than a sync-repaired value is
        # rejected at the shard lock, not re-installed. Applies also bypass
        # the server's event queue (no echo loop), so the device mirror is
        # fed inline here — only when the op actually changed state.
        def _set_ts(k: bytes, v: bytes, ts: int) -> bool:
            applied = engine.set_if_newer(k, v, ts)
            if applied:
                if mirror is not None:
                    mirror.apply_one(k, v)
                if storage is not None:
                    storage.record_set(k, v, ts)
            return applied

        def _del(k: bytes) -> None:
            if engine.delete(k):
                if mirror is not None:
                    mirror.apply_one(k, None)
                if storage is not None:
                    # delete() stamped the tombstone "now" inside the
                    # engine; journal that exact ts for identical replay.
                    ts = engine.tombstone_ts(k)
                    if ts is not None:
                        storage.record_delete(k, ts)

        def _del_ts(k: bytes, ts: int) -> bool:
            applied = engine.delete_if_newer(k, ts)
            if applied:
                if mirror is not None:
                    mirror.apply_one(k, None)
                if storage is not None:
                    storage.record_delete(k, ts)
            return applied

        def _store_ts(k: bytes) -> int:
            # The store's LWW floor: live entry ts or tombstone ts. Keeps a
            # restarted applier (empty in-memory maps) from resurrecting
            # state that anti-entropy or a prior run already superseded.
            return max(engine.get_ts(k) or 0, engine.tombstone_ts(k) or 0)

        self._applier = LWWApplier(
            engine.set,
            _del,
            set_ts_fn=_set_ts,
            del_ts_fn=_del_ts,
            store_ts_fn=_store_ts,
        )
        self._applier_mu = threading.Lock()
        # Spans drain..mirror-apply: a flush() must not return while another
        # thread holds drained-but-unapplied events, or device_root_hex's
        # read-your-writes guarantee breaks.
        self._flush_mu = threading.Lock()
        self._stop = threading.Event()
        self._drain_thread: Optional[threading.Thread] = None
        self.published = 0
        self.received = 0
        self.decode_errors = 0
        self.publish_errors = 0

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._server.enable_events(True)
        self._transport.subscribe(self._topic, self._on_message)
        self._drain_thread = threading.Thread(
            target=self._drain_loop, daemon=True, name="mkv-replicator-drain"
        )
        self._drain_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._drain_thread is not None:
            self._drain_thread.join(timeout=5)
            self._drain_thread = None
        if self._storage is None:
            self._server.enable_events(False)
        # else: the WAL still needs every write staged — leave events on so
        # no write acked during this teardown bypasses the journal (the
        # store's own drain resumes the queue right after).
        self.flush()
        self._transport.unsubscribe(self._on_message)

    # -- outbound -----------------------------------------------------------
    def flush(self) -> int:
        """Drain and publish pending native write events once."""
        with self._flush_mu:
            raws = self._server.drain_events()
            if not raws:
                return 0
            events = [self._to_event(r) for r in raws]
            # Mirror first: once events leave the native queue they are the
            # mirror's only chance to see these keys — a publish failure
            # must not cost the mirror the batch.
            if self._mirror is not None:
                try:
                    self._mirror.on_events(events)
                except Exception:
                    # Device trouble: a silently-dropped batch would serve a
                    # divergent root forever; invalidate so HASH falls back
                    # to the native path until a re-warm succeeds.
                    self._mirror.invalidate()
            published = 0
            for ev in events:
                # TRUNCATE stays local: it only invalidates device mirrors.
                if ev.op is OpKind.TRUNCATE:
                    continue
                payload = encode_cbor(ev)
                try:
                    self._retry.run(
                        lambda: self._transport.publish(self._topic, payload),
                        retry_on=(Exception,),
                        should_stop=self._stop.is_set,
                    )
                    published += 1
                except Exception:
                    # QoS-0 fabric: drop and count; anti-entropy repairs.
                    self.publish_errors += 1
                    get_metrics().inc("replicator.publish_errors")
            self.published += published
            if published:
                # Registry mirror of the instance counters so METRICS (and
                # the /metrics endpoint) can see replication flow without a
                # handle on this object.
                get_metrics().inc("replicator.published", published)
            if self._batch_listener is not None:
                try:
                    self._batch_listener(events)
                except Exception:
                    pass
            return len(events)

    def _drain_loop(self) -> None:
        while not self._stop.is_set():
            if self.flush() == 0:
                time.sleep(self._drain_interval)

    def _to_event(self, raw: ChangeEventRaw) -> ChangeEvent:
        return ChangeEvent(
            op=_OP_MAP[raw.op],
            key=raw.key.decode("utf-8", "surrogateescape"),
            val=raw.value if raw.has_value else None,
            ts=raw.ts_ns,
            src=self.node_id,
        )

    # -- inbound ------------------------------------------------------------
    def _on_message(self, topic: str, payload: bytes) -> None:
        try:
            ev = decode_any(payload)
        except ValueError:
            # Malformed messages are tolerated, like the reference's decoder
            # fallthrough (replication.rs:150-157).
            self.decode_errors += 1
            get_metrics().inc("replicator.decode_errors")
            return
        if ev.src == self.node_id:
            return  # loop prevention
        self.received += 1
        get_metrics().inc("replicator.received")
        with self._applier_mu:
            self._applier.apply(ev)

    # -- introspection -------------------------------------------------------
    @property
    def applier(self) -> LWWApplier:
        return self._applier

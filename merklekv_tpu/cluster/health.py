"""Peer failure detection: periodic PING probes + per-peer health table.

The reference has no peer health at all — a down peer is discovered only
when a sync attempt times out (SURVEY §5.3: "no peer health checks, no
membership"). Here a background monitor probes every configured peer with a
short-timeout PING, tracks (status, consecutive failures, last-ok time,
round-trip), feeds the metrics registry, and lets the anti-entropy loop
skip known-down peers instead of burning a full connect timeout per cycle.
Surfaced over the wire as the extension verb ``PEERS`` (docs/PROTOCOL.md).
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass
from typing import Optional

from merklekv_tpu.cluster.retry import HEALTH_PROBE, RetryPolicy
from merklekv_tpu.utils.tracing import get_metrics

__all__ = ["PeerHealth", "PeerHealthMonitor"]


@dataclass
class PeerHealth:
    peer: str  # "host:port"
    # "unknown" until the first probe lands; "down" only after down_after
    # consecutive failures; "degraded" when the peer answers probes but a
    # sync/replication operation against it died mid-flight (reported via
    # mark_degraded); one probe success flips degraded/down back to "up".
    status: str = "unknown"
    consecutive_failures: int = 0
    last_ok_unix: float = 0.0
    last_probe_unix: float = 0.0
    rtt_ms: float = -1.0
    probes: int = 0
    last_error: str = ""  # most recent degradation reason, "" when healthy


class PeerHealthMonitor:
    """Background PING prober over the cluster's peer list.

    Probe cadence/timeout/threshold derive from the shared HEALTH_PROBE
    policy (cluster/retry.py); explicit constructor arguments still win.
    """

    def __init__(
        self,
        peers: list[str],
        interval_seconds: Optional[float] = None,
        timeout: Optional[float] = None,
        down_after: Optional[int] = None,
        policy: Optional[RetryPolicy] = None,
    ) -> None:
        policy = policy if policy is not None else HEALTH_PROBE
        self._interval = (
            interval_seconds if interval_seconds is not None
            else policy.first_delay
        )
        self._timeout = timeout if timeout is not None else policy.op_timeout
        self._down_after = (
            down_after if down_after is not None else (policy.attempts or 2)
        )
        self._mu = threading.Lock()
        self._health: dict[str, PeerHealth] = {
            p: PeerHealth(peer=p) for p in peers
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="mkv-peer-health"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- probing -------------------------------------------------------------
    def probe_all(self) -> None:
        """One synchronous probe round (the loop body; tests call directly)."""
        with self._mu:
            peers = list(self._health)
        for peer in peers:
            ok, rtt = self._probe(peer)
            self._record(peer, ok, rtt)

    def _probe(self, peer: str) -> tuple[bool, float]:
        host, _, port = peer.rpartition(":")
        t0 = time.perf_counter()
        try:
            with socket.create_connection(
                (host, int(port)), timeout=self._timeout
            ) as sock:
                sock.settimeout(self._timeout)
                sock.sendall(b"PING health\r\n")
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = sock.recv(256)
                    if not chunk:
                        return False, -1.0
                    buf += chunk
                if not buf.startswith(b"PONG"):
                    return False, -1.0
        except (OSError, ValueError):
            return False, -1.0
        return True, (time.perf_counter() - t0) * 1e3

    def _record(self, peer: str, ok: bool, rtt_ms: float) -> None:
        now = time.time()
        flip = None  # (prev, new) outside the lock
        with self._mu:
            h = self._health.get(peer)
            if h is None:
                return
            h.probes += 1
            h.last_probe_unix = now
            if ok:
                if h.status == "down":
                    get_metrics().inc("health.peer_recoveries")
                if h.status not in ("up", "unknown"):
                    flip = (h.status, "up")
                h.status = "up"
                h.consecutive_failures = 0
                h.last_ok_unix = now
                h.rtt_ms = rtt_ms
                h.last_error = ""
            else:
                h.consecutive_failures += 1
                if (
                    h.consecutive_failures >= self._down_after
                    and h.status != "down"
                ):
                    flip = (h.status, "down")
                    h.status = "down"
                    get_metrics().inc("health.peer_failures")
        if flip is not None:
            # Flight recorder: peer state FLIPS only (the steady state is
            # noise; transitions are the timeline).
            from merklekv_tpu.obs.flightrec import record

            record("peer_health", peer=peer, prev=flip[0], new=flip[1])

    def _run(self) -> None:
        # First round immediately so the table is useful right away.
        while True:
            try:
                self.probe_all()
            except Exception:
                get_metrics().inc("health.probe_errors")
            if self._stop.wait(self._interval):
                return

    # -- external failure reports --------------------------------------------
    def mark_degraded(self, peer: str, reason: str = "") -> None:
        """A component saw ``peer`` fail mid-operation (sync stream died,
        injected fault, repair deadline expired) even though probes may
        still succeed. The table shows it, metrics count it, and the next
        successful probe clears it. Peers not in the configured list are
        added so ad-hoc sync targets surface too."""
        flipped_from = None
        with self._mu:
            h = self._health.get(peer)
            if h is None:
                h = self._health[peer] = PeerHealth(peer=peer)
            h.last_error = reason
            if h.status != "down":
                if h.status != "degraded":
                    flipped_from = h.status
                h.status = "degraded"
        get_metrics().inc("health.peer_degradations")
        if flipped_from is not None:
            from merklekv_tpu.obs.flightrec import record

            record("peer_health", peer=peer, prev=flipped_from,
                   new="degraded", reason=reason)

    # -- queries -------------------------------------------------------------
    def is_up(self, peer: str) -> bool:
        """False only for peers confirmed down; unknown/unconfigured peers
        answer True so nothing is skipped on startup."""
        with self._mu:
            h = self._health.get(peer)
            return h is None or h.status != "down"

    def snapshot(self) -> list[PeerHealth]:
        with self._mu:
            return [PeerHealth(**vars(h)) for h in self._health.values()]

    def wire_table(self) -> str:
        """The PEERS response body (extension verb)."""
        rows = self.snapshot()
        out = f"PEERS {len(rows)}\r\n"
        for h in rows:
            out += (
                f"addr={h.peer} status={h.status} "
                f"failures={h.consecutive_failures} "
                f"rtt_ms={h.rtt_ms:.2f} last_ok={int(h.last_ok_unix)}"
            )
            if h.last_error:
                # k=v fields are space-separated on the wire; the free-text
                # reason is squeezed so it stays one field.
                out += f" error={h.last_error.replace(' ', '_')[:80]}"
            out += "\r\n"
        out += "END\r\n"
        return out

"""Peer failure detection: periodic PING probes + per-peer health table.

The reference has no peer health at all — a down peer is discovered only
when a sync attempt times out (SURVEY §5.3: "no peer health checks, no
membership"). Here a background monitor probes every configured peer with a
short-timeout PING, tracks (status, consecutive failures, last-ok time,
round-trip), feeds the metrics registry, and lets the anti-entropy loop
skip known-down peers instead of burning a full connect timeout per cycle.
Surfaced over the wire as the extension verb ``PEERS`` (docs/PROTOCOL.md).
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass
from typing import Optional

from merklekv_tpu.utils.tracing import get_metrics

__all__ = ["PeerHealth", "PeerHealthMonitor"]


@dataclass
class PeerHealth:
    peer: str  # "host:port"
    # "unknown" until the first probe lands; "down" only after down_after
    # consecutive failures; one success flips back to "up".
    status: str = "unknown"
    consecutive_failures: int = 0
    last_ok_unix: float = 0.0
    last_probe_unix: float = 0.0
    rtt_ms: float = -1.0
    probes: int = 0


class PeerHealthMonitor:
    """Background PING prober over the cluster's peer list."""

    def __init__(
        self,
        peers: list[str],
        interval_seconds: float = 2.0,
        timeout: float = 1.0,
        down_after: int = 2,
    ) -> None:
        self._interval = interval_seconds
        self._timeout = timeout
        self._down_after = down_after
        self._mu = threading.Lock()
        self._health: dict[str, PeerHealth] = {
            p: PeerHealth(peer=p) for p in peers
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="mkv-peer-health"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- probing -------------------------------------------------------------
    def probe_all(self) -> None:
        """One synchronous probe round (the loop body; tests call directly)."""
        with self._mu:
            peers = list(self._health)
        for peer in peers:
            ok, rtt = self._probe(peer)
            self._record(peer, ok, rtt)

    def _probe(self, peer: str) -> tuple[bool, float]:
        host, _, port = peer.rpartition(":")
        t0 = time.perf_counter()
        try:
            with socket.create_connection(
                (host, int(port)), timeout=self._timeout
            ) as sock:
                sock.settimeout(self._timeout)
                sock.sendall(b"PING health\r\n")
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = sock.recv(256)
                    if not chunk:
                        return False, -1.0
                    buf += chunk
                if not buf.startswith(b"PONG"):
                    return False, -1.0
        except (OSError, ValueError):
            return False, -1.0
        return True, (time.perf_counter() - t0) * 1e3

    def _record(self, peer: str, ok: bool, rtt_ms: float) -> None:
        now = time.time()
        with self._mu:
            h = self._health.get(peer)
            if h is None:
                return
            h.probes += 1
            h.last_probe_unix = now
            if ok:
                if h.status == "down":
                    get_metrics().inc("health.peer_recoveries")
                h.status = "up"
                h.consecutive_failures = 0
                h.last_ok_unix = now
                h.rtt_ms = rtt_ms
            else:
                h.consecutive_failures += 1
                if (
                    h.consecutive_failures >= self._down_after
                    and h.status != "down"
                ):
                    h.status = "down"
                    get_metrics().inc("health.peer_failures")

    def _run(self) -> None:
        # First round immediately so the table is useful right away.
        while True:
            try:
                self.probe_all()
            except Exception:
                get_metrics().inc("health.probe_errors")
            if self._stop.wait(self._interval):
                return

    # -- queries -------------------------------------------------------------
    def is_up(self, peer: str) -> bool:
        """False only for peers confirmed down; unknown/unconfigured peers
        answer True so nothing is skipped on startup."""
        with self._mu:
            h = self._health.get(peer)
            return h is None or h.status != "down"

    def snapshot(self) -> list[PeerHealth]:
        with self._mu:
            return [PeerHealth(**vars(h)) for h in self._health.values()]

    def wire_table(self) -> str:
        """The PEERS response body (extension verb)."""
        rows = self.snapshot()
        out = f"PEERS {len(rows)}\r\n"
        for h in rows:
            out += (
                f"addr={h.peer} status={h.status} "
                f"failures={h.consecutive_failures} "
                f"rtt_ms={h.rtt_ms:.2f} last_ok={int(h.last_ok_unix)}\r\n"
            )
        out += "END\r\n"
        return out

"""DeviceTreeMirror: a live device-resident Merkle tree behind serving HASH.

The reference recomputes its tree from scratch on demand (HASH scans and
rehashes every leaf, server.rs:647-684) and never feeds writes into the tree
(TODO at replication.rs:312-316). Here the native server stages every write
into the event queue; the replicator drains them and this mirror applies the
batches to a ``DeviceMerkleState`` — value updates are O(k log C) scatters on
device, so a warm HASH answer costs one promotion-chain walk instead of an
O(n) rehash.

Consistency model: the mirror trails the engine by at most one drain
interval; ``ClusterNode.device_root_hex`` flushes the replicator first, so a
client that observed its write's response sees a root that includes it.
"""

from __future__ import annotations

import threading
from typing import Optional

from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind
from merklekv_tpu.native_bindings import NativeEngine

__all__ = ["DeviceTreeMirror"]


class DeviceTreeMirror:
    def __init__(self, engine: NativeEngine, sharded: bool = False) -> None:
        self._engine = engine
        # Shard the device tree's leaf level over ALL local JAX devices
        # (GSPMD over a "key" mesh) instead of living on one chip — the
        # serving-path integration of the SPMD program (SURVEY §2.4).
        self._sharded = sharded
        self._mu = threading.RLock()
        self._state = None  # lazy: built from an engine snapshot on first use
        self._warming = threading.Event()
        self._warm_thread: Optional[threading.Thread] = None
        self._closed = False
        # While a warm build runs outside the lock, writes landing meanwhile
        # are recorded here (keys only) and replayed against the engine's
        # current values when the built state is swapped in.
        self._pending: Optional[set] = None
        self._pending_truncate = False
        # Engine mutation version observed at the last applied batch — the
        # staleness gauge's anchor ("versions behind live"). Approximate by
        # design: a write racing the post-apply read is counted as synced
        # one batch early, never unboundedly.
        self._synced_version = 0

    # -- warm-up -------------------------------------------------------------
    def ready(self) -> bool:
        return self._state is not None

    def invalidate(self) -> None:
        """Throw the device state away (e.g. after a failed batch apply);
        the next HASH request answers natively and triggers a re-warm."""
        with self._mu:
            self._state = None
            self._pending = None
        self._warming.clear()

    def close(self) -> None:
        """Stop using the engine. MUST be called before the native engine is
        destroyed — the warm thread snapshots through its raw pointer."""
        with self._mu:
            self._closed = True
        t = self._warm_thread
        if t is not None and t.is_alive():
            t.join(timeout=30)

    def start_warming(self) -> None:
        """Build the device state off the serving path.

        The first device use pays jax import + kernel compile (seconds);
        HASH must not stall behind it, so the server keeps answering from
        the native path until ``ready()``. The build runs OUTSIDE the
        mirror lock — holding it would stall the replicator drain loop and
        inbound LWW applies for the whole compile. Writes landing during
        the build are recorded (keys only) and replayed from the engine's
        current values at swap-in; a truncate mid-build restarts it.
        """
        if self._warming.is_set():
            return
        self._warming.set()

        def warm() -> None:
            try:
                for _attempt in range(3):
                    with self._mu:
                        if self._state is not None or self._closed:
                            return
                        self._pending = set()
                        self._pending_truncate = False
                        items = self._engine.snapshot()
                    cls = self._device_state_cls()
                    st = cls.from_items(items, sharding=self._make_sharding())
                    # Pay the build + kernel-compile cost HERE so the first
                    # post-warm HASH answers immediately.
                    st.root_hex()
                    with self._mu:
                        if self._closed:
                            return
                        if self._pending_truncate:
                            self._pending = None
                            continue  # keyspace vanished mid-build; redo
                        pend, self._pending = self._pending, None
                        if pend:
                            st.apply(
                                [(k, self._engine.get(k)) for k in pend]
                            )
                        self._state = st
                        self._synced_version = self._engine.version()
                        return
            except Exception:
                pass
            self._warming.clear()  # allow a retry

        self._warm_thread = threading.Thread(
            target=warm, daemon=True, name="mkv-mirror-warm"
        )
        self._warm_thread.start()

    # -- event feeds ---------------------------------------------------------
    def on_events(self, events: list[ChangeEvent]) -> None:
        """Local writes, drained from the native event queue in batches.

        The event's payload value is deliberately ignored: local events
        arrive asynchronously (drain thread) while remote LWW applies land
        inline, so replaying stale payloads could leave the mirror on an
        older value than the engine. Re-reading the engine's CURRENT value
        for each touched key makes every batch a convergence step — any
        write racing the read stages its own later event.
        """
        with self._mu:
            if self._closed:
                return
            if self._state is None:
                self._note_pending(
                    (ev.key.encode("utf-8", "surrogateescape")
                     if ev.op is not OpKind.TRUNCATE else None)
                    for ev in events
                )
                return
            touched: dict[bytes, None] = {}
            for ev in events:
                if ev.op is OpKind.TRUNCATE:
                    # Everything before the truncate is dead.
                    touched.clear()
                    self._state = self._empty_state()
                    continue
                touched[ev.key.encode("utf-8", "surrogateescape")] = None
            if touched:
                self._state.apply(
                    [(k, self._engine.get(k)) for k in touched]
                )
            self._synced_version = self._engine.version()

    def apply_one(self, key: bytes, value: Optional[bytes]) -> None:
        """One remote write (anti-entropy repair hook)."""
        self.apply_batch([(key, value)])

    def apply_batch(self, pairs: list[tuple[bytes, Optional[bytes]]]) -> None:
        """Remote writes from one decoded replication frame: ONE lock
        acquisition and ONE device-state staging call for the whole frame
        (per-key applies paid both per event — at sustained remote write
        rates the lock/stage overhead, not the device math, dominated)."""
        if not pairs:
            return
        with self._mu:
            if self._closed:
                return
            if self._state is None:
                self._note_pending(k for k, _ in pairs)
                return
            self._state.apply(pairs)
            self._synced_version = self._engine.version()

    def _note_pending(self, keys) -> None:
        """Record writes landing during a warm build (lock held by caller).
        A None entry marks a truncate, which invalidates the whole build."""
        if self._pending is None:
            return  # no build in flight; the eventual snapshot covers these
        for k in keys:
            if k is None:
                self._pending_truncate = True
                self._pending.clear()
            else:
                self._pending.add(k)

    # -- queries -------------------------------------------------------------
    def root_hex(self) -> str:
        with self._mu:
            if self._closed:
                raise RuntimeError("mirror closed")
            if self._state is None:
                self._state = self._load_state()
            return self._state.root_hex()

    def level_nodes(self, level: int, lo: int, hi: int):
        """TREELEVEL slice from the device-resident tree: reference-level
        ``(idx, digest)`` rows plus the leaf count, or None while the state
        is not built (the native host fallback answers instead)."""
        with self._mu:
            if self._closed or self._state is None:
                return None
            return self._state.level_nodes(level, lo, hi)

    def leaf_count(self) -> int:
        """Leaf count of the built device tree, or -1 while warming. Reads
        the sorted key array only — no device work, safe on a gauge path
        (staged pending changes are not counted until their flush)."""
        with self._mu:
            if self._closed or self._state is None:
                return -1
            return self._state.leaf_count()

    def staleness(self) -> int:
        """Engine mutation versions the mirror trails the live keyspace by
        (0 = fully caught up; -1 while warming). Only meaningful on
        version-tracking engines (the sharded/log natives)."""
        with self._mu:
            if self._closed or self._state is None:
                return -1  # also guards the engine FFI after close()
            return max(0, self._engine.version() - self._synced_version)

    @property
    def state(self):
        return self._state

    # -- internals -----------------------------------------------------------
    @staticmethod
    def _device_state_cls():
        # Honor MERKLEKV_JAX_PLATFORM before the first device use (not at
        # module import): N spawned servers must not race for a
        # single-process accelerator backend.
        from merklekv_tpu.utils.jaxenv import ensure_platform

        ensure_platform()
        from merklekv_tpu.merkle.incremental import DeviceMerkleState

        return DeviceMerkleState

    def _make_sharding(self):
        """NamedSharding over local devices ("key" mesh) when sharded
        serving is on; None for the single-device tree. Non-power-of-two
        device counts mesh the largest power-of-two subset — the padded
        tree's capacity is a power of two and must divide evenly."""
        if not self._sharded:
            return None
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from merklekv_tpu.parallel.mesh import make_mesh

        # LOCAL devices only: the mirror is a per-node structure driven by
        # this node's event stream, not an SPMD program — under a
        # multi-host jax cluster (parallel/multihost.py) jax.devices()
        # includes other hosts' non-addressable chips, and a device_put
        # onto those would fail or deadlock.
        devs = jax.local_devices()
        n = 1 << (len(devs).bit_length() - 1)  # largest pow2 <= len(devs)
        mesh = make_mesh({"key": n}, devices=devs[:n])
        return NamedSharding(mesh, PartitionSpec("key", None))

    def _load_state(self):
        return self._device_state_cls().from_items(
            self._engine.snapshot(), sharding=self._make_sharding()
        )

    def _empty_state(self):
        return self._device_state_cls()(sharding=self._make_sharding())

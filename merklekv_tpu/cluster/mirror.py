"""DeviceTreeMirror: a live device-resident Merkle tree behind serving HASH.

The reference recomputes its tree from scratch on demand (HASH scans and
rehashes every leaf, server.rs:647-684) and never feeds writes into the tree
(TODO at replication.rs:312-316). Here the native server stages every write
into the event queue; the replicator drains them and this mirror applies the
batches to a ``DeviceMerkleState`` — value updates are O(k log C) scatters on
device, so a warm HASH answer costs one promotion-chain walk instead of an
O(n) rehash.

Freshness contract (the async-Merkle design, PAPERS.md arxiv 2311.17441):
writes never wait on the device plane. Staging an event batch is one lock +
one host-dict update; the **device-update pump** — a background thread owned
by this mirror — drains staged changes into incremental scatter dispatches
on its own cadence and PUBLISHES the result as the served snapshot
(version + generation + lazily cached root). Root-serving queries read the
last-published snapshot and therefore trail the live engine by a BOUNDED
window, governed by ``[device] max_staleness_ms`` / ``max_staleness_versions``:

  - idle -> the first staged batch wakes the pump and publishes immediately;
  - sustained load -> publishes are rate-limited to a small coalesce
    interval (a fraction of the window), so backlog accumulates into larger
    scatter dispatches instead of one device program per event batch — the
    adaptive sizing is emergent: arrival rate x publish latency = batch size;
  - the window is a hard serving bound: a breach (or a wedged pump) raises
    a ``tree_staleness`` flight event, and the staleness gauge reads the
    exact version lag.

Exactness escape hatch: ``publish_now()`` drains synchronously — the
``force=true`` query path (snapshot stamping, tests) and the wire-level
forced refresh use it. Every published answer can be stamped with
``published_version()`` so readers (anti-entropy) know which engine version
the tree reflects.

Watermark semantics (what makes ``staleness()`` exact): every staging call
carries the engine mutation version its events are covered through — the
replicator reads ``engine.version()`` BEFORE draining the native queue, so
the watermark can only UNDERSTATE coverage (a racing write either made the
drain or stages its own later event with a higher watermark). The pump's
published version is the watermark of the last drained staging, hence
``engine.version() - published_version`` never under-reports how far the
served tree trails. Remote-apply staging reads the version after its own
engine apply; a concurrent local write inside that instant can be counted
one drain cycle early — transient, corrected by the next local drain's
conservative watermark.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind
from merklekv_tpu.native_bindings import NativeEngine

__all__ = ["DeviceTreeMirror"]

# One tree_staleness flight flag per this many seconds (same one-flag-per-
# window discipline as the blackbox slow-command bursts).
_STALENESS_FLAG_WINDOW_S = 10.0


class DeviceTreeMirror:
    def __init__(
        self,
        engine: NativeEngine,
        sharded: bool = False,
        max_staleness_ms: float = 200.0,
        max_staleness_versions: int = 0,
        sharding: str = "off",
    ) -> None:
        self._engine = engine
        # Serving-tree backend selection ([device] sharding = auto|off|N):
        # "off" keeps the single-device DeviceMerkleState; anything else
        # resolves to a ShardedDeviceMerkleState over a power-of-two mesh
        # of LOCAL devices — per-shard subtree rebuilds in parallel, shard
        # roots combined via the all_gather top tree, answers bit-identical
        # to the single-device tree. ``sharded`` is the deprecated boolean
        # alias (== "auto").
        mode = str(sharding).strip().lower()
        self._sharding_mode = "auto" if (sharded and mode == "off") else mode
        self._mu = threading.RLock()
        self._state = None  # lazy: built from an engine snapshot on first use
        self._warming = threading.Event()
        self._warm_thread: Optional[threading.Thread] = None
        self._closed = False
        # While a warm build runs outside the lock, writes landing meanwhile
        # are recorded here (keys only) and replayed against the engine's
        # current values when the built state is swapped in.
        self._pending: Optional[set] = None
        self._pending_truncate = False
        # Freshness contract ([device]): the serving window the pump keeps
        # the published tree inside. ms is the wall bound; versions (0=off)
        # additionally forces an immediate publish once the backlog deepens
        # past it (skipping the coalesce delay).
        self._window_s = max(0.001, float(max_staleness_ms) / 1000.0)
        self._max_lag_versions = max(0, int(max_staleness_versions))
        # Publish rate limit under sustained load — the emergent-batching
        # knob. A fraction of the window so several pump cycles always fit
        # inside the contract.
        self._coalesce_s = min(0.005, self._window_s / 8.0)
        # Engine-version watermark the staging covers (see module
        # docstring) and the watermark of the last PUBLISHED snapshot.
        self._staged_version = 0
        self._published_version = 0
        self._published_gen = 0  # bumps on every publish; keys the root cache
        self._published_root: Optional[str] = None  # lazy per generation
        self._staged_since_m: Optional[float] = None  # oldest unpublished stage
        self._last_publish_m = 0.0
        self._staleness_flagged_m = -1e18
        # The device-update pump.
        self._pump_wake = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        # Test hook: callable raised/invoked inside the pump's drain (chaos
        # tests kill the pump mid-drain through it). None in production.
        self._pump_inject = None

    # -- warm-up -------------------------------------------------------------
    def ready(self) -> bool:
        return self._state is not None

    def invalidate(self) -> None:
        """Throw the device state away (e.g. after a failed batch apply);
        the next HASH request answers natively and triggers a re-warm."""
        with self._mu:
            self._state = None
            self._pending = None
            self._published_root = None
            self._staged_since_m = None
        self._warming.clear()

    def close(self) -> None:
        """Stop using the engine. MUST be called before the native engine is
        destroyed — the warm thread and the pump read through its raw
        pointer."""
        with self._mu:
            self._closed = True
        self._pump_wake.set()
        p = self._pump_thread
        if p is not None and p.is_alive():
            p.join(timeout=30)
        t = self._warm_thread
        if t is not None and t.is_alive():
            t.join(timeout=30)

    def start_warming(self) -> None:
        """Build the device state off the serving path.

        The first device use pays jax import + kernel compile (seconds);
        HASH must not stall behind it, so the server keeps answering from
        the native path until ``ready()``. The build runs OUTSIDE the
        mirror lock — holding it would stall the replicator drain loop and
        inbound LWW applies for the whole compile. Writes landing during
        the build are recorded (keys only) and replayed from the engine's
        current values at swap-in; a truncate mid-build restarts it."""
        self._ensure_pump()
        if self._warming.is_set():
            return
        self._warming.set()

        def warm() -> None:
            try:
                for _attempt in range(3):
                    with self._mu:
                        if self._state is not None or self._closed:
                            return
                        self._pending = set()
                        self._pending_truncate = False
                        # Watermark BEFORE the snapshot: every mutation at
                        # or below it is in the snapshot by construction;
                        # later ones either land in _pending or stage their
                        # own event with a higher watermark.
                        v0 = self._engine.version()
                        items = self._engine.snapshot()
                    st = self._build_state(items)
                    # Pay the build + kernel-compile cost HERE so the first
                    # post-warm HASH answers immediately.
                    st.root_hex()
                    with self._mu:
                        if self._closed:
                            return
                        if self._pending_truncate:
                            self._pending = None
                            continue  # keyspace vanished mid-build; redo
                        pend, self._pending = self._pending, None
                        if pend:
                            st.apply(
                                [(k, self._engine.get(k)) for k in pend]
                            )
                            st.flush_pending()
                        self._state = st
                        self._staged_version = max(
                            self._staged_version, v0
                        )
                        self._publish_locked()
                        return
            except Exception:
                pass
            self._warming.clear()  # allow a retry

        self._warm_thread = threading.Thread(
            target=warm, daemon=True, name="mkv-mirror-warm"
        )
        self._warm_thread.start()

    # -- event feeds (staging: never device work beyond PENDING_LIMIT) -------
    def on_events(
        self, events: list[ChangeEvent], watermark: Optional[int] = None
    ) -> None:
        """Local writes, drained from the native event queue in batches.

        The event's payload value is deliberately ignored: local events
        arrive asynchronously (drain thread) while remote LWW applies land
        inline, so replaying stale payloads could leave the mirror on an
        older value than the engine. Re-reading the engine's CURRENT value
        for each touched key makes every batch a convergence step — any
        write racing the read stages its own later event.

        ``watermark`` is the engine version read BEFORE the queue drain
        (conservative coverage — see the module docstring); None falls back
        to a read at staging time."""
        with self._mu:
            if self._closed:
                return
            if self._state is None:
                self._note_pending(
                    (ev.key.encode("utf-8", "surrogateescape")
                     if ev.op is not OpKind.TRUNCATE else None)
                    for ev in events
                )
                return
            touched: dict[bytes, None] = {}
            truncated = False
            for ev in events:
                if ev.op is OpKind.TRUNCATE:
                    # Everything before the truncate is dead.
                    touched.clear()
                    self._state = self._empty_state()
                    truncated = True
                    continue
                touched[ev.key.encode("utf-8", "surrogateescape")] = None
            if touched:
                self._state.apply(
                    [(k, self._engine.get(k)) for k in touched]
                )
            self._note_staged(watermark)
            if truncated:
                # The served tree content changed in place (reset): flush
                # whatever was staged after the truncate and publish, so the
                # generation moves with the content and stamps stay
                # truthful.
                self._state.flush_pending()
                self._publish_locked()
        self._ensure_pump()  # a dead pump is respawned by fresh staging
        self._pump_wake.set()

    def apply_one(self, key: bytes, value: Optional[bytes]) -> None:
        """One remote write (anti-entropy repair hook)."""
        self.apply_batch([(key, value)])

    def apply_batch(self, pairs: list[tuple[bytes, Optional[bytes]]]) -> None:
        """Remote writes from one decoded replication frame: ONE lock
        acquisition and ONE device-state staging call for the whole frame
        (per-key applies paid both per event — at sustained remote write
        rates the lock/stage overhead, not the device math, dominated)."""
        if not pairs:
            return
        with self._mu:
            if self._closed:
                return
            if self._state is None:
                self._note_pending(k for k, _ in pairs)
                return
            self._state.apply(pairs)
            self._note_staged(None)
        self._ensure_pump()  # a dead pump is respawned by fresh staging
        self._pump_wake.set()

    def _note_staged(self, watermark: Optional[int]) -> None:
        """Bookkeeping after a staging call (lock held): advance the staged
        watermark, start the lag clock, and — when the state auto-flushed at
        PENDING_LIMIT — publish inline so the served tree content can never
        move without a generation/version bump."""
        wm = watermark if watermark is not None else self._engine.version()
        self._staged_version = max(self._staged_version, wm)
        if self._staged_since_m is None:
            self._staged_since_m = time.monotonic()
        if self._state is not None and self._state.pending_count() == 0:
            # DeviceMerkleState.apply flushed at its PENDING_LIMIT ceiling:
            # the built tree just advanced past the published generation.
            self._publish_locked()

    def _note_pending(self, keys) -> None:
        """Record writes landing during a warm build (lock held by caller).
        A None entry marks a truncate, which invalidates the whole build."""
        if self._pending is None:
            return  # no build in flight; the eventual snapshot covers these
        for k in keys:
            if k is None:
                self._pending_truncate = True
                self._pending.clear()
            else:
                self._pending.add(k)

    # -- the device-update pump ----------------------------------------------
    def _ensure_pump(self) -> None:
        """Start (or restart after a death) the pump thread. Cheap when the
        thread is alive; a pump killed by device trouble mid-drain is
        respawned by the next warm-up, so one wedged drain never leaves the
        mirror permanently unpumped."""
        with self._mu:
            if self._closed:
                return
            p = self._pump_thread
            if p is not None and p.is_alive():
                return
            self._pump_thread = threading.Thread(
                target=self._pump_loop, daemon=True, name="mkv-mirror-pump"
            )
            self._pump_thread.start()

    def _pump_loop(self) -> None:
        from merklekv_tpu.utils.tracing import get_metrics

        while True:
            self._pump_wake.wait(timeout=self._window_s)
            self._pump_wake.clear()
            with self._mu:
                if self._closed:
                    return
                st = self._state
                ver_lag = self._staged_version - self._published_version
                behind = (
                    st is not None
                    and (st.pending_count() > 0 or ver_lag > 0)
                )
            if behind:
                # Coalesce under sustained load: a publish that would land
                # hot on the heels of the previous one waits a beat so the
                # backlog accumulates into one larger scatter dispatch.
                # Idle arrivals (last publish long ago) and deep backlogs
                # (past the versions knob, measured in ENGINE MUTATIONS
                # like the config documents — a hot single key rewritten N
                # times is N versions behind, not 1 staged key) drain
                # immediately.
                since = time.monotonic() - self._last_publish_m
                wait = self._coalesce_s - since
                deep = (
                    self._max_lag_versions
                    and ver_lag >= self._max_lag_versions
                )
                if wait > 0 and not deep:
                    time.sleep(min(wait, self._window_s / 2))
                try:
                    self.publish_now()
                    get_metrics().inc("device.pump_batches")
                except Exception:
                    # A wedged device drain must not serve a divergent tree
                    # forever: flag the timeline, then throw the state away
                    # (queries fall back to the native path and trigger a
                    # re-warm, which also respawns this pump if the failure
                    # killed it). The flag rides the tree_staleness event —
                    # after invalidate() the breach check goes silent
                    # (state None), so this is the one chance to record
                    # the drain death.
                    get_metrics().inc("device.pump_errors")
                    try:
                        since = self._staged_since_m
                        lag_ms = (
                            0.0 if since is None
                            else (time.monotonic() - since) * 1000.0
                        )
                        # Quiet the generic breach flag for a window: this
                        # explicit event IS the flag for this failure.
                        self._staleness_flagged_m = time.monotonic()
                        from merklekv_tpu.obs.flightrec import record

                        record(
                            "tree_staleness",
                            lag_ms=int(lag_ms),
                            lag_versions=int(max(0, ver_lag)),
                            window_ms=int(self._window_s * 1000),
                            drain_failed=1,
                        )
                    except Exception:
                        pass
                    self.invalidate()
            self._check_staleness_breach()

    def publish_now(self) -> None:
        """Synchronous drain + publish — the ``force=true`` escape hatch
        (snapshot stamping, wire-level forced refresh) and the pump's own
        drain step. Dispatches every staged change to the device and stamps
        the published snapshot with the staged watermark."""
        with self._mu:
            if self._closed or self._state is None:
                return
            if self._pump_inject is not None:
                self._pump_inject()  # chaos hook: die mid-drain
            had_work = (
                self._state.pending_count() > 0
                or self._staged_version > self._published_version
            )
            self._state.flush_pending()
            if had_work or self._published_gen == 0:
                self._publish_locked()

    def _publish_locked(self) -> None:
        """Stamp the built tree as the served snapshot (lock held; the
        state's pending set MUST be empty — flush before publishing, or the
        stamp would claim coverage of undispatched changes)."""
        self._published_version = max(
            self._published_version, self._staged_version
        )
        self._published_gen += 1
        self._published_root = None  # recomputed lazily, cached per gen
        self._staged_since_m = None
        self._last_publish_m = time.monotonic()

    def _check_staleness_breach(self) -> None:
        """Flight-recorder hook: one ``tree_staleness`` event per flag
        window when the published tree trails past the contract (deep
        version lag or a stale wall clock) — a wedged device queue then
        shows up on the blackbox timeline instead of only as a gauge.

        Deliberately LOCK-FREE: the exact failure this event exists for is
        a pump wedged inside a device dispatch while HOLDING ``_mu`` — a
        lock-taking check could never run then. It reads plain attributes
        (atomic in CPython; a torn read costs at most one spurious or
        missed flag, never a wrong serve), and it is invoked both by the
        pump loop and by the monitoring reads (``pump_lag_ms`` — polled
        every second by the flight sampler via the gauge), so a dead or
        stuck pump is still flagged."""
        if self._closed or self._state is None:
            return
        since = self._staged_since_m
        lag_ms = (
            0.0 if since is None
            else max(0.0, (time.monotonic() - since) * 1000.0)
        )
        try:
            lag_v = max(0, self._engine.version() - self._published_version)
        except Exception:
            return
        breached = lag_ms > self._window_s * 1000.0 or (
            self._max_lag_versions
            and lag_v > self._max_lag_versions
            and since is not None
        )
        now = time.monotonic()
        if (
            not breached
            or now - self._staleness_flagged_m < _STALENESS_FLAG_WINDOW_S
        ):
            return
        self._staleness_flagged_m = now
        from merklekv_tpu.obs.flightrec import record

        record(
            "tree_staleness",
            lag_ms=int(lag_ms),
            lag_versions=int(lag_v),
            window_ms=int(self._window_s * 1000),
        )

    # -- queries (published-snapshot serving) ---------------------------------
    def root_hex(self) -> str:
        """EXACT root: drains staged changes first (one publish), then
        serves. Direct-API callers (tests, snapshot verification) get
        read-your-writes; the wire query path uses ``published_root_hex``
        so it never waits on the device plane."""
        with self._mu:
            if self._closed:
                raise RuntimeError("mirror closed")
            if self._state is None:
                self._state = self._load_state()
                self._staged_version = max(
                    self._staged_version, self._engine.version()
                )
            self.publish_now()
            return self.published_root_hex()

    def published_root_hex(self) -> Optional[str]:
        """Root of the last-published snapshot (None while warming): the
        bounded-staleness serving path. Cached per publish generation, so
        a HASH storm costs one device root walk per pump cycle, not per
        query."""
        with self._mu:
            if self._closed or self._state is None:
                return None
            if self._published_root is None:
                self._published_root = self._state.root_hex(flush=False)
            return self._published_root

    def level_nodes(self, level: int, lo: int, hi: int):
        """TREELEVEL slice from the last-published device tree: reference-
        level ``(idx, digest)`` rows plus the leaf count, or None while the
        state is not built (the native host fallback answers instead).
        Serves the tree AS PUBLISHED — staged changes stay staged, so a
        walker's fetches within one generation are mutually consistent."""
        with self._mu:
            if self._closed or self._state is None:
                return None
            return self._state.level_nodes(level, lo, hi, flush=False)

    def leaf_count(self) -> int:
        """Leaf count of the built device tree, or -1 while warming. Reads
        the sorted key array only — no device work, safe on a gauge path
        (staged pending changes are not counted until their flush)."""
        with self._mu:
            if self._closed or self._state is None:
                return -1
            return self._state.leaf_count()

    def published_version(self) -> int:
        """Engine mutation version the served tree reflects (the version
        stamp on TREELEVEL/HASH answers). 0 while warming."""
        with self._mu:
            return self._published_version if self._state is not None else 0

    def published_root_stamped(self) -> Optional[tuple[str, int]]:
        """(root_hex, published_version) read under ONE lock hold, so the
        stamp can never claim a different generation than the root it rides
        with. None while warming."""
        with self._mu:
            root = self.published_root_hex()
            if root is None:
                return None
            return root, self._published_version

    def level_nodes_stamped(self, level: int, lo: int, hi: int):
        """``level_nodes`` plus the published version, atomically (one lock
        hold) — the stamped TREELEVEL serve. None while warming."""
        with self._mu:
            out = self.level_nodes(level, lo, hi)
            if out is None:
                return None
            rows, n = out
            return rows, n, self._published_version

    def staleness(self) -> int:
        """Engine mutation versions the PUBLISHED tree trails the live
        keyspace by (0 = fully caught up; -1 while warming). Exact against
        ``mkv_engine_version`` up to the conservative-watermark semantics
        in the module docstring. Only meaningful on version-tracking
        engines (the sharded/log natives)."""
        with self._mu:
            if self._closed or self._state is None:
                return -1  # also guards the engine FFI after close()
            return max(0, self._engine.version() - self._published_version)

    def pump_lag_ms(self) -> float:
        """Milliseconds the oldest staged-but-unpublished change has waited
        (0.0 when the pump is caught up) — the wall half of the staleness
        contract, and the ``device.pump_lag_ms`` gauge. Lock-free (plain
        attribute reads) so a pump wedged under ``_mu`` cannot block the
        monitoring plane; each read also runs the breach check, which is
        how a wedged/dead pump still lands a ``tree_staleness`` event via
        the flight sampler's 1 s gauge poll."""
        since = self._staged_since_m
        self._check_staleness_breach()
        if since is None or self._state is None:
            return 0.0
        return max(0.0, (time.monotonic() - since) * 1000.0)

    @property
    def state(self):
        return self._state

    # -- internals -----------------------------------------------------------
    def _resolve_shards(self) -> int:
        """[device] sharding -> shard count (0 = single-device backend).
        Resolved at state-build time against the LOCAL device complement:
        the mirror is a per-node structure driven by this node's event
        stream, not a cross-host SPMD program — under a multi-host jax
        cluster (parallel/multihost.py) jax.devices() includes other hosts'
        non-addressable chips, and a device_put onto those would fail or
        deadlock."""
        # Honor MERKLEKV_JAX_PLATFORM before the first device use (not at
        # module import): N spawned servers must not race for a
        # single-process accelerator backend.
        from merklekv_tpu.utils.jaxenv import ensure_platform

        ensure_platform()
        import jax

        from merklekv_tpu.parallel.sharded_state import resolve_shard_count

        return resolve_shard_count(
            self._sharding_mode, len(jax.local_devices())
        )

    def _build_state(self, items):
        """State factory — the pluggable backend seam. The pump, staging,
        and every query path drive whichever state this returns through the
        identical DeviceMerkleState surface."""
        d = self._resolve_shards()
        if d <= 0:
            from merklekv_tpu.merkle.incremental import DeviceMerkleState

            return DeviceMerkleState.from_items(items)
        from merklekv_tpu.parallel.sharded_state import (
            ShardedDeviceMerkleState,
        )

        return ShardedDeviceMerkleState.from_items(items, shards=d)

    def _load_state(self):
        return self._build_state(self._engine.snapshot())

    def _empty_state(self):
        return self._build_state(())

    def shard_count(self) -> int:
        """Device shards serving the built tree (1 = single-device state;
        -1 while warming/closed) — the ``device.shards`` gauge."""
        with self._mu:
            st = self._state
            if self._closed or st is None:
                return -1
            return int(getattr(st, "_n_shards", 1))

    def shard_rebuild_us(self) -> int:
        """Dispatch cost of the last sharded subtree rebuild in
        microseconds (-1: single-device backend or none yet) — the
        ``device.shard_rebuild_us`` gauge. Lock-free like pump_lag_ms: a
        monitoring read must never park behind a device dispatch."""
        st = self._state
        if st is None:
            return -1
        return int(getattr(st, "last_shard_rebuild_us", -1))

"""DeviceTreeMirror: a live device-resident Merkle tree behind serving HASH.

The reference recomputes its tree from scratch on demand (HASH scans and
rehashes every leaf, server.rs:647-684) and never feeds writes into the tree
(TODO at replication.rs:312-316). Here the native server stages every write
into the event queue; the replicator drains them and this mirror applies the
batches to a ``DeviceMerkleState`` — value updates are O(k log C) scatters on
device, so a warm HASH answer costs one promotion-chain walk instead of an
O(n) rehash.

Freshness contract (the async-Merkle design, PAPERS.md arxiv 2311.17441):
writes never wait on the device plane. Staging an event batch is one lock +
one host-dict update; the **device-update pump** — a background thread owned
by this mirror — drains staged changes into incremental scatter dispatches
on its own cadence and PUBLISHES the result as the served snapshot
(version + generation + lazily cached root). Root-serving queries read the
last-published snapshot and therefore trail the live engine by a BOUNDED
window, governed by ``[device] max_staleness_ms`` / ``max_staleness_versions``:

  - idle -> the first staged batch wakes the pump and publishes immediately;
  - sustained load -> publishes are rate-limited to a small coalesce
    interval (a fraction of the window), so backlog accumulates into larger
    scatter dispatches instead of one device program per event batch — the
    adaptive sizing is emergent: arrival rate x publish latency = batch size;
  - the window is a hard serving bound: a breach (or a wedged pump) raises
    a ``tree_staleness`` flight event, and the staleness gauge reads the
    exact version lag.

Exactness escape hatch: ``publish_now()`` drains synchronously — the
``force=true`` query path (snapshot stamping, tests) and the wire-level
forced refresh use it. Every published answer can be stamped with
``published_version()`` so readers (anti-entropy) know which engine version
the tree reflects.

Watermark semantics (what makes ``staleness()`` exact): every staging call
carries the engine mutation version its events are covered through — the
replicator reads ``engine.version()`` BEFORE draining the native queue, so
the watermark can only UNDERSTATE coverage (a racing write either made the
drain or stages its own later event with a higher watermark). The pump's
published version is the watermark of the last drained staging, hence
``engine.version() - published_version`` never under-reports how far the
served tree trails. Remote-apply staging reads the version after its own
engine apply; a concurrent local write inside that instant can be counted
one drain cycle early — transient, corrected by the next local drain's
conservative watermark.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind
from merklekv_tpu.device.guard import DeviceDispatchError, configure as _configure_guard
from merklekv_tpu.device.ladder import (
    DeviceBackendLadder,
    build_state_for_rung,
)
from merklekv_tpu.native_bindings import NativeEngine
from merklekv_tpu.obs.metrics import get_metrics
from merklekv_tpu.utils.errorkind import classify_exception

__all__ = ["DeviceTreeMirror"]

# One tree_staleness flight flag per this many seconds (same one-flag-per-
# window discipline as the blackbox slow-command bursts).
_STALENESS_FLAG_WINDOW_S = 10.0

# One device_fallback heartbeat per this many seconds while a previously
# ready mirror serves off the native fallback (post-invalidate) — a node on
# the fallback rung must be visible in the flight timeline, not silent.
_FALLBACK_FLAG_WINDOW_S = 10.0


class DeviceTreeMirror:
    def __init__(
        self,
        engine: NativeEngine,
        sharded: bool = False,
        max_staleness_ms: float = 200.0,
        max_staleness_versions: int = 0,
        sharding: str = "off",
        dispatch_deadline_ms: Optional[float] = None,
        scrub_interval_s: float = 30.0,
        scrub_keys: int = 256,
        degrade_after: int = 2,
        ladder: Optional[DeviceBackendLadder] = None,
    ) -> None:
        self._engine = engine
        # Serving-tree backend selection ([device] sharding = auto|off|N):
        # "off" keeps the single-device DeviceMerkleState; anything else
        # resolves to a ShardedDeviceMerkleState over a power-of-two mesh
        # of LOCAL devices — per-shard subtree rebuilds in parallel, shard
        # roots combined via the all_gather top tree, answers bit-identical
        # to the single-device tree. ``sharded`` is the deprecated boolean
        # alias (== "auto").
        mode = str(sharding).strip().lower()
        self._sharding_mode = "auto" if (sharded and mode == "off") else mode
        self._mu = threading.RLock()
        self._state = None  # lazy: built from an engine snapshot on first use
        self._warming = threading.Event()
        self._warm_thread: Optional[threading.Thread] = None
        self._closed = False
        # While a warm build runs outside the lock, writes landing meanwhile
        # are recorded here (keys only) and replayed against the engine's
        # current values when the built state is swapped in.
        self._pending: Optional[set] = None
        self._pending_truncate = False
        # Freshness contract ([device]): the serving window the pump keeps
        # the published tree inside. ms is the wall bound; versions (0=off)
        # additionally forces an immediate publish once the backlog deepens
        # past it (skipping the coalesce delay).
        self._window_s = max(0.001, float(max_staleness_ms) / 1000.0)
        self._max_lag_versions = max(0, int(max_staleness_versions))
        # Publish rate limit under sustained load — the emergent-batching
        # knob. A fraction of the window so several pump cycles always fit
        # inside the contract.
        self._coalesce_s = min(0.005, self._window_s / 8.0)
        # Engine-version watermark the staging covers (see module
        # docstring) and the watermark of the last PUBLISHED snapshot.
        self._staged_version = 0
        self._published_version = 0
        self._published_gen = 0  # bumps on every publish; keys the root cache
        # The published (root, version) pair — the ONLY root cache: one
        # immutable tuple assigned under _mu, read WITHOUT it by the
        # root-serving fast path — a HASH never waits behind a pump drain
        # holding the mirror lock across a device dispatch. Root is None
        # while warming / after invalidate / when a publish had no eager
        # root (the locked lazy path refills it).
        self._pub_snapshot: tuple[Optional[str], int] = (None, 0)
        self._staged_since_m: Optional[float] = None  # oldest unpublished stage
        self._last_publish_m = 0.0
        self._staleness_flagged_m = -1e18
        # The device-update pump.
        self._pump_wake = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        # Test hook: callable raised/invoked inside the pump's drain (chaos
        # tests kill the pump mid-drain through it). None in production.
        self._pump_inject = None
        # Fault containment ([device]): every dispatch under the warm
        # build, the pump, and the query paths runs deadline-guarded —
        # process-wide guard, last EXPLICIT configuration wins
        # (documented). A mirror built without a deadline must not clobber
        # a node's configured value with the guard default.
        if dispatch_deadline_ms is not None:
            _configure_guard(deadline_ms=dispatch_deadline_ms)
        # The degradation ladder. Resolved lazily (the rung list needs the
        # local device complement, i.e. a jax import) unless a test
        # injected one.
        self._ladder = ladder
        self._degrade_after = max(1, int(degrade_after))
        # Integrity scrub: low-rate background cross-check of served
        # device leaf digests against the CPU golden hash over a sampled
        # range (0 = off).
        self._scrub_interval_s = float(scrub_interval_s)
        self._scrub_keys = max(1, int(scrub_keys))
        self._scrub_rng = random.Random()
        self._last_scrub_m = time.monotonic()
        # Fallback-serving heartbeat state (see _check_fallback_heartbeat).
        self._was_ready = False
        self._fallback_flagged_m = -1e18
        self._replacing = False  # a replace-warm (heal re-warm) in flight
        self._probing = False  # a heal-probe pass in flight (own thread)
        self._scrubbing = False  # a scrub pass in flight (own thread)
        self._scrub_thread: Optional[threading.Thread] = None

    # -- warm-up -------------------------------------------------------------
    def ready(self) -> bool:
        return self._state is not None

    def invalidate(self) -> None:
        """Throw the device state away (e.g. after a failed batch apply);
        the next HASH request answers natively and triggers a re-warm.
        While the state is gone, a previously ready mirror emits one
        ``device_fallback`` heartbeat per 10 s window (the flight
        sampler's gauge poll drives it) so fallback serving is visible in
        the timeline, not silent."""
        with self._mu:
            self._state = None
            self._pending = None
            self._pub_snapshot = (None, 0)
            self._staged_since_m = None
        self._warming.clear()

    def close(self) -> None:
        """Stop using the engine. MUST be called before the native engine is
        destroyed — the warm thread and the pump read through its raw
        pointer."""
        with self._mu:
            self._closed = True
        self._pump_wake.set()
        p = self._pump_thread
        if p is not None and p.is_alive():
            p.join(timeout=30)
        t = self._warm_thread
        if t is not None and t.is_alive():
            t.join(timeout=30)
        s = self._scrub_thread
        if s is not None and s.is_alive():
            s.join(timeout=30)

    def start_warming(self) -> None:
        """Build the device state off the serving path.

        The first device use pays jax import + kernel compile (seconds);
        HASH must not stall behind it, so the server keeps answering from
        the native path until ``ready()``. The build runs OUTSIDE the
        mirror lock — holding it would stall the replicator drain loop and
        inbound LWW applies for the whole compile. Writes landing during
        the build are recorded (keys only) and replayed from the engine's
        current values at swap-in; a truncate mid-build restarts it. The
        build itself rides the degradation ladder: a rung whose dispatch
        fails steps down, so warming always completes at SOME rung (the
        CPU golden tree is infallible)."""
        self._ensure_pump()
        if self._warming.is_set():
            return
        self._warming.set()
        self._spawn_warm(replace=False)

    def _start_replace_warm(self) -> None:
        """Re-warm at the ladder's (newly climbed) rung while the CURRENT
        state keeps serving — the heal path's zero-downtime rebuild. The
        old snapshot answers queries until the new state swaps in under
        one lock hold; version stamps stay monotone (publish always
        max()es)."""
        with self._mu:
            if self._closed or self._replacing:
                return
            self._replacing = True
        self._spawn_warm(replace=True)

    def _spawn_warm(self, replace: bool) -> None:
        def warm() -> None:
            try:
                self._warm_body(replace)
            finally:
                if replace:
                    with self._mu:
                        self._replacing = False
            # The ladder may have climbed again while this build ran —
            # the pump's _maybe_heal invariant check re-warms at the
            # final rung next wake; poke it so that happens promptly.
            if replace and not self._closed:
                self._pump_wake.set()

        self._warm_thread = threading.Thread(
            target=warm, daemon=True, name="mkv-mirror-warm"
        )
        self._warm_thread.start()

    def _warm_body(self, replace: bool) -> None:
        mine: Optional[set] = None
        try:
            for _attempt in range(3):
                with self._mu:
                    if self._closed:
                        return
                    if self._state is not None and not replace:
                        return
                    # Ownership-tagged pending set: invalidate() (sets it
                    # None) or a concurrently spawned warm (replaces it)
                    # both ORPHAN this attempt's recording — the swap-in
                    # below checks identity and restarts from a fresh
                    # snapshot rather than install a state whose
                    # mid-build writes were recorded into someone else's
                    # set (that stamped-fresh-but-missing-writes state
                    # would serve a silently wrong root).
                    mine = set()
                    self._pending = mine
                    self._pending_truncate = False
                    # Watermark BEFORE the snapshot: every mutation at
                    # or below it is in the snapshot by construction;
                    # later ones either land in _pending or stage their
                    # own event with a higher watermark.
                    v0 = self._engine.version()
                    items = self._engine.snapshot()
                st = self._build_state(items)
                # Pay the build + kernel-compile cost HERE so the first
                # post-warm HASH answers immediately.
                st.root_hex()
                with self._mu:
                    if self._closed:
                        return
                    if self._pending is not mine:
                        continue  # orphaned (invalidate/new warm); redo
                    if self._pending_truncate:
                        self._pending = None
                        continue  # keyspace vanished mid-build; redo
                    pend, self._pending = self._pending, None
                    # The replay below fixes VALUES for keys whose events
                    # already drained into pend, but it cannot raise the
                    # coverage watermark past v0: local writes reach
                    # _pending only through the async drain, so a write
                    # between v0 and the current engine version may be in
                    # neither the snapshot nor pend. Fencing to the
                    # current version would OVERCLAIM — staleness() reads
                    # 0 for a tree missing that write, and the scrub's
                    # quiescence check would then call the miss silent
                    # corruption. v0 understates at worst (allowed); the
                    # write's own event bumps the watermark when it
                    # drains.
                    if pend:
                        st.apply(
                            [(k, self._engine.get(k)) for k in pend]
                        )
                        st.flush_pending()
                    # Eager root BEFORE the install (same contract as
                    # publish_now): a failing walk unwinds into the warm
                    # retry path with nothing half-published.
                    root = st.root_hex(flush=False)
                    self._state = st
                    self._was_ready = True
                    self._staged_version = max(
                        self._staged_version, v0
                    )
                    self._publish_locked()
                    self._pub_snapshot = (root, self._published_version)
                    self._warming.set()
                    return
        except Exception:
            pass
        finally:
            # Never leak a live recording set from a dead attempt: fresh
            # staging would keep feeding keys no warm will ever consume.
            with self._mu:
                if mine is not None and self._pending is mine:
                    self._pending = None
        if not replace:
            self._warming.clear()  # allow a retry
        # A failed REPLACE warm leaves the old state serving; _warming
        # stays set (its meaning — "a built state is in place") and the
        # pump's heal/invariant pass schedules another attempt.

    # -- event feeds (staging: never device work beyond PENDING_LIMIT) -------
    def on_events(
        self, events: list[ChangeEvent], watermark: Optional[int] = None
    ) -> None:
        """Local writes, drained from the native event queue in batches.

        The event's payload value is deliberately ignored: local events
        arrive asynchronously (drain thread) while remote LWW applies land
        inline, so replaying stale payloads could leave the mirror on an
        older value than the engine. Re-reading the engine's CURRENT value
        for each touched key makes every batch a convergence step — any
        write racing the read stages its own later event.

        ``watermark`` is the engine version read BEFORE the queue drain
        (conservative coverage — see the module docstring); None falls back
        to a read at staging time."""
        with self._mu:
            if self._closed:
                return
            if self._state is None:
                self._note_pending(
                    (ev.key.encode("utf-8", "surrogateescape")
                     if ev.op is not OpKind.TRUNCATE else None)
                    for ev in events
                )
                return
            touched: dict[bytes, None] = {}
            truncated = False
            for ev in events:
                if ev.op is OpKind.TRUNCATE:
                    # Everything before the truncate is dead.
                    touched.clear()
                    self._state = self._empty_state()
                    truncated = True
                    continue
                touched[ev.key.encode("utf-8", "surrogateescape")] = None
            if touched:
                self._state.apply(
                    [(k, self._engine.get(k)) for k in touched]
                )
            if self._pending is not None:
                # A replace re-warm (ladder heal) is building a successor
                # state off the engine snapshot: record these keys for
                # replay at its swap-in, like the initial warm does.
                if truncated:
                    self._note_pending([None])
                self._note_pending(iter(touched))
            self._note_staged(watermark)
            if truncated:
                # The served tree content changed in place (reset): flush
                # whatever was staged after the truncate and publish, so the
                # generation moves with the content and stamps stay
                # truthful.
                self._state.flush_pending()
                self._publish_locked()
        self._ensure_pump()  # a dead pump is respawned by fresh staging
        self._pump_wake.set()

    def apply_one(self, key: bytes, value: Optional[bytes]) -> None:
        """One remote write (anti-entropy repair hook)."""
        self.apply_batch([(key, value)])

    def apply_batch(self, pairs: list[tuple[bytes, Optional[bytes]]]) -> None:
        """Remote writes from one decoded replication frame: ONE lock
        acquisition and ONE device-state staging call for the whole frame
        (per-key applies paid both per event — at sustained remote write
        rates the lock/stage overhead, not the device math, dominated)."""
        if not pairs:
            return
        with self._mu:
            if self._closed:
                return
            if self._state is None:
                self._note_pending(k for k, _ in pairs)
                return
            self._state.apply(pairs)
            if self._pending is not None:
                # Replace re-warm in flight: replay these at swap-in too.
                self._note_pending(k for k, _ in pairs)
            self._note_staged(None)
        self._ensure_pump()  # a dead pump is respawned by fresh staging
        self._pump_wake.set()

    def _note_staged(self, watermark: Optional[int]) -> None:
        """Bookkeeping after a staging call (lock held): advance the staged
        watermark, start the lag clock, and — when the state auto-flushed at
        PENDING_LIMIT — publish inline so the served tree content can never
        move without a generation/version bump."""
        wm = watermark if watermark is not None else self._engine.version()
        self._staged_version = max(self._staged_version, wm)
        if self._staged_since_m is None:
            self._staged_since_m = time.monotonic()
        if self._state is not None and self._state.pending_count() == 0:
            # DeviceMerkleState.apply flushed at its PENDING_LIMIT ceiling:
            # the built tree just advanced past the published generation.
            self._publish_locked()

    def _note_pending(self, keys) -> None:
        """Record writes landing during a warm build (lock held by caller).
        A None entry marks a truncate, which invalidates the whole build."""
        if self._pending is None:
            return  # no build in flight; the eventual snapshot covers these
        for k in keys:
            if k is None:
                self._pending_truncate = True
                self._pending.clear()
            else:
                self._pending.add(k)

    # -- the device-update pump ----------------------------------------------
    def _ensure_pump(self) -> None:
        """Start (or restart after a death) the pump thread. Cheap when the
        thread is alive; a pump killed by device trouble mid-drain is
        respawned by the next warm-up, so one wedged drain never leaves the
        mirror permanently unpumped."""
        with self._mu:
            if self._closed:
                return
            p = self._pump_thread
            if p is not None and p.is_alive():
                return
            self._pump_thread = threading.Thread(
                target=self._pump_loop, daemon=True, name="mkv-mirror-pump"
            )
            self._pump_thread.start()

    def _pump_loop(self) -> None:
        from merklekv_tpu.utils.tracing import get_metrics

        while True:
            self._pump_wake.wait(timeout=self._window_s)
            self._pump_wake.clear()
            with self._mu:
                if self._closed:
                    return
                st = self._state
                ver_lag = self._staged_version - self._published_version
                behind = (
                    st is not None
                    and (st.pending_count() > 0 or ver_lag > 0)
                )
            if behind:
                # Coalesce under sustained load: a publish that would land
                # hot on the heels of the previous one waits a beat so the
                # backlog accumulates into one larger scatter dispatch.
                # Idle arrivals (last publish long ago) and deep backlogs
                # (past the versions knob, measured in ENGINE MUTATIONS
                # like the config documents — a hot single key rewritten N
                # times is N versions behind, not 1 staged key) drain
                # immediately.
                since = time.monotonic() - self._last_publish_m
                wait = self._coalesce_s - since
                deep = (
                    self._max_lag_versions
                    and ver_lag >= self._max_lag_versions
                )
                if wait > 0 and not deep:
                    time.sleep(min(wait, self._window_s / 2))
                try:
                    self.publish_now()
                    get_metrics().inc("device.pump_batches")
                    if self._ladder is not None:
                        self._ladder.note_success()
                except Exception as e:
                    # A failed drain must not serve a divergent tree (the
                    # staged batch was RESTORED by the state's flush, so
                    # the published snapshot stays consistent — just
                    # stale): flag the timeline, count the failure against
                    # the current ladder rung, and once the rung is deemed
                    # sick, step down + rebuild there. Before the step, the
                    # next pump wake simply retries — a one-off backend
                    # blip costs one coalesce window, not the whole tree.
                    get_metrics().inc("device.pump_errors")
                    try:
                        since = self._staged_since_m
                        lag_ms = (
                            0.0 if since is None
                            else (time.monotonic() - since) * 1000.0
                        )
                        # Quiet the generic breach flag for a window: this
                        # explicit event IS the flag for this failure.
                        self._staleness_flagged_m = time.monotonic()
                        from merklekv_tpu.obs.flightrec import record

                        record(
                            "tree_staleness",
                            lag_ms=int(lag_ms),
                            lag_versions=int(max(0, ver_lag)),
                            window_ms=int(self._window_s * 1000),
                            drain_failed=1,
                        )
                    except Exception:
                        pass
                    self._on_drain_failure(e)
            self._maybe_heal()
            self._maybe_scrub()
            self._check_staleness_breach()
            self._check_fallback_heartbeat()

    def publish_now(self) -> None:
        """Synchronous drain + publish — the ``force=true`` escape hatch
        (snapshot stamping, wire-level forced refresh) and the pump's own
        drain step. Dispatches every staged change to the device and stamps
        the published snapshot with the staged watermark."""
        with self._mu:
            if self._closed or self._state is None:
                return
            if self._pump_inject is not None:
                self._pump_inject()  # chaos hook: die mid-drain
            had_work = (
                self._state.pending_count() > 0
                or self._staged_version > self._published_version
            )
            self._state.flush_pending()
            if had_work or self._published_gen == 0:
                # Eager root BEFORE the generation bump: pay the
                # promotion-chain walk HERE (the pump already owns this
                # cycle's device budget) so query threads serve the
                # cached snapshot with ZERO device work. A FLUSH that
                # dies restores its staged batch, so the previous publish
                # stays fully intact (ver_lag stays > 0, the pump
                # retries, the failure feeds the ladder). A ROOT WALK
                # that dies after a successful flush is different: the
                # tree content has already advanced past the published
                # stamp, so keeping the old snapshot would hand a walker
                # level digests that don't hash to the served root —
                # invalidate (native fallback answers, re-warm restores)
                # and let the raised error feed the ladder as usual.
                try:
                    root = self._state.root_hex(flush=False)
                except BaseException:
                    self.invalidate()
                    raise
                self._publish_locked()
                self._pub_snapshot = (root, self._published_version)

    def _publish_locked(self) -> None:
        """Stamp the built tree as the served snapshot (lock held; the
        state's pending set MUST be empty — flush before publishing, or the
        stamp would claim coverage of undispatched changes)."""
        self._published_version = max(
            self._published_version, self._staged_version
        )
        self._published_gen += 1
        # Root recomputed lazily, cached per generation in _pub_snapshot.
        self._pub_snapshot = (None, self._published_version)
        self._staged_since_m = None
        self._last_publish_m = time.monotonic()

    def _check_staleness_breach(self) -> None:
        """Flight-recorder hook: one ``tree_staleness`` event per flag
        window when the published tree trails past the contract (deep
        version lag or a stale wall clock) — a wedged device queue then
        shows up on the blackbox timeline instead of only as a gauge.

        Deliberately LOCK-FREE: the exact failure this event exists for is
        a pump wedged inside a device dispatch while HOLDING ``_mu`` — a
        lock-taking check could never run then. It reads plain attributes
        (atomic in CPython; a torn read costs at most one spurious or
        missed flag, never a wrong serve), and it is invoked both by the
        pump loop and by the monitoring reads (``pump_lag_ms`` — polled
        every second by the flight sampler via the gauge), so a dead or
        stuck pump is still flagged."""
        if self._closed or self._state is None:
            return
        since = self._staged_since_m
        lag_ms = (
            0.0 if since is None
            else max(0.0, (time.monotonic() - since) * 1000.0)
        )
        try:
            lag_v = max(0, self._engine.version() - self._published_version)
        except Exception:
            return
        breached = lag_ms > self._window_s * 1000.0 or (
            self._max_lag_versions
            and lag_v > self._max_lag_versions
            and since is not None
        )
        now = time.monotonic()
        if (
            not breached
            or now - self._staleness_flagged_m < _STALENESS_FLAG_WINDOW_S
        ):
            return
        self._staleness_flagged_m = now
        from merklekv_tpu.obs.flightrec import record

        record(
            "tree_staleness",
            lag_ms=int(lag_ms),
            lag_versions=int(lag_v),
            window_ms=int(self._window_s * 1000),
        )

    # -- fault containment (ladder / heal / scrub / heartbeat) ---------------
    def _on_drain_failure(self, e: BaseException) -> None:
        """Pump-drain failure accounting, by classified kind:

        - ``code`` (a bug in our own dispatch path, or an injected pump
          death): the state is not trustworthy — invalidate NOW (native
          fallback answers, a re-warm restores serving at the same rung).
          The ladder does not step: the rung isn't sick, the code is.
        - ``environment`` (backend blip, hang, tunnel death): below the
          degrade threshold the published tree stays — consistent, just
          stale; the flush restored its staged batch — and the next wake
          retries. At the threshold the ladder steps down and the mirror
          rebuilds at the lower rung (the build loop keeps stepping if
          that rung is sick too, so the re-warm always lands somewhere)."""
        kind = (
            e.kind
            if isinstance(e, DeviceDispatchError)
            else classify_exception(e)
        )
        ladder = self._ladder
        if kind == "code" or ladder is None:
            # Invalidate only — the next query's warm-up rebuilds (the
            # pre-ladder contract; tests observe the fallback window).
            self.invalidate()
            return
        if ladder.note_failure(kind, "drain"):
            self.invalidate()
            # Rebuild proactively: anti-entropy serves off this tree, and
            # a query-less node must not sit on the fallback rung when a
            # lower rung can serve.
            self.start_warming()

    def _probe_rung(self, target: int) -> bool:
        """One heal probe: build a tiny tree at ``target`` and check its
        root against the CPU golden — a rung that dispatches but answers
        WRONG is as sick as one that throws."""
        probe_items = [(b"mkv:heal-probe", b"ok")]
        try:
            from merklekv_tpu.merkle.cpu_state import CpuMerkleState

            golden = CpuMerkleState.from_items(probe_items).root_hex()
            st = build_state_for_rung(target, probe_items)
            return st.root_hex() == golden
        except Exception:
            return False

    def _maybe_heal(self) -> None:
        """Schedule the background re-warm probe: while degraded,
        periodically (under ``retry.DEVICE_HEAL`` escalating backoff)
        probe a higher rung — on the probe's OWN thread, never the
        pump's: a hang-shaped fault at the probed rung costs the probe
        thread a dispatch deadline, while the pump keeps draining the
        healthy serving rung inside the staleness contract."""
        ladder = self._ladder
        if ladder is None or self._closed:
            return
        # Invariant repair: a probe climb can land while a replace build
        # for a LOWER rung is still in flight — the swapped-in state then
        # trails the ladder. Rebuild at the ladder's rung.
        st = self._state
        if (
            st is not None
            and not self._replacing
            and int(getattr(st, "_n_shards", 1)) != ladder.current()
        ):
            self._start_replace_warm()
            return
        if not ladder.degraded() or not ladder.heal_due():
            return
        with self._mu:
            if self._probing or self._closed:
                return
            self._probing = True
        threading.Thread(
            target=self._heal_probe_pass, daemon=True,
            name="mkv-mirror-probe",
        ).start()

    def _heal_probe_pass(self) -> None:
        """One probe pass (probe thread): consecutive successful probes
        climb AS FAR AS THE PLANE ALLOWS (probes are tiny; full-size
        rebuilds are not), then ONE replace re-warm rebuilds the serving
        state at the final rung while the current state keeps serving."""
        ladder = self._ladder
        climbed = None
        try:
            while ladder.degraded() and not self._closed:
                if climbed is None and not ladder.heal_due():
                    return
                ok = self._probe_rung(ladder.probe_target())
                if ladder.note_probe(ok) is None:
                    break  # failed probe: next attempt after its backoff
                climbed = ladder.current()
        finally:
            with self._mu:
                self._probing = False
            if climbed is not None and not self._closed:
                if self._state is None:
                    self.start_warming()
                else:
                    self._start_replace_warm()

    def _maybe_scrub(self) -> None:
        """Schedule one scrub pass on its OWN thread, never the pump's —
        the same invariant as the heal probe: the scrub's level gather is
        a guarded dispatch, and a hang-shaped fault there would otherwise
        park the pump for the full dispatch deadline while staged writes
        blow through the staleness contract."""
        if self._scrub_interval_s <= 0 or self._closed:
            return
        now = time.monotonic()
        if now - self._last_scrub_m < self._scrub_interval_s:
            return
        with self._mu:
            if self._scrubbing or self._closed:
                return
            self._scrubbing = True
        self._last_scrub_m = now
        self._scrub_thread = threading.Thread(
            target=self._scrub_pass, daemon=True, name="mkv-mirror-scrub"
        )
        self._scrub_thread.start()

    def _scrub_pass(self) -> None:
        try:
            self.scrub_once()
        except Exception:
            pass  # a failed scrub read is a dispatch problem, not a leak
        finally:
            with self._mu:
                self._scrubbing = False

    def scrub_once(self) -> Optional[bool]:
        """Integrity scrub: cross-check a sampled leaf range of the SERVED
        device tree against CPU golden leaf hashes recomputed from the
        engine's current values. Runs only at a quiescent instant (nothing
        staged, engine version == published version, re-checked after the
        reads) so any mismatch proves SILENT DEVICE CORRUPTION — the tree
        content cannot have legitimately moved — and triggers
        invalidate + rebuild instead of serving a wrong root into
        anti-entropy. Returns True (clean), False (mismatch, repair
        kicked), or None (skipped: not quiescent / CPU rung / warming)."""
        from merklekv_tpu.merkle.encoding import leaf_hash

        with self._mu:
            if self._closed or self._state is None:
                return None
            st = self._state
            if getattr(st, "_n_shards", 1) == 0:
                return None  # the CPU rung IS the golden tree
            if st.pending_count() > 0:
                return None
            try:
                v0 = self._engine.version()
            except Exception:
                return None
            if v0 != self._published_version:
                return None  # writes in flight; sample next time
            n = st.leaf_count()
            if n <= 0:
                return None
            k = min(self._scrub_keys, n)
            lo = self._scrub_rng.randrange(0, n - k + 1)
            gen0 = self._published_gen
        # Device gather + engine reads OUTSIDE the mirror lock: the
        # gather is a guarded dispatch — on a wedged backend it parks for
        # the full dispatch deadline, and holding ``_mu`` across that
        # would stall staging, applies, and every locked query path for
        # the duration. The fences below (not ``_mu``) make a mismatch
        # conclusive: keyspace movement bumps the engine version, tree
        # movement (a pump flush or a replace swap-in mid-gather) bumps
        # the publish generation or replaces the state object.
        try:
            out = st.level_nodes(0, lo, lo + k, flush=False)
            if out is None:
                return None
            rows, _ = out
            keys = list(st._keys[lo:lo + k])
        except Exception:
            return None  # raced a tree mutation; not conclusive
        # The gather may have parked for the full dispatch deadline —
        # close() could have run (and its join timed out) meanwhile, and
        # the engine pointer is only valid until then.
        with self._mu:
            if self._closed:
                return None
        vals = [self._engine.get(key) for key in keys]
        try:
            if self._engine.version() != v0:
                return None  # raced a write after all; not conclusive
        except Exception:
            return None
        with self._mu:
            if (
                self._closed
                or self._state is not st
                or self._published_gen != gen0
            ):
                return None  # tree moved under the gather; not conclusive
        get_metrics().inc("device.scrub_checks")
        bad = None
        for (idx, dig), key, val in zip(rows, keys, vals):
            if val is None or leaf_hash(key, val) != dig:
                bad = (idx, key)
                break
        if bad is None:
            return True
        # Mismatch: corruption. Count it against the rung (repeated
        # corruption is a sick device, not cosmic rays) and rebuild from
        # the engine — the engine is authoritative; the device tree is a
        # cache.
        get_metrics().inc("device.scrub_mismatches")
        try:
            from merklekv_tpu.obs.flightrec import record

            record(
                "device_corruption",
                leaf_index=int(bad[0]),
                rung=self.backend_level(),
            )
        except Exception:
            pass
        if self._ladder is not None:
            self._ladder.note_failure("corruption", "scrub")
        self.invalidate()
        self.start_warming()
        return False

    def _check_fallback_heartbeat(self) -> None:
        """One ``device_fallback`` flight event per flag window while a
        previously ready mirror serves off the native fallback
        (post-invalidate, pre-re-warm) — without it, invalidate() silenced
        the staleness breach check (state None) and a node could sit on
        the fallback rung indefinitely with nothing in the timeline.
        Lock-free like the breach check, and invoked from both the pump
        loop and the monitoring reads (``pump_lag_ms``), so it fires even
        with the pump dead."""
        if self._closed or self._state is not None or not self._was_ready:
            return
        now = time.monotonic()
        if now - self._fallback_flagged_m < _FALLBACK_FLAG_WINDOW_S:
            return
        self._fallback_flagged_m = now
        ladder = self._ladder
        try:
            from merklekv_tpu.obs.flightrec import record

            record(
                "device_fallback",
                rung=ladder.current() if ladder is not None else -1,
            )
        except Exception:
            pass

    def backend_level(self) -> int:
        """Serving-backend rung code — the ``device.backend_level`` gauge:
        N>=2 sharded width, 1 single-device, 0 CPU golden tree, -1 native
        fallback (warming / invalidated / closed). Lock-free: a monitoring
        read must never park behind a device dispatch."""
        st = self._state
        if self._closed or st is None:
            return -1
        return int(getattr(st, "_n_shards", 1))

    @property
    def ladder(self) -> Optional[DeviceBackendLadder]:
        return self._ladder

    # -- queries (published-snapshot serving) ---------------------------------
    def root_hex(self) -> str:
        """EXACT root: drains staged changes first (one publish), then
        serves. Direct-API callers (tests, snapshot verification) get
        read-your-writes; the wire query path uses ``published_root_hex``
        so it never waits on the device plane."""
        with self._mu:
            if self._closed:
                raise RuntimeError("mirror closed")
            if self._state is None:
                self._state = self._load_state()
                self._staged_version = max(
                    self._staged_version, self._engine.version()
                )
            self.publish_now()
            return self.published_root_hex()

    def published_root_hex(self) -> Optional[str]:
        """Root of the last-published snapshot (None while warming): the
        bounded-staleness serving path. Cached per publish generation —
        and served LOCK-FREE off the ``_pub_snapshot`` tuple when the
        eager publish filled it (the common case), so a HASH never waits
        behind a pump drain holding ``_mu`` across a device dispatch.
        The locked lazy path below only runs for publishes that skipped
        the eager walk (PENDING_LIMIT / truncate inline publishes)."""
        root, _ = self._pub_snapshot
        if root is not None and self._state is not None and not self._closed:
            return root
        with self._mu:
            if self._closed or self._state is None:
                return None
            root, _ = self._pub_snapshot
            if root is None:
                root = self._state.root_hex(flush=False)
                self._pub_snapshot = (root, self._published_version)
            return root

    def level_nodes(self, level: int, lo: int, hi: int):
        """TREELEVEL slice from the last-published device tree: reference-
        level ``(idx, digest)`` rows plus the leaf count, or None while the
        state is not built (the native host fallback answers instead).
        Serves the tree AS PUBLISHED — staged changes stay staged, so a
        walker's fetches within one generation are mutually consistent."""
        with self._mu:
            if self._closed or self._state is None:
                return None
            return self._state.level_nodes(level, lo, hi, flush=False)

    def leaf_count(self) -> int:
        """Leaf count of the built device tree, or -1 while warming. Reads
        the sorted key array only — no device work, safe on a gauge path
        (staged pending changes are not counted until their flush)."""
        with self._mu:
            if self._closed or self._state is None:
                return -1
            return self._state.leaf_count()

    def published_version(self) -> int:
        """Engine mutation version the served tree reflects (the version
        stamp on TREELEVEL/HASH answers). 0 while warming."""
        with self._mu:
            return self._published_version if self._state is not None else 0

    def published_root_stamped(self) -> Optional[tuple[str, int]]:
        """(root_hex, published_version) read atomically — the stamp can
        never claim a different generation than the root it rides with.
        Lock-free off ``_pub_snapshot`` (one immutable tuple) when the
        eager root is in place; the locked path covers lazy fills. None
        while warming."""
        snap = self._pub_snapshot
        if snap[0] is not None and self._state is not None and not self._closed:
            return snap
        with self._mu:
            root = self.published_root_hex()
            if root is None:
                return None
            return root, self._published_version

    def level_nodes_stamped(self, level: int, lo: int, hi: int):
        """``level_nodes`` plus the published version, atomically (one lock
        hold) — the stamped TREELEVEL serve. None while warming."""
        with self._mu:
            out = self.level_nodes(level, lo, hi)
            if out is None:
                return None
            rows, n = out
            return rows, n, self._published_version

    def staleness(self) -> int:
        """Engine mutation versions the PUBLISHED tree trails the live
        keyspace by (0 = fully caught up; -1 while warming). Exact against
        ``mkv_engine_version`` up to the conservative-watermark semantics
        in the module docstring. Only meaningful on version-tracking
        engines (the sharded/log natives)."""
        with self._mu:
            if self._closed or self._state is None:
                return -1  # also guards the engine FFI after close()
            return max(0, self._engine.version() - self._published_version)

    def pump_lag_ms(self) -> float:
        """Milliseconds the oldest staged-but-unpublished change has waited
        (0.0 when the pump is caught up) — the wall half of the staleness
        contract, and the ``device.pump_lag_ms`` gauge. Lock-free (plain
        attribute reads) so a pump wedged under ``_mu`` cannot block the
        monitoring plane; each read also runs the breach check, which is
        how a wedged/dead pump still lands a ``tree_staleness`` event via
        the flight sampler's 1 s gauge poll."""
        since = self._staged_since_m
        self._check_staleness_breach()
        self._check_fallback_heartbeat()
        if since is None or self._state is None:
            return 0.0
        return max(0.0, (time.monotonic() - since) * 1000.0)

    @property
    def state(self):
        return self._state

    # -- internals -----------------------------------------------------------
    def _resolve_shards(self) -> int:
        """[device] sharding -> shard count (0 = single-device backend).
        Resolved at state-build time against the LOCAL device complement:
        the mirror is a per-node structure driven by this node's event
        stream, not a cross-host SPMD program — under a multi-host jax
        cluster (parallel/multihost.py) jax.devices() includes other hosts'
        non-addressable chips, and a device_put onto those would fail or
        deadlock."""
        # Honor MERKLEKV_JAX_PLATFORM before the first device use (not at
        # module import): N spawned servers must not race for a
        # single-process accelerator backend.
        from merklekv_tpu.utils.jaxenv import ensure_platform

        ensure_platform()
        import jax

        from merklekv_tpu.parallel.sharded_state import resolve_shard_count

        return resolve_shard_count(
            self._sharding_mode, len(jax.local_devices())
        )

    def _ensure_ladder(self) -> DeviceBackendLadder:
        """The degradation ladder, resolved against the local device
        complement on first use (tests may inject a pre-built one)."""
        if self._ladder is None:
            self._ladder = DeviceBackendLadder(
                self._resolve_shards(),
                degrade_after=self._degrade_after,
            )
        return self._ladder

    def _build_state(self, items):
        """State factory — the pluggable backend seam, now riding the
        degradation ladder: build at the current rung; a rung whose
        guarded dispatch fails steps the ladder down IMMEDIATELY (a build
        failure means the rung cannot serve at all — counting to the
        drain threshold would just repeat the cliff) and the build retries
        one rung lower. The CPU golden rung is infallible, so this always
        returns a serving state."""
        items = list(items)
        ladder = self._ensure_ladder()
        while True:
            rung = ladder.current()
            try:
                st = build_state_for_rung(rung, items)
                ladder.note_success()
                return st
            except BaseException as e:
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
                if rung <= 0:
                    raise  # a CPU-rung failure is a bug, not weather
                kind = (
                    e.kind
                    if isinstance(e, DeviceDispatchError)
                    else classify_exception(e)
                )
                ladder.note_failure(kind, "build", immediate=True)

    def _load_state(self):
        return self._build_state(self._engine.snapshot())

    def _empty_state(self):
        return self._build_state(())

    def shard_count(self) -> int:
        """Device shards serving the built tree (1 = single-device state;
        -1 while warming/closed) — the ``device.shards`` gauge."""
        with self._mu:
            st = self._state
            if self._closed or st is None:
                return -1
            return int(getattr(st, "_n_shards", 1))

    def shard_rebuild_us(self) -> int:
        """Dispatch cost of the last sharded subtree rebuild in
        microseconds (-1: single-device backend or none yet) — the
        ``device.shard_rebuild_us`` gauge. Lock-free like pump_lag_ms: a
        monitoring read must never park behind a device dispatch."""
        st = self._state
        if st is None:
            return -1
        return int(getattr(st, "last_shard_rebuild_us", -1))

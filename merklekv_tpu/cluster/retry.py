"""Unified retry/timeout/backoff policy for the cluster plane.

Before this module every component carried its own scattered constants:
transport heal loops hard-coded first/max backoff, the sync manager a bare
socket timeout, the health monitor its own probe timeout and failure
threshold, the replicator a fixed drain sleep. A partial failure then
behaved differently at every layer, and none of it was tunable or testable
as one model. "Asynchronous Merkle Trees" (PAPERS.md) argues correctness
under an adversarial scheduler; the chaos suite (tests/test_faults.py)
creates that adversary, and this policy object is the single knob the
stack answers it with.

Semantics:

- **Jittered capped exponential backoff** — delay_i = min(first * mult^i,
  max), +/- jitter fraction, drawn from a caller-supplied ``random.Random``
  so chaos tests stay deterministic under a fixed seed.
- **Per-operation deadline** — ``Deadline`` is a monotonic budget handed
  down a call chain; long multi-batch operations (anti-entropy repair)
  check it between batches and persist a resumable session instead of
  running unbounded.
- **Bounded attempts** — ``run()`` retries a callable under the policy;
  ``attempts`` caps the tries, ``deadline`` caps the wall clock, whichever
  binds first.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, replace
from typing import Callable, Iterator, Optional, TypeVar

__all__ = [
    "RetryPolicy",
    "Deadline",
    "TRANSPORT_HEAL",
    "SYNC_PEER",
    "HEALTH_PROBE",
    "REPLICATOR_PUBLISH",
    "BOOTSTRAP_FETCH",
    "SERVER_BUSY",
    "DEVICE_DISPATCH",
    "DEVICE_HEAL",
    "PARTITION_MOVED",
    "RETRYABLE_ERRORS",
    "ROUTED_RETRYABLE_ERRORS",
]

T = TypeVar("T")


class Deadline:
    """Monotonic time budget shared down a call chain. ``None`` seconds
    means unbounded (never expires)."""

    def __init__(self, seconds: Optional[float]) -> None:
        self._expires = (
            None if seconds is None else time.monotonic() + seconds
        )

    @classmethod
    def unbounded(cls) -> "Deadline":
        return cls(None)

    def remaining(self) -> Optional[float]:
        """Seconds left, or None when unbounded. Floors at 0.0."""
        if self._expires is None:
            return None
        return max(0.0, self._expires - time.monotonic())

    def expired(self) -> bool:
        return self._expires is not None and time.monotonic() >= self._expires

    def clamp(self, timeout: float) -> float:
        """A socket/op timeout no longer than the remaining budget."""
        rem = self.remaining()
        return timeout if rem is None else max(0.001, min(timeout, rem))


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered capped exponential backoff + attempt/deadline bounds.

    ``op_timeout`` is the per-network-operation (connect/recv) timeout the
    component should run with; ``op_deadline`` bounds one whole logical
    operation (e.g. one anti-entropy cycle against one peer), after which
    the operation must checkpoint/resume rather than keep running.
    """

    first_delay: float = 0.2
    max_delay: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.1  # +/- fraction of each delay
    attempts: Optional[int] = None  # None = unbounded retries
    op_timeout: float = 5.0
    op_deadline: Optional[float] = None  # None = unbounded

    def with_overrides(self, **kw) -> "RetryPolicy":
        return replace(self, **kw)

    def deadline(self) -> Deadline:
        return Deadline(self.op_deadline)

    def backoff(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Delay before retry ``attempt`` (0-based), jittered."""
        # Grow iteratively, stopping at the cap: ``multiplier ** attempt``
        # overflows to OverflowError near attempt=1024, and an unbounded
        # heal loop (broker down for hours) does reach such counts.
        base = self.first_delay
        for _ in range(attempt):
            if base >= self.max_delay:
                break
            base *= self.multiplier
        base = min(base, self.max_delay)
        if self.jitter <= 0:
            return base
        r = rng.random() if rng is not None else random.random()
        return max(0.0, base * (1.0 + self.jitter * (2.0 * r - 1.0)))

    def delays(self, rng: Optional[random.Random] = None) -> Iterator[float]:
        """Backoff sequence; finite iff ``attempts`` is set (yields
        attempts-1 delays — the first try is free)."""
        i = 0
        while self.attempts is None or i < self.attempts - 1:
            yield self.backoff(i, rng)
            i += 1

    def run(
        self,
        fn: Callable[[], T],
        retry_on: tuple[type[BaseException], ...] = (OSError,),
        should_stop: Optional[Callable[[], bool]] = None,
        rng: Optional[random.Random] = None,
        deadline: Optional[Deadline] = None,
    ) -> T:
        """Call ``fn`` under the policy; re-raise the last error once
        attempts/deadline are exhausted or ``should_stop()`` turns true."""
        if deadline is None:
            deadline = self.deadline()
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on:
                out_of_attempts = (
                    self.attempts is not None and attempt >= self.attempts - 1
                )
                if out_of_attempts or deadline.expired() or (
                    should_stop is not None and should_stop()
                ):
                    raise
                time.sleep(deadline.clamp(self.backoff(attempt, rng)))
                attempt += 1


# Shared defaults — every cluster component derives its constants from one
# of these instead of hard-coding its own (ISSUE 1 tentpole part 2).

# Broker-link healing: first retry almost immediately (broker restarts are
# usually fast), cap well below the anti-entropy interval so the fabric
# heals before the repair loop has to.
TRANSPORT_HEAL = RetryPolicy(
    first_delay=0.2, max_delay=5.0, jitter=0.1, op_timeout=5.0
)

# Anti-entropy per-peer work: a couple of quick connect retries, a bounded
# per-peer cycle budget; past the budget the cycle checkpoints a resumable
# session instead of blocking the loop.
SYNC_PEER = RetryPolicy(
    first_delay=0.1,
    max_delay=1.0,
    jitter=0.2,
    attempts=2,
    op_timeout=30.0,
    op_deadline=120.0,
)

# Failure-detector probes: short timeout, declared down after ``attempts``
# consecutive misses, probing at ``first_delay`` cadence.
HEALTH_PROBE = RetryPolicy(
    first_delay=2.0, max_delay=2.0, jitter=0.0, attempts=2, op_timeout=1.0
)

# Replication publish: QoS-0 by design — one near-immediate retry for a
# transient transport hiccup, then drop and count (anti-entropy repairs).
REPLICATOR_PUBLISH = RetryPolicy(
    first_delay=0.05, max_delay=0.1, jitter=0.5, attempts=2, op_timeout=5.0
)

# Bootstrap snapshot fetch: per-chunk retries ride this backoff (the chunk
# offset is the checkpoint — a retried chunk refetches only itself, never
# the verified prefix); op_deadline bounds one donor's whole transfer, past
# which the session fails over to the next donor.
BOOTSTRAP_FETCH = RetryPolicy(
    first_delay=0.1,
    max_delay=2.0,
    jitter=0.2,
    attempts=4,
    op_timeout=30.0,
    op_deadline=600.0,
)

# Device dispatch guard (merklekv_tpu.device.guard): ONE near-immediate
# retry when a device program call fails with an environment-classified
# error (backend RPC blip, transient tunnel reset) — a second failure
# escalates to the degradation ladder instead of retrying into a sick
# backend. Hangs are never retried: the abandoned executor already spent
# the dispatch deadline, and the pump's stall budget is the deadline, not
# a multiple of it.
DEVICE_DISPATCH = RetryPolicy(
    first_delay=0.05, max_delay=0.5, jitter=0.2, attempts=2, op_timeout=5.0
)

# Device-plane re-warm probe (degradation-ladder heal): escalating backoff
# between probes of a higher rung while the node serves from a degraded
# backend. First probe comes quickly (most faults are transient backend
# hiccups); a persistently sick device plane backs the probing off to once
# a minute so the probe dispatches themselves never become load.
DEVICE_HEAL = RetryPolicy(
    first_delay=2.0, max_delay=60.0, multiplier=2.0, jitter=0.2
)

# Overload shed (ERROR BUSY -> client.ServerBusyError): the server asked
# for backoff, so the first retry waits a real beat (not the near-
# immediate transport-hiccup retry) and the window stays bounded — a node
# still shedding after ~6 tries across a few seconds is genuinely
# overloaded, and the caller should surface that, not hammer it. NOT for
# ReadOnlyError: read-only means wait-for-recovery, and retrying it would
# just re-ask a node that already said it cannot.
SERVER_BUSY = RetryPolicy(
    first_delay=0.1, max_delay=1.0, jitter=0.3, attempts=6, op_timeout=5.0
)

# Stale partition map (ERROR MOVED -> client.MovedError): retry AFTER a
# map refresh + re-route, near-immediately — the condition heals the
# moment the fresh map arrives, and a handful of attempts bounds a
# cluster mid-rebalance. A caller that keeps getting MOVED past these
# attempts holds a map no reachable node agrees with — surface it.
# PartitionedClient implements this loop internally; use the policy for
# hand-rolled partition-aware callers.
PARTITION_MOVED = RetryPolicy(
    first_delay=0.05, max_delay=0.5, jitter=0.2, attempts=4, op_timeout=5.0
)


# The classification retry-driven callers pass as ``retry_on``: transient
# transport failures AND the server's explicit shed answer. ReadOnlyError
# is deliberately absent (see SERVER_BUSY above) — a read-only node asked
# callers to WAIT, not to hammer it.
from merklekv_tpu.client import MovedError, ServerBusyError  # noqa: E402
# (no cycle: client.py only lazy-imports cluster.partmap inside methods)

RETRYABLE_ERRORS: tuple[type[BaseException], ...] = (OSError, ServerBusyError)

# For PARTITION-AWARE callers only: MovedError is retryable *after a map
# refresh + re-route* — plain callers without a routing table would just
# re-ask the same node and collect the same refusal, so it is deliberately
# NOT in RETRYABLE_ERRORS.
ROUTED_RETRYABLE_ERRORS: tuple[type[BaseException], ...] = (
    RETRYABLE_ERRORS + (MovedError,)
)

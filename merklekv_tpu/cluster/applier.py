"""Pure LWW + idempotency application logic.

Mirrors the reference subscriber's rules (replication.rs:272-318) with the
deterministic tie-break from its LocalApplier double (change_event.rs:222-246):
  - drop events whose op_id was already applied (idempotency under QoS-1
    at-least-once delivery);
  - drop events older than the key's last applied ts (LWW);
  - on a ts tie, keep the lexicographically larger op_id (total order);
  - Del removes, everything else writes the post-op value.

Improvements over the reference: the reference's `seen`/`last_ts` maps grow
without bound and die with the process (replication.rs:277-278 TODO); here
the dedupe set is LRU-bounded, and when the store tracks per-key last-write
timestamps (``store_ts_fn``), the LWW floor is read from the STORE — so the
ordering survives an applier restart and agrees with anti-entropy repairs
instead of maintaining a second, divergent in-memory ordering.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind

__all__ = ["LWWApplier"]


class LWWApplier:
    """Applies ChangeEvents onto set/delete callables (engine-agnostic).

    Callables:
      set_fn(key, value)          — plain install (no ts tracking).
      del_fn(key)                 — plain delete.
      set_ts_fn(key, value, ts)   — install carrying the EVENT's ts; should
                                    be LWW-conditional (engine set_if_newer)
                                    when the store tracks timestamps.
      del_ts_fn(key, ts)          — delete carrying the event's ts (engine
                                    del_if_newer records the tombstone).
      store_ts_fn(key) -> int     — the store's authoritative last-write
                                    floor for a key: max(entry ts, tombstone
                                    ts), 0 if unknown. Consulted IN ADDITION
                                    to the in-memory map, so a restarted
                                    applier (empty maps) still rejects stale
                                    events against repaired/persisted state.
    """

    def __init__(
        self,
        set_fn: Callable[[bytes, bytes], None],
        del_fn: Callable[[bytes], None],
        max_seen: int = 1 << 20,
        set_ts_fn: Optional[Callable[[bytes, bytes, int], None]] = None,
        del_ts_fn: Optional[Callable[[bytes, int], None]] = None,
        store_ts_fn: Optional[Callable[[bytes], int]] = None,
    ) -> None:
        self._set = set_fn
        self._set_ts = set_ts_fn
        self._del = del_fn
        self._del_ts = del_ts_fn
        self._store_ts = store_ts_fn
        self._seen: OrderedDict[bytes, None] = OrderedDict()
        self._max_seen = max_seen
        self._last_ts: dict[str, int] = {}
        self._last_op_id: dict[str, bytes] = {}
        self.applied = 0
        self.skipped_dup = 0
        self.skipped_lww = 0

    def apply(self, ev: ChangeEvent) -> bool:
        """Apply one event; returns True if state changed."""
        if ev.op_id in self._seen:
            self.skipped_dup += 1
            return False
        key = ev.key.encode("utf-8")
        mem_ts = self._last_ts.get(ev.key, 0)
        last_ts = mem_ts
        if self._store_ts is not None:
            last_ts = max(last_ts, self._store_ts(key))
        if ev.ts < last_ts:
            self._remember(ev.op_id)
            self.skipped_lww += 1
            return False
        # op_id tie-break only against the in-memory record: the store
        # tracks timestamps, not op ids. After a restart an equal-ts event
        # re-applies — idempotent for redelivery, and cross-writer equal-ts
        # conflicts still converge through anti-entropy's digest tie-break.
        if ev.ts == mem_ts and ev.op_id < self._last_op_id.get(ev.key, b"\0" * 16):
            self._remember(ev.op_id)
            self.skipped_lww += 1
            return False

        if ev.op is OpKind.DEL:
            if self._del_ts is not None:
                self._del_ts(key, ev.ts)
            else:
                self._del(key)
        elif ev.val is not None:
            # Post-op value semantics: INCR/DECR/APPEND/PREPEND all apply as
            # an absolute SET of the result (change_event.rs:17-19).
            if self._set_ts is not None:
                self._set_ts(key, ev.val, ev.ts)
            else:
                self._set(key, ev.val)
        self._last_ts[ev.key] = ev.ts
        self._last_op_id[ev.key] = ev.op_id
        self._remember(ev.op_id)
        self.applied += 1
        return True

    def _remember(self, op_id: bytes) -> None:
        self._seen[op_id] = None
        if len(self._seen) > self._max_seen:
            self._seen.popitem(last=False)

    def last_ts(self, key: str) -> Optional[int]:
        return self._last_ts.get(key)

"""Pure LWW + idempotency application logic.

Mirrors the reference subscriber's rules (replication.rs:272-318) with the
deterministic tie-break from its LocalApplier double (change_event.rs:222-246):
  - drop events whose op_id was already applied (idempotency under QoS-1
    at-least-once delivery);
  - drop events older than the key's last applied ts (LWW);
  - on a ts tie, keep the lexicographically larger op_id (total order);
  - Del removes, everything else writes the post-op value.

Improvements over the reference: the reference's `seen`/`last_ts` maps grow
without bound and die with the process (replication.rs:277-278 TODO); here
the dedupe set is LRU-bounded, and when the store tracks per-key last-write
timestamps (``store_ts_fn``), the LWW floor is read from the STORE — so the
ordering survives an applier restart and agrees with anti-entropy repairs
instead of maintaining a second, divergent in-memory ordering.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind

__all__ = ["LWWApplier"]


class LWWApplier:
    """Applies ChangeEvents onto set/delete callables (engine-agnostic).

    Callables:
      set_fn(key, value)          — plain install (no ts tracking).
      del_fn(key)                 — plain delete.
      set_ts_fn(key, value, ts)   — install carrying the EVENT's ts; should
                                    be LWW-conditional (engine set_if_newer)
                                    when the store tracks timestamps, and
                                    return truthy iff state advanced.
      del_ts_fn(key, ts)          — delete carrying the event's ts (engine
                                    del_if_newer records the tombstone);
                                    returns truthy iff state advanced.
      store_ts_fn(key) -> int     — the store's authoritative last-write
                                    floor for a key: max(entry ts, tombstone
                                    ts), 0 if unknown. Consulted IN ADDITION
                                    to the in-memory map, so a restarted
                                    applier (empty maps) still rejects stale
                                    events against repaired/persisted state.
      apply_batch_fn(ops) -> flags — run a whole frame of LWW-conditional
                                    ops (``(key, value|None-for-del, ts)``)
                                    in ONE engine call, returning one
                                    applied flag per op. When wired,
                                    :meth:`apply_batch` crosses the FFI
                                    once per frame instead of once per
                                    event, and the engine (not a host-side
                                    ts floor) is the LWW authority.
    """

    def __init__(
        self,
        set_fn: Callable[[bytes, bytes], None],
        del_fn: Callable[[bytes], None],
        max_seen: int = 1 << 20,
        set_ts_fn: Optional[Callable[[bytes, bytes, int], None]] = None,
        del_ts_fn: Optional[Callable[[bytes, int], None]] = None,
        store_ts_fn: Optional[Callable[[bytes], int]] = None,
        apply_batch_fn: Optional[
            Callable[[list[tuple[bytes, Optional[bytes], int]]], list[bool]]
        ] = None,
    ) -> None:
        self._set = set_fn
        self._set_ts = set_ts_fn
        self._del = del_fn
        self._del_ts = del_ts_fn
        self._store_ts = store_ts_fn
        self._apply_batch_fn = apply_batch_fn
        self._seen: OrderedDict[bytes, None] = OrderedDict()
        self._max_seen = max_seen
        self._last_ts: dict[str, int] = {}
        self._last_op_id: dict[str, bytes] = {}
        self.applied = 0
        self.skipped_dup = 0
        self.skipped_lww = 0

    def apply(self, ev: ChangeEvent) -> bool:
        """Apply one event; returns True if state changed."""
        if ev.op_id in self._seen:
            self.skipped_dup += 1
            return False
        # surrogateescape round-trips keys that were decoded from non-UTF-8
        # wire bytes (replicator._to_event) — strict encoding would raise
        # and the transport callback guard would silently drop the event.
        key = ev.key.encode("utf-8", "surrogateescape")
        mem_ts = self._last_ts.get(ev.key, 0)
        last_ts = mem_ts
        if self._store_ts is not None:
            last_ts = max(last_ts, self._store_ts(key))
        if ev.ts < last_ts:
            self._remember(ev.op_id)
            self.skipped_lww += 1
            return False
        # Equal-ts arbitration: with engine-conditional ops wired
        # (set_ts_fn -> set_if_newer), the ENGINE breaks exact-ts ties by
        # value digest — a deterministic order that survives applier
        # restarts and matches anti-entropy's (ts, liveness, digest) rule,
        # so replication alone converges cross-writer equal-ts conflicts.
        # An in-memory op_id tie-break here would fight it: after a restart
        # (maps empty) replicas that applied in different orders would
        # disagree about which event "came first". Only the plain-callable
        # path (test doubles without ts tracking) keeps the op_id rule,
        # since a dict store has no digest arbitration of its own.
        if (
            self._set_ts is None
            and ev.ts == mem_ts
            and ev.op_id < self._last_op_id.get(ev.key, b"\0" * 16)
        ):
            self._remember(ev.op_id)
            self.skipped_lww += 1
            return False

        # The ts-carrying fns are LWW-conditional in the engine (set_if_newer
        # / del_if_newer) and report whether state actually advanced — an
        # equal-ts digest-losing SET or an already-covered DEL is a rejection
        # and must count as an LWW skip, not an apply. The plain callables
        # (dict-store doubles) apply unconditionally.
        changed = True
        if ev.op is OpKind.DEL:
            if self._del_ts is not None:
                changed = bool(self._del_ts(key, ev.ts))
            else:
                self._del(key)
        elif ev.val is not None:
            # Post-op value semantics: INCR/DECR/APPEND/PREPEND all apply as
            # an absolute SET of the result (change_event.rs:17-19).
            if self._set_ts is not None:
                changed = bool(self._set_ts(key, ev.val, ev.ts))
            else:
                self._set(key, ev.val)
        else:
            changed = False  # SET-like op with no value: nothing to install
        self._remember(ev.op_id)
        if not changed:
            self.skipped_lww += 1
            return False
        self._last_ts[ev.key] = ev.ts
        self._last_op_id[ev.key] = ev.op_id
        self.applied += 1
        return True

    def apply_batch(self, events: list[ChangeEvent]) -> list[ChangeEvent]:
        """Apply one decoded wire frame; returns the events that changed
        state (in frame order).

        With ``apply_batch_fn`` wired (the native engine's batched
        LWW-conditional call), all surviving ops cross the FFI ONCE —
        dedupe and the cheap in-memory ts floor still prefilter here, but
        the engine's conditional verbs are the LWW authority (a per-event
        ``store_ts_fn`` consult would reintroduce two FFI calls per event,
        and the engine rejects stale timestamps anyway). Without it, falls
        back to per-event :meth:`apply` (plain-callable test doubles).
        """
        if self._apply_batch_fn is None:
            return [ev for ev in events if self.apply(ev)]
        pending: list[ChangeEvent] = []
        ops: list[tuple[bytes, Optional[bytes], int]] = []
        batch_seen: set[bytes] = set()
        for ev in events:
            if ev.op_id in self._seen or ev.op_id in batch_seen:
                # _seen is only updated after the engine call, so a
                # duplicated op INSIDE one frame needs the batch-local set.
                self.skipped_dup += 1
                continue
            batch_seen.add(ev.op_id)
            if ev.ts < self._last_ts.get(ev.key, 0):
                self._remember(ev.op_id)
                self.skipped_lww += 1
                continue
            key = ev.key.encode("utf-8", "surrogateescape")
            if ev.op is OpKind.DEL:
                pending.append(ev)
                ops.append((key, None, ev.ts))
            elif ev.val is not None:
                pending.append(ev)
                ops.append((key, ev.val, ev.ts))
            else:  # SET-like op with no value: nothing to install
                self._remember(ev.op_id)
                self.skipped_lww += 1
        if not ops:
            return []
        flags = self._apply_batch_fn(ops)
        applied: list[ChangeEvent] = []
        for ev, flag in zip(pending, flags):
            self._remember(ev.op_id)
            if flag:
                self._last_ts[ev.key] = ev.ts
                self._last_op_id[ev.key] = ev.op_id
                self.applied += 1
                applied.append(ev)
            else:
                self.skipped_lww += 1
        return applied

    def _remember(self, op_id: bytes) -> None:
        self._seen[op_id] = None
        if len(self._seen) > self._max_seen:
            self._seen.popitem(last=False)

    def last_ts(self, key: str) -> Optional[int]:
        return self._last_ts.get(key)

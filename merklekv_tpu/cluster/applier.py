"""Pure LWW + idempotency application logic.

Mirrors the reference subscriber's rules (replication.rs:272-318) with the
deterministic tie-break from its LocalApplier double (change_event.rs:222-246):
  - drop events whose op_id was already applied (idempotency under QoS-1
    at-least-once delivery);
  - drop events older than the key's last applied ts (LWW);
  - on a ts tie, keep the lexicographically larger op_id (total order);
  - Del removes, everything else writes the post-op value.

Improvements over the reference: the reference's `seen`/`last_ts` maps grow
without bound and die with the process (replication.rs:277-278 TODO); here
the dedupe set is LRU-bounded.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind

__all__ = ["LWWApplier"]


class LWWApplier:
    """Applies ChangeEvents onto set/delete callables (engine-agnostic)."""

    def __init__(
        self,
        set_fn: Callable[[bytes, bytes], None],
        del_fn: Callable[[bytes], None],
        max_seen: int = 1 << 20,
        set_ts_fn: Optional[Callable[[bytes, bytes, int], None]] = None,
    ) -> None:
        self._set = set_fn
        # When the store tracks per-key last-write timestamps, applies go
        # through set_ts_fn with the EVENT's ts so anti-entropy LWW and
        # replication LWW agree on ordering.
        self._set_ts = set_ts_fn
        self._del = del_fn
        self._seen: OrderedDict[bytes, None] = OrderedDict()
        self._max_seen = max_seen
        self._last_ts: dict[str, int] = {}
        self._last_op_id: dict[str, bytes] = {}
        self.applied = 0
        self.skipped_dup = 0
        self.skipped_lww = 0

    def apply(self, ev: ChangeEvent) -> bool:
        """Apply one event; returns True if state changed."""
        if ev.op_id in self._seen:
            self.skipped_dup += 1
            return False
        last_ts = self._last_ts.get(ev.key, 0)
        if ev.ts < last_ts:
            self._remember(ev.op_id)
            self.skipped_lww += 1
            return False
        if ev.ts == last_ts and ev.op_id < self._last_op_id.get(ev.key, b"\0" * 16):
            self._remember(ev.op_id)
            self.skipped_lww += 1
            return False

        key = ev.key.encode("utf-8")
        if ev.op is OpKind.DEL:
            self._del(key)
        elif ev.val is not None:
            # Post-op value semantics: INCR/DECR/APPEND/PREPEND all apply as
            # an absolute SET of the result (change_event.rs:17-19).
            if self._set_ts is not None:
                self._set_ts(key, ev.val, ev.ts)
            else:
                self._set(key, ev.val)
        self._last_ts[ev.key] = ev.ts
        self._last_op_id[ev.key] = ev.op_id
        self._remember(ev.op_id)
        self.applied += 1
        return True

    def _remember(self, op_id: bytes) -> None:
        self._seen[op_id] = None
        if len(self._seen) > self._max_seen:
            self._seen.popitem(last=False)

    def last_ts(self, key: str) -> Optional[int]:
        return self._last_ts.get(key)

"""Live partition rebalancing: epoch-bumped online resharding.

One RebalanceManager rides every ClusterNode and plays whichever role the
wire hands it:

- **donor** — the node serving partition p receives ``REBALANCE SPLIT``
  and drives the whole session: conscript the joiner, double-apply live
  moving-range writes onto the joiner's replication topic, stream a
  Merkle-stamped snapshot over the existing SNAPMETA/SNAPCHUNK path,
  fence the moving range, verify the joiner's root bit-for-bit against
  its own range root, persist map epoch E+1 (THE commit point), flip,
  and drop the moved range behind the new guard.
- **joiner** — a reserve node receives ``REBALANCE JOIN``: it subscribes
  to its future partition topic with applies held (journal-but-buffer),
  fetches + verifies the donor snapshot, installs the moving-range subset,
  releases the held forward stream, and serves its root for verification
  until COMMIT opens the serving gate.
- **sibling** — the donor's replica-group peers take ``REBALANCE FENCE``
  (TTL-guarded write fence over the moving cell) and ``REBALANCE
  COMMIT``/``ABORT``. On commit a sibling sweep-forwards its moved-range
  residue to the joiner (closing the QoS-0 window where a replication
  frame from sibling to donor was dropped mid-transfer) before dropping
  the range.

Crash containment (docs/FAULT_MODEL.md "Mid-rebalance kill windows"):
the epoch flip is exactly as atomic as ``partmap.save_map_file``'s
rename. A donor killed before it restarts at epoch E and the session
evaporates — sibling fences expire on their TTL, and the joiner's
resolve loop polls the donor's PARTMAP, sees epoch E, and wipes itself
back to reserve (full rollback). A donor killed after it restarts at
E+1 from the persisted map (boot foreign-key sweep drops the moved
range), the joiner's resolve loop sees epoch >= pending and
self-commits, and sibling fence-expiry probes adopt the newer map (full
roll-forward). A joiner killed mid-transfer fails the donor's poll
budget; the donor — which served reads AND non-moving writes throughout
— aborts, disarms everything, and stays at epoch E.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from merklekv_tpu.client import (
    ChunkIntegrityError,
    ConnectionError as ClientConnectionError,
    MerkleKVClient,
    MerkleKVError,
    ProtocolError,
)
from merklekv_tpu.cluster.change_event import ChangeEvent, OpKind
from merklekv_tpu.cluster.partmap import (
    PartitionMap,
    PartitionMapError,
    format_map_spec,
    key_in_range,
    parse_map_spec,
)
from merklekv_tpu.cluster.retry import BOOTSTRAP_FETCH
from merklekv_tpu.obs.flightrec import record as flight_record
from merklekv_tpu.storage import snapshot as snapmod
from merklekv_tpu.utils.tracing import get_metrics

__all__ = ["RebalanceManager", "STATE_CODES", "main"]

# rebalance.state gauge codes: donor phases count up 1..7, joiner phases
# live in the 10s, and every terminal failure mode is negative — a fleet
# scrape can tell "mid-flip" (3-5) from "transfer grinding" (2) from
# "rolled back" (<0) without reading logs.
STATE_CODES = {
    "idle": 0,
    "conscribe": 1,
    "transfer": 2,
    "fence": 3,
    "verify": 4,
    "commit": 5,
    "drop": 6,
    "done": 7,
    "joining": 10,
    "join_fetch": 11,
    "join_live": 12,
    "join_committed": 13,
    "failed": -1,
    "aborted": -2,
    "join_aborted": -3,
}

# Donor-side poll cadence against the joiner, and the session heartbeat
# interval (snapshot pin refresh + progress flight marks) derived from it.
_POLL_S = 0.25
_HEARTBEAT_EVERY = 4  # polls per heartbeat (~1 s)
# Whole-transfer budget: past this the donor aborts (the joiner is dead,
# wedged, or the link is unusable) — the donor served throughout, so the
# cost of an abort is one wasted transfer, never availability.
TRANSFER_DEADLINE_S = 600.0
# Consecutive failed joiner polls before the donor declares it dead.
_POLL_FAILURE_BUDGET = 20
# Post-fence verification: bounded retries while in-flight frames settle.
_VERIFY_ATTEMPTS = 60
# Sibling write-fence TTL: a donor death leaves fences armed, so they
# self-expire (restoring write availability) and probe the donor's epoch
# to decide rollback vs roll-forward.
FENCE_TTL_MS = 30_000
# Joiner resolve budget after losing the donor: poll the donor's PARTMAP
# this long for a commit/rollback verdict before assuming rollback.
_JOIN_RESOLVE_S = float(os.environ.get("MERKLEKV_REBALANCE_RESOLVE_S", 120.0))
# Chunk size + per-chunk pause for the joiner's snapshot fetch. The env
# overrides exist for spawned-process chaos drills (which cannot
# monkeypatch module globals): shrinking the chunk and adding a pause
# holds the transfer window open long enough to kill -9 a side
# mid-stream deterministically.
_SNAP_CHUNK = int(os.environ.get("MERKLEKV_REBALANCE_CHUNK_BYTES", 256 * 1024))
_FETCH_PAUSE_S = float(os.environ.get("MERKLEKV_REBALANCE_FETCH_PAUSE_S", 0.0))
_APPLY_SLAB = 8192


def _range_root_hex(items: list[tuple[bytes, bytes]]) -> str:
    """Merkle root over sorted (key, value) pairs, pinned to the CPU
    builder: donor and joiner must compute bit-identical roots for the
    flip gate, so neither side may take the device path (whose
    availability can differ per node)."""
    return snapmod.compute_root_hex(items, engine="cpu")


class RebalanceManager:
    """Per-node rebalance state machine; see module docstring for roles."""

    def __init__(self, node) -> None:
        self._node = node
        self._mu = threading.Lock()
        self._state = "idle"
        self._detail = ""
        self._pending: Optional[PartitionMap] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # sibling fence watchdog
        self._fence_epoch = 0
        self._fence_deadline = 0.0
        self._fence_thread: Optional[threading.Thread] = None
        # joiner session
        self._donor_addr = ""
        self._newpid: Optional[int] = None

    # -- introspection -----------------------------------------------------
    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    def state_code(self) -> int:
        return STATE_CODES.get(self.state, 0)

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
        f = self._fence_thread
        if f is not None:
            f.join(timeout=2)

    def _set_state(self, state: str, detail: str = "") -> None:
        with self._mu:
            self._state = state
            self._detail = detail
        get_metrics().inc(f"rebalance.phase.{state}")
        flight_record("rebalance_phase", phase=state, detail=detail[:120])

    # -- wire dispatch -----------------------------------------------------
    def handle(self, parts: list[str]) -> str:
        """One REBALANCE wire exchange; parts is the tokenized tail after
        the verb. Every malformed request answers ERROR (single line) —
        never an exception into the native dispatch path."""
        if not parts:
            return "ERROR rebalance: missing subcommand\r\n"
        sub = parts[0].upper()
        try:
            if sub == "SPLIT":
                return self._wire_split(parts[1:])
            if sub == "JOIN":
                return self._wire_join(parts[1:])
            if sub == "STATUS":
                return self._wire_status()
            if sub == "FENCE":
                return self._wire_fence(parts[1:])
            if sub == "COMMIT":
                return self._wire_commit(parts[1:])
            if sub == "ABORT":
                return self._wire_abort(parts[1:])
        except (ValueError, PartitionMapError, IndexError) as e:
            return f"ERROR rebalance: {e}\r\n"
        return f"ERROR rebalance: unknown subcommand {parts[0]}\r\n"

    # -- SPLIT (donor) -----------------------------------------------------
    def _wire_split(self, args: list[str]) -> str:
        if len(args) != 3:
            return (
                "ERROR rebalance: SPLIT requires <partition> <epoch> "
                "<replicas>\r\n"
            )
        pid, epoch = int(args[0]), int(args[1])
        replicas = [a.strip() for a in args[2].split(",") if a.strip()]
        node = self._node
        if node._partmap is None:
            return "ERROR rebalance: node is not partitioned\r\n"
        if pid != node._partition_id:
            return (
                f"ERROR rebalance: this node serves partition "
                f"{node._partition_id}, not {pid} (send SPLIT to the "
                "donor)\r\n"
            )
        if epoch != node._partmap.epoch:
            return (
                f"ERROR rebalance: stale epoch {epoch} "
                f"(current {node._partmap.epoch})\r\n"
            )
        if node._storage is None:
            return "ERROR rebalance: donor requires durable storage\r\n"
        if node.replicator is None:
            return "ERROR rebalance: donor requires live replication\r\n"
        if not replicas:
            return "ERROR rebalance: no replicas for the new partition\r\n"
        with self._mu:
            if self._thread is not None and self._thread.is_alive():
                return (
                    f"ERROR rebalance: session already active "
                    f"({self._state})\r\n"
                )
            pending = node._partmap.split(pid, replicas)  # validates
            self._pending = pending
            self._state = "conscribe"
            self._detail = ""
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._run_split,
                args=(node._partmap, pending, pid),
                daemon=True,
                name="mkv-rebalance-donor",
            )
            self._thread.start()
        newpid = pending.count - 1
        return f"OK rebalance started {newpid} {pending.epoch}\r\n"

    def _self_addr(self) -> str:
        host = self._node._cfg.host
        if host in ("", "0.0.0.0", "::"):
            host = "127.0.0.1"
        return f"{host}:{self._node._server.port}"

    def _is_self_addr(self, addr: str) -> bool:
        host, _, port = addr.rpartition(":")
        if port != str(self._node._server.port):
            return False
        cfg_host = self._node._cfg.host
        return host == cfg_host or cfg_host in ("0.0.0.0", "::", "")

    def _joiner_topic(self, newpid: int) -> str:
        prefix = self._node._cfg.replication.topic_prefix
        return f"{prefix}/p{newpid}/events"

    def _run_split(
        self, current: PartitionMap, pending: PartitionMap, pid: int
    ) -> None:
        node = self._node
        newpid = pending.count - 1
        moving = current.moving_range(pid)  # == pending's cell for newpid
        joiner = pending.replicas[newpid][0]
        siblings = [
            a for a in current.replicas[pid] if not self._is_self_addr(a)
        ]
        flight_record(
            "rebalance_start",
            partition=pid,
            new_partition=newpid,
            epoch=pending.epoch,
            joiner=joiner,
        )
        fenced = False
        try:
            # 1. Conscribe FIRST: the joiner must be subscribed (applies
            # held, frames journaled) before the forward arms, and the
            # forward must arm before the snapshot is cut — every write
            # lands in the snapshot, the held stream, or both (LWW makes
            # the overlap idempotent); none can fall between.
            self._set_state("conscribe", joiner)
            self._rpc(
                joiner,
                "JOIN "
                f"{pending.epoch} {pending.count} {newpid} "
                f"{self._self_addr()} {format_map_spec(pending)}",
            )
            rep = node.replicator
            if rep is None:
                raise RuntimeError("replication disabled mid-session")
            topic = self._joiner_topic(newpid)
            base, root, depth, path = moving
            rep.set_range_forward(
                topic, lambda k: key_in_range(k, base, root, depth, path)
            )
            # 2. Fresh snapshot AFTER the forward armed: its state plus
            # the forward stream covers the full write history.
            node._storage.snapshot_now()
            meta = node._storage.donor_meta()
            pinned = meta[0] if isinstance(meta, tuple) else None
            # 3. Transfer: the joiner fetches at its own pace; heartbeat
            # the snapshot pins so a throttled transfer can outlive the
            # 120 s pin TTL (the PR's donor-pin-lifetime fix).
            self._set_state("transfer", f"snapshot {pinned}")
            self._wait_joiner_live(joiner)
            # 4. Fence the moving cell on every replica of p — writes to
            # moving keys answer the retryable BUSY while reads keep
            # serving; non-moving writes are untouched.
            self._set_state("fence")
            flight_record("rebalance_fence", partition=pid)
            node._server.set_partition_fence(base, root, depth, path)
            fenced = True
            for addr in siblings:
                self._rpc(
                    addr,
                    f"FENCE {pending.epoch} {base} {root} {depth} {path} "
                    f"{FENCE_TTL_MS}",
                    ignore_errors=True,
                )
            # 5. Verify: donor's reference root over the moving range must
            # match the joiner's whole-engine root bit-for-bit.
            self._set_state("verify")
            self._verify_roots(joiner, moving)
            # 6. COMMIT POINT: persist E+1. Everything before this rolls
            # back on a donor kill; everything after rolls forward.
            self._set_state("commit")
            node.adopt_partition_map(pending)
            node._server.clear_partition_fence()
            fenced = False
            flight_record(
                "rebalance_commit", partition=pid, epoch=pending.epoch
            )
            commit_cmd = (
                f"COMMIT {pending.epoch} {pending.count} "
                f"{format_map_spec(pending)}"
            )
            self._rpc(joiner, commit_cmd, ignore_errors=True)
            for addr in siblings:
                self._rpc(addr, commit_cmd, ignore_errors=True)
            # 7. Drop the moved range behind the new guard (which already
            # answers MOVED for it — the quiet delete can never race a
            # resurrecting write).
            self._set_state("drop")
            rep.clear_range_forward()
            self._drop_moved_range(moving, sweep_to=None)
            self._set_state("done")
            flight_record(
                "rebalance_done", partition=pid, epoch=pending.epoch
            )
            get_metrics().inc("rebalance.splits_completed")
        except Exception as e:
            self._abort_split(
                reason=str(e),
                fenced=fenced,
                siblings=siblings,
                joiner=joiner,
                epoch=pending.epoch,
            )
        finally:
            with self._mu:
                self._pending = None

    def _rpc(
        self, addr: str, subcommand: str, ignore_errors: bool = False
    ) -> Optional[str]:
        host, _, port = addr.rpartition(":")
        try:
            with MerkleKVClient(host, int(port), timeout=5.0) as c:
                return c.rebalance(subcommand)
        except (MerkleKVError, OSError, ValueError):
            if ignore_errors:
                # COMMIT/ABORT fan-out is best-effort by design: a dead
                # sibling heals through its fence TTL probe (or the boot
                # sweep), a dead joiner through its resolve loop.
                get_metrics().inc("rebalance.rpc_errors")
                return None
            raise

    def _poll_status(self, addr: str) -> tuple[str, int, str]:
        """One REBALANCE STATUS exchange -> (state, epoch, root_hex)."""
        resp = self._rpc(addr, "STATUS")
        fields = (resp or "").split(" ")
        if len(fields) != 4 or fields[0] != "REBALSTATUS":
            raise ProtocolError(f"malformed REBALSTATUS: {resp!r}")
        try:
            epoch = int(fields[2])
        except ValueError:
            raise ProtocolError(f"malformed REBALSTATUS: {resp!r}") from None
        return fields[1], epoch, fields[3]

    def _wait_joiner_live(self, joiner: str) -> None:
        deadline = time.monotonic() + TRANSFER_DEADLINE_S
        failures = 0
        polls = 0
        while True:
            if self._stop_evt.is_set():
                raise RuntimeError("node stopping")
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"transfer deadline ({TRANSFER_DEADLINE_S:.0f}s) "
                    "exceeded"
                )
            try:
                state, _, _ = self._poll_status(joiner)
                failures = 0
            except (MerkleKVError, OSError) as e:
                failures += 1
                if failures >= _POLL_FAILURE_BUDGET:
                    raise RuntimeError(f"joiner unreachable: {e}")
                time.sleep(_POLL_S)
                continue
            if state == "join_live":
                return
            if state in ("join_aborted", "idle", "failed"):
                raise RuntimeError(f"joiner gave up (state {state})")
            polls += 1
            if polls % _HEARTBEAT_EVERY == 0:
                # Session heartbeat: keep every donor snapshot artifact
                # pinned while the transfer is alive, however slowly the
                # joiner pulls chunks.
                node = self._node
                if node._storage is not None:
                    node._storage.refresh_pin()
            time.sleep(_POLL_S)

    def _moving_items(
        self, moving: tuple[int, int, int, int]
    ) -> list[tuple[bytes, bytes]]:
        base, root, depth, path = moving
        return [
            (k, v)
            for k, v in self._node._engine.snapshot()
            if key_in_range(k, base, root, depth, path)
        ]

    def _verify_roots(
        self, joiner: str, moving: tuple[int, int, int, int]
    ) -> None:
        """Post-fence flip gate: flush the forward stream, then compare
        the donor's moving-range reference root with the joiner's engine
        root until they are bit-identical (bounded retries let in-flight
        frames settle). Equality is the zero-loss proof: the joiner holds
        exactly the donor's moving keys, bit for bit."""
        rep = self._node.replicator
        last = ("", "")
        for attempt in range(_VERIFY_ATTEMPTS):
            if self._stop_evt.is_set():
                raise RuntimeError("node stopping")
            if rep is not None:
                rep.flush()
            mine = _range_root_hex(self._moving_items(moving))
            _, _, theirs = self._poll_status(joiner)
            if mine == theirs:
                flight_record(
                    "rebalance_verified", root=mine[:16], attempts=attempt + 1
                )
                return
            last = (mine, theirs)
            time.sleep(_POLL_S)
        raise RuntimeError(
            f"range roots diverged after {_VERIFY_ATTEMPTS} attempts "
            f"(donor {last[0][:16]} joiner {last[1][:16]})"
        )

    def _drop_moved_range(
        self,
        moving: tuple[int, int, int, int],
        sweep_to: Optional[str],
    ) -> int:
        """Drop every moved-range key (quiet deletes: no replication echo,
        no WAL churn — the new guard plus the boot-time sweep make the
        range unreachable). When ``sweep_to`` names the joiner's topic,
        first forward the residue at its stored timestamps: that closes
        the window where a sibling held a moving-range write the donor's
        double-apply never saw (a QoS-0 frame drop mid-transfer)."""
        node = self._node
        engine = node._engine
        base, root, depth, path = moving
        items = self._moving_items(moving)
        rep = node.replicator
        if sweep_to is not None and rep is not None and items:
            ts_map = dict(engine.key_timestamps())
            events = [
                ChangeEvent(
                    op=OpKind.SET,
                    key=k.decode("utf-8", "surrogateescape"),
                    val=v,
                    ts=ts_map.get(k, 0),
                    src=rep.node_id,
                )
                for k, v in items
            ]
            events += [
                ChangeEvent(
                    op=OpKind.DEL,
                    key=k.decode("utf-8", "surrogateescape"),
                    val=None,
                    ts=ts,
                    src=rep.node_id,
                )
                for k, ts in engine.tombstones()
                if key_in_range(k, base, root, depth, path)
            ]
            rep.forward_events(sweep_to, events)
            get_metrics().inc("rebalance.swept_events", len(events))
        dropped = 0
        pairs = []
        for k, _ in items:
            if engine.delete_quiet(k):
                dropped += 1
                pairs.append((k, None))
        with node._rep_mu:
            mirror = node._mirror
        if mirror is not None and pairs:
            # Quiet deletes bypass the event queue — tell the device
            # mirror directly so HASH stays truthful post-flip.
            mirror.apply_batch(pairs)
        if node._storage is not None:
            node._storage.request_snapshot()
        get_metrics().inc("rebalance.keys_dropped", dropped)
        flight_record("rebalance_dropped", keys=dropped)
        return dropped

    def _abort_split(
        self,
        reason: str,
        fenced: bool,
        siblings: list[str],
        joiner: str,
        epoch: int,
    ) -> None:
        node = self._node
        rep = node.replicator
        if rep is not None:
            rep.clear_range_forward()
        if fenced:
            node._server.clear_partition_fence()
        for addr in siblings:
            self._rpc(addr, f"ABORT {epoch}", ignore_errors=True)
        self._rpc(joiner, f"ABORT {epoch}", ignore_errors=True)
        self._set_state("failed", reason)
        flight_record("rebalance_abort", reason=reason[:160], epoch=epoch)
        get_metrics().inc("rebalance.splits_aborted")

    # -- JOIN (joiner) -----------------------------------------------------
    def _wire_join(self, args: list[str]) -> str:
        if len(args) != 5:
            return (
                "ERROR rebalance: JOIN requires <epoch> <count> <pid> "
                "<donor> <mapspec>\r\n"
            )
        epoch, count, newpid = int(args[0]), int(args[1]), int(args[2])
        donor, mapspec = args[3], args[4]
        node = self._node
        if node._partmap is not None:
            return (
                "ERROR rebalance: node already serves partition "
                f"{node._partition_id} (joiners must be reserve nodes)\r\n"
            )
        with self._mu:
            if self._thread is not None and self._thread.is_alive():
                return (
                    f"ERROR rebalance: session already active "
                    f"({self._state})\r\n"
                )
        pending = parse_map_spec(mapspec, count, epoch)
        if not 0 <= newpid < pending.count:
            return f"ERROR rebalance: pid {newpid} out of range\r\n"
        if not any(
            self._is_self_addr(a) for a in pending.replicas[newpid]
        ):
            return (
                "ERROR rebalance: this node is not a replica of "
                f"partition {newpid} in the offered map\r\n"
            )
        # Idempotent conscription: a reserve re-joining after a crashed
        # attempt wipes its leftovers. TRUNCATE journals, so a joiner
        # restart mid-join recovers empty too.
        node._engine.truncate()
        node._server.set_serving(False)
        node._partmap = pending
        node._partition_id = newpid
        node._install_partition_guard()
        err = node._enable_replication()
        if err is not None:
            # Undo conscription: without the forward stream the transfer
            # cannot be gap-free.
            self._reset_to_reserve()
            return f"ERROR rebalance: {err}\r\n"
        rep = node.replicator
        rep.hold_applies()
        with self._mu:
            self._donor_addr = donor
            self._newpid = newpid
            self._pending = pending
            self._state = "joining"
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._run_join,
                args=(pending, newpid, donor),
                daemon=True,
                name="mkv-rebalance-joiner",
            )
            self._thread.start()
        flight_record(
            "rebalance_join", partition=newpid, epoch=epoch, donor=donor
        )
        return "OK joining\r\n"

    def _reset_to_reserve(self) -> None:
        """Wipe conscripted state back to an idle reserve node."""
        node = self._node
        node._disable_replication()
        node._engine.truncate()
        node._partmap = None
        node._partition_id = None
        node._server.set_partition(0, 0, 0)
        node._server.set_serving(True)

    def _run_join(
        self, pending: PartitionMap, newpid: int, donor: str
    ) -> None:
        node = self._node
        moving = (pending.hash_base, *pending.assignment(newpid))
        try:
            self._set_state("join_fetch", donor)
            blob, root_hex = self._fetch_snapshot(donor)
            snap = snapmod.parse_snapshot_bytes(blob)
            if snap.root_hex != root_hex:
                raise snapmod.SnapshotCorruptError(
                    "stamped root changed mid-transfer"
                )
            snapmod.verify_snapshot(snap, engine="cpu")
            self._install_filtered(snap, moving)
            rep = node.replicator
            if rep is not None:
                rep.release_applies()
            self._set_state("join_live")
            flight_record("rebalance_join_live", partition=newpid)
            # Stay resident watching the donor: COMMIT/ABORT normally
            # arrives over the wire; if the donor dies instead, its
            # restarted PARTMAP epoch is the verdict.
            self._watch_donor(pending, newpid, donor)
        except Exception as e:
            if self.state not in ("join_committed", "done"):
                self._abort_join(str(e))

    def _fetch_snapshot(self, donor: str) -> tuple[bytes, str]:
        """SNAPMETA/SNAPCHUNK fetch loop against the donor (the PR-6
        bootstrap path's wire, reused verbatim): per-offset retries under
        BOOTSTRAP_FETCH, reconnect on transport death, integrity enforced
        per chunk by the client and end-to-end by the stamped root."""
        host, _, port = donor.rpartition(":")
        policy = BOOTSTRAP_FETCH
        deadline = time.monotonic() + TRANSFER_DEADLINE_S
        client: Optional[MerkleKVClient] = None

        def connect() -> MerkleKVClient:
            return MerkleKVClient(
                host, int(port), timeout=policy.op_timeout
            ).connect()

        try:
            client = connect()
            # Donor freshness gate: wait out the donor's conscribe phase
            # (its post-forward-arm snapshot) so we never ship an
            # artifact cut before the double-apply armed.
            while True:
                if time.monotonic() >= deadline:
                    raise RuntimeError("donor never reached transfer phase")
                state, _, _ = self._poll_status(donor)
                if state in ("transfer", "fence", "verify"):
                    break
                if state in ("failed", "aborted", "idle", "done"):
                    raise RuntimeError(f"donor session gone (state {state})")
                time.sleep(_POLL_S)
            while True:
                try:
                    seq, _, size, root_hex = client.snap_meta()
                    break
                except ProtocolError as e:
                    if "retry" not in str(e):
                        raise
                    if time.monotonic() >= deadline:
                        raise RuntimeError("donor snapshot never built")
                    time.sleep(_POLL_S)
            chunks: list[bytes] = []
            offset = 0
            while offset < size:
                if self._stop_evt.is_set():
                    raise RuntimeError("node stopping")
                if time.monotonic() >= deadline:
                    raise RuntimeError("transfer deadline exceeded")
                for attempt in range(policy.attempts or 1):
                    try:
                        raw = client.snap_chunk(seq, offset, _SNAP_CHUNK)
                        break
                    except (
                        ClientConnectionError,
                        ChunkIntegrityError,
                        OSError,
                    ):
                        if attempt + 1 >= (policy.attempts or 1):
                            raise
                        try:
                            client.close()
                        except Exception:
                            pass
                        time.sleep(policy.backoff(attempt))
                        client = connect()
                if not raw:
                    raise RuntimeError(
                        f"snapshot {seq} truncated at {offset}/{size}"
                    )
                chunks.append(raw)
                offset += len(raw)
                get_metrics().inc("rebalance.fetch_bytes", len(raw))
                if _FETCH_PAUSE_S:
                    time.sleep(_FETCH_PAUSE_S)
            return b"".join(chunks), root_hex
        finally:
            if client is not None:
                client.close()

    def _install_filtered(
        self, snap, moving: tuple[int, int, int, int]
    ) -> None:
        """Apply the moving-range subset of a VERIFIED donor snapshot:
        sets and tombstones at their exact stamped timestamps, in slabs,
        feeding the mirror + WAL through the same hook bootstrap uses."""
        node = self._node
        base, root, depth, path = moving
        triples = [
            (k, v, ts)
            for k, v, ts in snap.items
            if key_in_range(k, base, root, depth, path)
        ] + [
            (k, None, ts)
            for k, ts in snap.tombstones
            if key_in_range(k, base, root, depth, path)
        ]
        installed = 0
        for i in range(0, len(triples), _APPLY_SLAB):
            slab = triples[i : i + _APPLY_SLAB]
            node._engine.apply_batch(slab)
            node._on_bootstrap_applied(slab)
            installed += len(slab)
        get_metrics().inc("rebalance.keys_installed", installed)
        flight_record("rebalance_installed", keys=installed)

    def _wire_status(self) -> str:
        with self._mu:
            state = self._state
            pending = self._pending
        epoch = (
            pending.epoch
            if pending is not None
            else (
                self._node._partmap.epoch
                if self._node._partmap is not None
                else 0
            )
        )
        root = "-"
        if state == "join_live":
            # The joiner's whole engine IS the moving range: its root is
            # the donor's flip gate. CPU-pinned to match the donor's
            # reference computation bit for bit.
            root = _range_root_hex(self._node._engine.snapshot())
        return f"REBALSTATUS {state} {epoch} {root}\r\n"

    # Donor-role phases that mean "session still running — keep waiting".
    _ACTIVE_DONOR_STATES = frozenset(
        ("conscribe", "transfer", "fence", "verify", "commit", "drop")
    )

    def _watch_donor(
        self, pending: PartitionMap, newpid: int, donor: str
    ) -> None:
        """join_live residency: normally COMMIT/ABORT arrives over the
        wire. If the donor dies instead, its restarted state is the
        verdict — REBALSTATUS epoch >= pending (or phase ``done``) means
        the flip persisted before the death: roll forward (self-commit).
        An idle/failed donor still at the old epoch means the session
        evaporated: roll back to reserve (self-abort). Silence past the
        resolve budget is treated as rollback — the conservative verdict,
        since a commit the joiner misses only costs a re-run while a
        phantom commit would double-own the range."""
        host, _, port = donor.rpartition(":")
        unreachable_since: Optional[float] = None
        while not self._stop_evt.is_set():
            if self.state != "join_live":
                return  # COMMIT/ABORT arrived over the wire
            try:
                dstate, depoch, _ = self._poll_status(donor)
                unreachable_since = None
                if dstate in self._ACTIVE_DONOR_STATES:
                    # Mid-session the donor's STATUS carries the PENDING
                    # epoch — not a commit signal. Checked first, or the
                    # joiner would self-commit before verification.
                    pass
                elif dstate == "done" or depoch >= pending.epoch:
                    # The donor persisted the flip but its COMMIT
                    # broadcast never reached us: roll forward.
                    self._commit_join(pending, depoch)
                    return
                else:
                    # Reachable, not mid-session, old epoch: the session
                    # is gone (abort, or a crash-restart at E).
                    self._abort_join(
                        f"donor session gone (state {dstate}, "
                        f"epoch {depoch})"
                    )
                    return
            except (MerkleKVError, OSError, ValueError):
                now = time.monotonic()
                if unreachable_since is None:
                    unreachable_since = now
                elif now - unreachable_since > _JOIN_RESOLVE_S:
                    self._abort_join("donor unreachable past resolve budget")
                    return
            time.sleep(_POLL_S * 4)

    def _commit_join(self, pending: PartitionMap, epoch: int) -> None:
        node = self._node
        with self._mu:
            if self._state == "join_committed":
                return
        node.adopt_partition_map(pending)
        node._server.set_serving(True)
        self._set_state("join_committed")
        flight_record(
            "rebalance_join_commit",
            partition=node._partition_id,
            epoch=epoch,
        )
        get_metrics().inc("rebalance.joins_committed")

    def _abort_join(self, reason: str) -> None:
        self._reset_to_reserve()
        self._set_state("join_aborted", reason)
        flight_record("rebalance_join_abort", reason=reason[:160])
        get_metrics().inc("rebalance.joins_aborted")

    # -- FENCE / COMMIT / ABORT (sibling + joiner wire side) ---------------
    def _wire_fence(self, args: list[str]) -> str:
        if len(args) != 6:
            return (
                "ERROR rebalance: FENCE requires <epoch> <base> <root> "
                "<depth> <path> <ttl_ms>\r\n"
            )
        epoch = int(args[0])
        base, root, depth = int(args[1]), int(args[2]), int(args[3])
        path, ttl_ms = int(args[4]), int(args[5])
        node = self._node
        if node._partmap is None:
            return "ERROR rebalance: node is not partitioned\r\n"
        if epoch != node._partmap.epoch + 1:
            return (
                f"ERROR rebalance: fence epoch {epoch} does not extend "
                f"current {node._partmap.epoch}\r\n"
            )
        node._server.set_partition_fence(base, root, depth, path)
        with self._mu:
            self._fence_epoch = epoch
            self._fence_deadline = time.monotonic() + ttl_ms / 1000.0
            if self._fence_thread is None or not self._fence_thread.is_alive():
                self._fence_thread = threading.Thread(
                    target=self._fence_watchdog,
                    daemon=True,
                    name="mkv-rebalance-fence",
                )
                self._fence_thread.start()
        flight_record("rebalance_fenced", epoch=epoch, ttl_ms=ttl_ms)
        return "OK fenced\r\n"

    def _fence_watchdog(self) -> None:
        """Sibling-side fence TTL: a donor death must not leave moving-
        range writes refused forever. On expiry, clear the fence and probe
        the donor group's epoch — adopt a newer committed map (roll
        forward: sweep + drop) or stand down at the current one (the
        rollback)."""
        while not self._stop_evt.is_set():
            with self._mu:
                deadline = self._fence_deadline
                epoch = self._fence_epoch
            if deadline == 0.0:
                return  # disarmed by COMMIT/ABORT
            wait = deadline - time.monotonic()
            if wait > 0:
                time.sleep(min(wait, 0.5))
                continue
            node = self._node
            node._server.clear_partition_fence()
            with self._mu:
                self._fence_deadline = 0.0
            flight_record("rebalance_fence_expired", epoch=epoch)
            get_metrics().inc("rebalance.fence_expiries")
            self._probe_epoch_after_expiry(epoch)
            return

    def _probe_epoch_after_expiry(self, pending_epoch: int) -> None:
        """Ask the replica group whether the flip committed while this
        sibling was out of the loop (donor died between persisting E+1
        and broadcasting COMMIT). Bounded probe; adoption reuses the
        COMMIT path so the sweep + drop still run."""
        node = self._node
        if node._partmap is None:
            return
        peers = [
            a
            for a in node._partmap.replicas[node._partition_id]
            if not self._is_self_addr(a)
        ]
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not self._stop_evt.is_set():
            for addr in peers:
                host, _, port = addr.rpartition(":")
                try:
                    with MerkleKVClient(host, int(port), timeout=2.0) as c:
                        m = c.partition_map()
                except (MerkleKVError, OSError, ValueError):
                    continue
                if m.epoch >= pending_epoch:
                    self._adopt_committed(m)
                    return
                # A reachable peer still at the old epoch IS the verdict:
                # the flip rolled back.
                flight_record(
                    "rebalance_fence_rollback", epoch=pending_epoch
                )
                return
            time.sleep(1.0)

    def _adopt_committed(self, pmap: PartitionMap) -> None:
        """Sibling-side adoption of a committed split: install the map,
        sweep-forward the moved residue to the joiner, drop the range."""
        node = self._node
        if node._partmap is not None and pmap.epoch <= node._partmap.epoch:
            return
        newpid = pmap.count - 1
        node.adopt_partition_map(pmap)
        moving = (pmap.hash_base, *pmap.assignment(newpid))
        self._drop_moved_range(moving, sweep_to=self._joiner_topic(newpid))

    def _wire_commit(self, args: list[str]) -> str:
        if len(args) != 3:
            return (
                "ERROR rebalance: COMMIT requires <epoch> <count> "
                "<mapspec>\r\n"
            )
        epoch, count, mapspec = int(args[0]), int(args[1]), args[2]
        node = self._node
        pmap = parse_map_spec(mapspec, count, epoch)
        with self._mu:
            joining = self._state in ("joining", "join_fetch", "join_live")
            self._fence_deadline = 0.0  # disarm the watchdog
        if joining:
            self._commit_join(pmap, epoch)
            return "OK committed\r\n"
        if node._partmap is None:
            return "ERROR rebalance: node is not partitioned\r\n"
        if epoch <= node._partmap.epoch:
            return "OK committed\r\n"  # idempotent re-delivery
        node._server.clear_partition_fence()
        self._adopt_committed(pmap)
        return "OK committed\r\n"

    def _wire_abort(self, args: list[str]) -> str:
        epoch = int(args[0]) if args else 0
        node = self._node
        with self._mu:
            joining = self._state in ("joining", "join_fetch", "join_live")
            self._fence_deadline = 0.0  # disarm the watchdog
        if joining:
            self._stop_evt.set()  # stop the fetch/watch thread
            t = self._thread
            if t is not None and t is not threading.current_thread():
                t.join(timeout=10)
            self._stop_evt.clear()
            self._abort_join(f"donor aborted (epoch {epoch})")
        else:
            node._server.clear_partition_fence()
            flight_record("rebalance_abort_received", epoch=epoch)
        return "OK aborted\r\n"


# -- operator CLI ----------------------------------------------------------


def main(argv=None) -> int:
    """``python -m merklekv_tpu rebalance``: drive one online split.

    Sends ``REBALANCE SPLIT`` to the donor (the node currently serving
    ``--partition``) and tails the session's phases until it lands in
    done / failed — the operator-facing shape of docs/DEPLOYMENT.md
    "Online rebalancing".
    """
    import argparse
    import sys

    p = argparse.ArgumentParser(prog="merklekv_tpu rebalance")
    p.add_argument(
        "--donor",
        required=True,
        help="host:port of the node serving the partition to split",
    )
    p.add_argument(
        "--partition",
        type=int,
        required=True,
        help="partition id to split (the donor must serve it)",
    )
    p.add_argument(
        "--joiner",
        required=True,
        help="comma-separated host:port replica set for the NEW "
        "partition; each must be a running reserve node",
    )
    p.add_argument(
        "--timeout",
        type=float,
        default=TRANSFER_DEADLINE_S + 60,
        help="give up tailing after this many seconds (the session "
        "itself keeps its own deadline)",
    )
    args = p.parse_args(argv)
    host, _, port = args.donor.rpartition(":")
    try:
        with MerkleKVClient(host, int(port), timeout=10.0) as c:
            epoch = c.partition_map().epoch
            resp = c.rebalance(
                f"SPLIT {args.partition} {epoch} {args.joiner}"
            )
    except (MerkleKVError, OSError, ValueError) as e:
        print(f"rebalance: {e}", file=sys.stderr)
        return 1
    print(resp)
    last = ""
    deadline = time.monotonic() + args.timeout
    while time.monotonic() < deadline:
        try:
            with MerkleKVClient(host, int(port), timeout=5.0) as c:
                fields = c.rebalance("STATUS").split(" ")
        except (MerkleKVError, OSError):
            time.sleep(1.0)
            continue
        state = fields[1] if len(fields) >= 2 else "?"
        if state != last:
            print(f"phase: {state}")
            last = state
        if state == "done":
            return 0
        if state in ("failed", "aborted", "idle"):
            print("rebalance did not commit (session rolled back); "
                  "the cluster is unchanged", file=sys.stderr)
            return 1
        time.sleep(0.5)
    print("rebalance: tail timeout (session may still be running)",
          file=sys.stderr)
    return 1

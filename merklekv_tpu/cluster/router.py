"""Thin partition router: the dumb-client fallback of partitioned mode.

Smart clients (client.PartitionedClient) route key -> partition
themselves. Everything else — the 13 language SDKs, redis-cli-style
tools — can point at ONE router address instead: the router holds the
cluster's partition map, parses just enough of each request line to find
the key(s), forwards to the owning partition's replica group, and relays
the response. Multi-key verbs (MGET/MSET/EXISTS) fan out per partition
and merge; SCAN/DBSIZE aggregate across all partitions.

Deliberately THIN: thread-per-connection, one backend connection per
(client connection, partition), no caching, no pipelining beyond the
backend client's own. A MOVED answer from a backend (the router's map
went stale mid-rebalance) refreshes the shared map and re-routes under
the bounded PARTITION_MOVED backoff policy; a BUSY answer (the moving
range's write fence during a live split's flip window) waits the same
policy out — the router serves straight through a rebalance, it just
pays refreshes.

Run: ``python -m merklekv_tpu router --port 7400 --seeds host:7001,host:7003``.
"""

from __future__ import annotations

import socket
import sys
import threading
import time
from typing import Optional

from merklekv_tpu.client import (
    ConnectionError as ClientConnectionError,
    MerkleKVClient,
    MerkleKVError,
    MovedError,
    ProtocolError,
    ServerBusyError,
)
from merklekv_tpu.cluster.partmap import PartitionMap
from merklekv_tpu.cluster.retry import PARTITION_MOVED
from merklekv_tpu.utils.tracing import get_metrics

__all__ = ["PartitionRouter"]

# Single-key verbs the router forwards verbatim (verb -> needs_value).
# INC/DEC route separately (their optional amount argument).
_SINGLE_KEY = {
    "GET": False,
    "DELETE": False,
    "DEL": False,
    "SET": True,
    "APPEND": True,
    "PREPEND": True,
}


class PartitionRouter:
    """TCP proxy routing the text protocol across a partitioned cluster."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        seeds: Optional[list[str]] = None,
        timeout: float = 5.0,
    ) -> None:
        if not seeds:
            raise ValueError("router needs at least one seed node")
        self.host = host
        self._port = port
        self.seeds = list(seeds)
        self.timeout = timeout
        self._map: Optional[PartitionMap] = None
        self._map_mu = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    def start(self, map_wait_s: float = 10.0) -> "PartitionRouter":
        deadline = time.monotonic() + map_wait_s
        while True:
            try:
                self.refresh_map()
                break
            except ClientConnectionError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self._port))
        self._sock.listen(128)
        self._port = self._sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="mkv-router-accept"
        )
        self._accept_thread.start()
        return self

    @property
    def port(self) -> int:
        return self._port

    @property
    def map(self) -> Optional[PartitionMap]:
        with self._map_mu:
            return self._map

    def stop(self) -> None:
        self._stopped.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None

    # -- map ----------------------------------------------------------------
    def refresh_map(self, min_epoch: int = 0) -> None:
        """Newest reachable map (seeds, then known replicas); raises
        ClientConnectionError when nobody serves one. Shared across every
        connection thread under the map lock."""
        with self._map_mu:
            candidates = list(self.seeds)
            if self._map is not None:
                for reps in self._map.replicas:
                    for a in reps:
                        if a not in candidates:
                            candidates.append(a)
            best = self._map
        fresh = None
        errors: list[str] = []
        for addr in candidates:
            host, _, port = addr.rpartition(":")
            try:
                with MerkleKVClient(host, int(port),
                                    timeout=self.timeout) as c:
                    m = c.partition_map()
            except (MerkleKVError, ValueError) as e:
                errors.append(f"{addr}: {e}")
                continue
            if fresh is None or m.epoch > fresh.epoch:
                fresh = m
            if fresh.epoch >= min_epoch > 0:
                break
        if fresh is None:
            raise ClientConnectionError(
                "router: no reachable node served a partition map: "
                + "; ".join(errors[:4])
            )
        with self._map_mu:
            if best is None or fresh.epoch >= best.epoch:
                self._map = fresh
                get_metrics().inc("router.map_refreshes")

    # -- serving -------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                daemon=True,
                name="mkv-router-conn",
            ).start()

    # Request-line byte cap, mirroring the native server's default
    # [server] max_line_bytes: without it one dumb client streaming a
    # newline-less line would balloon the router's memory unboundedly.
    MAX_LINE = 1 << 20

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        backends: dict[int, MerkleKVClient] = {}
        f = conn.makefile("rb")
        try:
            while not self._stopped.is_set():
                raw = f.readline(self.MAX_LINE + 1)
                if not raw:
                    return
                if len(raw) > self.MAX_LINE and not raw.endswith(b"\n"):
                    # Same refusal as the native server: answer once,
                    # close — the rest of the oversized line is garbage.
                    conn.sendall(b"ERROR line too long\r\n")
                    return
                line = raw.rstrip(b"\r\n").decode("utf-8", "surrogateescape")
                resp = self._dispatch(line, backends)
                conn.sendall(resp.encode("utf-8", "surrogateescape"))
        except OSError:
            pass
        finally:
            for b in backends.values():
                b.close()
            try:
                conn.close()
            except OSError:
                pass

    def _backend(
        self, pid: int, backends: dict[int, MerkleKVClient]
    ) -> MerkleKVClient:
        c = backends.get(pid)
        if c is not None:
            return c
        with self._map_mu:
            pmap = self._map
        if not 0 <= pid < pmap.count:
            # A concurrent refresh shrank the map between this command's
            # routing snapshot and now: heal exactly like a MOVED answer
            # — the dispatch retry regroups under the fresh map instead
            # of this thread dying on an IndexError mid-command.
            raise MovedError(
                f"MOVED {pid} {pmap.epoch}", pid, pmap.epoch
            )
        reps = list(pmap.replicas[pid])
        last: Optional[Exception] = None
        for addr in reps:
            host, _, port = addr.rpartition(":")
            try:
                c = MerkleKVClient(
                    host, int(port), timeout=self.timeout
                ).connect()
                backends[pid] = c
                return c
            except ClientConnectionError as e:
                last = e
        raise ClientConnectionError(
            f"partition {pid} unreachable: {last}"
        )

    def _dispatch(
        self, line: str, backends: dict[int, MerkleKVClient]
    ) -> str:
        m = get_metrics()
        m.inc("router.commands")
        parts = line.split(" ", 1)
        verb = parts[0].upper()
        rest = parts[1] if len(parts) > 1 else ""
        try:
            if verb == "PING":
                return f"PONG {rest}\r\n" if rest else "PONG \r\n"
            if verb == "PARTMAP":
                with self._map_mu:
                    return self._map.wire()
            # Bounded MOVED/BUSY healing around the real routing work
            # (PARTITION_MOVED retry policy): during a live rebalance a
            # command can land in the fence window (BUSY — wait it out)
            # and then on a flipped epoch (MOVED — refresh + re-route),
            # several times in a row. Each MOVED refreshes the map and
            # redials; the final attempt's refusal surfaces to the
            # client, which can apply its own policy.
            attempt = 0
            while True:
                try:
                    return self._route(verb, rest, backends)
                except MovedError as e:
                    if attempt + 1 >= (PARTITION_MOVED.attempts or 1):
                        raise
                    m.inc("router.moved_refreshes")
                    for b in backends.values():
                        b.close()
                    backends.clear()
                    time.sleep(PARTITION_MOVED.backoff(attempt))
                    attempt += 1
                    self.refresh_map(min_epoch=e.epoch)
                except ServerBusyError:
                    if attempt + 1 >= (PARTITION_MOVED.attempts or 1):
                        raise
                    m.inc("router.busy_retries")
                    time.sleep(PARTITION_MOVED.backoff(attempt))
                    attempt += 1
                    try:
                        self.refresh_map()
                    except ClientConnectionError:
                        pass  # retry against the current map
        except MovedError as e:
            return f"ERROR MOVED {e.partition} {e.epoch}\r\n"
        except ProtocolError as e:
            return f"ERROR {e}\r\n"
        except (MerkleKVError, OSError) as e:
            m.inc("router.backend_errors")
            # The backend connection state is unknown mid-error: drop all
            # of this client's backends so the next command redials.
            for b in backends.values():
                b.close()
            backends.clear()
            return f"ERROR router: {e}\r\n"

    def _route(
        self, verb: str, rest: str, backends: dict[int, MerkleKVClient]
    ) -> str:
        with self._map_mu:
            pmap = self._map
        if verb in ("INC", "DEC"):
            key, _, amt_s = rest.strip().partition(" ")
            if not key:
                return f"ERROR {verb} command requires a key\r\n"
            try:
                amt = int(amt_s) if amt_s else None
            except ValueError:
                return (
                    f"ERROR {verb} command amount must be a valid "
                    "number\r\n"
                )
            c = self._backend(pmap.partition_for_key(key), backends)
            fn = c.increment if verb == "INC" else c.decrement
            return f"VALUE {fn(key, amt)}\r\n"
        if verb in _SINGLE_KEY:
            if _SINGLE_KEY[verb]:  # "<key> <value>", first-space split
                key, sep, value = rest.partition(" ")
                if not sep or not key:
                    return f"ERROR {verb} command requires a key and value\r\n"
            else:
                key = rest.strip()
                if not key or " " in key:
                    return f"ERROR {verb} command requires a key\r\n"
            c = self._backend(pmap.partition_for_key(key), backends)
            if verb == "GET":
                v = c.get(key)
                return f"VALUE {v}\r\n" if v is not None else "NOT_FOUND\r\n"
            if verb in ("DEL", "DELETE"):
                return "DELETED\r\n" if c.delete(key) else "NOT_FOUND\r\n"
            if verb == "SET":
                c.set(key, value)
                return "OK\r\n"
            # APPEND / PREPEND
            fn = c.append if verb == "APPEND" else c.prepend
            return f"VALUE {fn(key, value)}\r\n"
        if verb == "EXISTS":
            keys = rest.split()
            if not keys:
                return "ERROR EXISTS command requires at least one key\r\n"
            total = 0
            for pid, sub in self._group(keys, pmap):
                total += self._backend(pid, backends).exists(*sub)
            return f"EXISTS {total}\r\n"
        if verb == "MGET":
            keys = rest.split()
            if not keys:
                return "ERROR MGET command requires at least one key\r\n"
            merged: dict[str, Optional[str]] = {}
            for pid, sub in self._group(keys, pmap):
                merged.update(self._backend(pid, backends).mget(sub))
            found = sum(1 for v in merged.values() if v is not None)
            if found == 0:
                return "NOT_FOUND\r\n"
            body = "".join(
                f"{k} {merged[k] if merged[k] is not None else 'NOT_FOUND'}"
                "\r\n"
                for k in keys
            )
            return f"VALUES {found}\r\n{body}"
        if verb == "MSET":
            args = rest.split()
            if not args or len(args) % 2:
                return (
                    "ERROR MSET command requires an even number of "
                    "arguments (key-value pairs)\r\n"
                )
            pairs = dict(zip(args[::2], args[1::2]))
            for pid, sub in self._group(list(pairs), pmap):
                self._backend(pid, backends).mset(
                    {k: pairs[k] for k in sub}
                )
            return "OK\r\n"
        if verb == "SCAN":
            prefix = rest.strip()
            keys: list[str] = []
            for pid in range(pmap.count):
                keys += self._backend(pid, backends).scan(prefix)
            keys.sort()
            body = "".join(f"{k}\r\n" for k in keys)
            return f"KEYS {len(keys)}\r\n{body}"
        if verb == "DBSIZE":
            total = sum(
                self._backend(pid, backends).dbsize()
                for pid in range(pmap.count)
            )
            return f"DBSIZE {total}\r\n"
        return (
            f"ERROR router: unsupported verb {verb} "
            "(connect to a node directly or use a partition-aware "
            "client)\r\n"
        )

    @staticmethod
    def _group(
        keys: list[str], pmap: PartitionMap
    ) -> list[tuple[int, list[str]]]:
        groups: dict[int, list[str]] = {}
        for k in keys:
            groups.setdefault(pmap.partition_for_key(k), []).append(k)
        return sorted(groups.items())


def main(argv: list[str]) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="merklekv_tpu router")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7400)
    p.add_argument(
        "--seeds",
        required=True,
        help="comma-separated node addresses to bootstrap the partition "
        "map from (any cluster member)",
    )
    args = p.parse_args(argv)
    seeds = [s.strip() for s in args.seeds.split(",") if s.strip()]
    router = PartitionRouter(args.host, args.port, seeds).start()
    print(
        f"merklekv_tpu router listening on {args.host}:{router.port} "
        f"({router.map.count} partitions, epoch {router.map.epoch})",
        flush=True,
    )
    try:
        while True:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Versioned partition map: the routing table of partitioned cluster mode.

The keyspace is hashed into ``P`` partitions, each owned by its own
replica group (disjoint nodes, own replication topic, own per-partition
Merkle root — a replica holds ONLY its partition's keys, so its
whole-node root IS the partition root and anti-entropy, bootstrap,
overload and the staleness pump stay partition-local by construction).

The map is (epoch, partition -> replica list). Nodes serve it over the
``PARTMAP`` wire verb; smart clients and the thin router bootstrap from
any node and refresh whenever a node answers ``ERROR MOVED <pid>
<epoch>`` (the native guard's stale-routing refusal). The epoch is a
generation counter: rebalancing installs a new map with a bumped epoch,
and a MOVED answer carrying a newer epoch is the client's refresh signal.

Ownership is a split tree over the hash space. ``h`` is the first 8
bytes of SHA-256(key) as a big-endian u64 (bit-identical to the native
guard, server.cc::partition_of_key). With ``base`` = the partition count
the cluster booted with, a partition owns the assignment ``(root, depth,
path)``::

    root = h % base            # which boot-time shard
    sub  = h // base           # the infinite refinement coordinate
    owns iff root matches and (sub & ((1 << depth) - 1)) == path

A boot map is depth-0 everywhere (partition ``i`` owns ``(i, 0, 0)``),
which makes ``partition_for_key`` exactly the legacy ``h % P`` — every
pre-split deployment routes bit-identically to before. Splitting
partition ``p`` at ``(r, d, q)`` refines ONE bit: ``p`` keeps ``(r, d+1,
q)`` and the new partition takes ``(r, d+1, q | 1 << d)``, so the moving
range is partition-local — no other partition's keys move. That locality
is what makes live rebalancing (cluster/rebalance.py) possible at all:
``h % P -> h % (P+1)`` would remap nearly every key in the cluster.

Wire/spec compatibility: an unsplit map serializes in the PR-15 format
verbatim (3-field header, plain rows). A split map needs the v2 format —
header gains ``base``, rows gain a ``root.depth.path`` token — which old
parsers reject LOUDLY (arity/address errors), never misroute silently.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Optional

__all__ = [
    "partition_of",
    "hash_of_key",
    "key_in_range",
    "PartitionMap",
    "parse_map_spec",
    "format_map_spec",
    "save_map_file",
    "load_map_file",
    "MAP_FILE_NAME",
    "PartitionMapError",
]


class PartitionMapError(ValueError):
    """A partition map (wire dump or config spec) failed validation —
    wrong shape, missing partitions, out-of-range ids, malformed replica
    addresses, or a split tree that does not tile the hash space. Raised
    instead of ever returning a PARTIAL map: routing on a half-parsed
    table is the silent-wrong-node bug the MOVED guard exists to kill."""


def hash_of_key(key: bytes | str) -> int:
    """key -> u64 routing hash: first 8 bytes of SHA-256(key), big-endian
    — bit-identical to the native dispatch guard (server.cc)."""
    if isinstance(key, str):
        key = key.encode("utf-8", "surrogateescape")
    return int.from_bytes(hashlib.sha256(key).digest()[:8], "big")


def partition_of(key: bytes | str, count: int) -> int:
    """key -> partition id under an UNSPLIT map (stable hash
    partitioning, ``h % count``). Split-aware routing lives on
    :meth:`PartitionMap.partition_for_key`; this stays the boot-map
    special case every pre-split caller (and the native guard's legacy
    path) agrees on."""
    if count <= 0:
        raise ValueError(f"partition count must be positive, got {count}")
    return hash_of_key(key) % count


def key_in_range(
    key: bytes | str, base: int, root: int, depth: int, path: int
) -> bool:
    """True iff ``key`` falls inside the assignment ``(root, depth,
    path)`` under ``base`` — the one predicate the donor's moving-range
    filter, the replicator's double-apply forward, and the native fence
    all agree on."""
    h = hash_of_key(key)
    if h % base != root:
        return False
    return ((h // base) & ((1 << depth) - 1)) == path


def _check_addr(addr: str) -> str:
    host, sep, port = addr.rpartition(":")
    if not sep or not host:
        raise PartitionMapError(f"replica address needs host:port: {addr!r}")
    try:
        p = int(port)
    except ValueError:
        raise PartitionMapError(
            f"replica address needs a numeric port: {addr!r}"
        ) from None
    if not 0 < p <= 65535:
        raise PartitionMapError(f"replica port out of range: {addr!r}")
    return addr


def _check_assignment_cover(
    base: int, assignments: list[tuple[int, int, int]]
) -> None:
    """Every hash must land in exactly one assignment: per root, the
    (depth, path) set must tile the sub-coordinate space — pairwise
    disjoint and summing to the whole. Anything else means a key with no
    owner (lost) or two owners (double-owned), the two failure modes the
    rebalance chaos drill exists to disprove."""
    by_root: dict[int, list[tuple[int, int]]] = {}
    for pid, (root, depth, path) in enumerate(assignments):
        if not 0 <= root < base:
            raise PartitionMapError(
                f"partition {pid} root {root} out of range 0..{base - 1}"
            )
        if depth < 0 or depth > 62:
            raise PartitionMapError(
                f"partition {pid} depth {depth} out of range 0..62"
            )
        if not 0 <= path < (1 << depth):
            raise PartitionMapError(
                f"partition {pid} path {path} out of range for depth {depth}"
            )
        by_root.setdefault(root, []).append((depth, path))
    for root in range(base):
        cells = by_root.get(root)
        if not cells:
            raise PartitionMapError(f"no partition owns hash root {root}")
        for i, (d1, p1) in enumerate(cells):
            for d2, p2 in cells[i + 1 :]:
                lo, hi = ((d1, p1), (d2, p2)) if d1 <= d2 else ((d2, p2), (d1, p1))
                if hi[1] & ((1 << lo[0]) - 1) == lo[1]:
                    raise PartitionMapError(
                        f"hash root {root}: overlapping assignments "
                        f"{lo} and {hi}"
                    )
        maxd = max(d for d, _ in cells)
        total = sum(1 << (maxd - d) for d, _ in cells)
        if total != 1 << maxd:
            raise PartitionMapError(
                f"hash root {root}: assignments do not cover the space "
                f"({total}/{1 << maxd} cells)"
            )


@dataclass
class PartitionMap:
    """Epoch-versioned partition -> replica-set table."""

    epoch: int = 1
    # replicas[pid] = ["host:port", ...] — index IS the partition id.
    replicas: list[list[str]] = field(default_factory=list)
    # Split-tree state. base = boot partition count (0 -> count: legacy
    # unsplit map); assignments[pid] = (root, depth, path) ([] -> the
    # trivial depth-0 map where partition i owns root i).
    base: int = 0
    assignments: list[tuple[int, int, int]] = field(default_factory=list)

    @property
    def count(self) -> int:
        return len(self.replicas)

    @property
    def hash_base(self) -> int:
        return self.base if self.base > 0 else self.count

    @property
    def is_split(self) -> bool:
        """True once any partition sits below depth 0 — the signal that
        the v2 wire/spec formats (and assignment-aware routing) are
        required."""
        if self.base and self.base != self.count:
            return True
        return any(d != 0 for _, d, _ in self.assignments)

    def assignment(self, pid: int) -> tuple[int, int, int]:
        if self.assignments:
            return self.assignments[pid]
        return (pid, 0, 0)

    def validate(self) -> "PartitionMap":
        if self.epoch < 1:
            raise PartitionMapError(f"epoch must be >= 1, got {self.epoch}")
        if not self.replicas:
            raise PartitionMapError("partition map has no partitions")
        for pid, reps in enumerate(self.replicas):
            if not reps:
                raise PartitionMapError(f"partition {pid} has no replicas")
            for addr in reps:
                _check_addr(addr)
        if self.base < 0:
            raise PartitionMapError(f"base must be >= 1, got {self.base}")
        if self.assignments and len(self.assignments) != self.count:
            raise PartitionMapError(
                f"assignment count mismatch: {len(self.assignments)} "
                f"assignments for {self.count} partitions"
            )
        if self.base and not self.assignments and self.base != self.count:
            raise PartitionMapError(
                f"base {self.base} != count {self.count} needs explicit "
                "assignments"
            )
        if self.assignments:
            _check_assignment_cover(
                self.hash_base, [self.assignment(p) for p in range(self.count)]
            )
        return self

    def partition_for_key(self, key: bytes | str) -> int:
        h = hash_of_key(key)
        if not self.is_split:
            return h % self.count
        base = self.hash_base
        root, sub = h % base, h // base
        for pid in range(self.count):
            r, d, p = self.assignment(pid)
            if r == root and (sub & ((1 << d) - 1)) == p:
                return pid
        # Unreachable on a validated map (the cover check guarantees an
        # owner); loud beats silent if one sneaks through unvalidated.
        raise PartitionMapError(f"no partition owns hash root {root}")

    def replicas_for_key(self, key: bytes | str) -> list[str]:
        return self.replicas[self.partition_for_key(key)]

    def partition_of_replica(self, addr: str) -> int | None:
        """The partition a replica address serves, or None when the
        address is not in the map."""
        for pid, reps in enumerate(self.replicas):
            if addr in reps:
                return pid
        return None

    # -- rebalance ----------------------------------------------------------
    def split(self, pid: int, new_replicas: list[str]) -> "PartitionMap":
        """The epoch-E+1 map splitting ``pid``: ``pid`` keeps the low
        half of its assignment one bit deeper, the appended partition
        (id = old count) takes the high half and ``new_replicas``. Pure —
        installing the result anywhere is the caller's (rebalance state
        machine's) job."""
        if not 0 <= pid < self.count:
            raise PartitionMapError(
                f"split partition {pid} out of range 0..{self.count - 1}"
            )
        root, depth, path = self.assignment(pid)
        if depth >= 62:
            raise PartitionMapError(f"partition {pid} at max split depth")
        assigns = [self.assignment(p) for p in range(self.count)]
        assigns[pid] = (root, depth + 1, path)
        assigns.append((root, depth + 1, path | (1 << depth)))
        return PartitionMap(
            epoch=self.epoch + 1,
            replicas=[list(r) for r in self.replicas] + [list(new_replicas)],
            base=self.hash_base,
            assignments=assigns,
        ).validate()

    def moving_range(self, pid: int) -> tuple[int, int, int, int]:
        """(base, root, depth, path) of the range that would LEAVE
        ``pid`` on split — i.e. the new child's assignment. The donor's
        snapshot filter, forward filter, and fence all take this tuple."""
        root, depth, path = self.assignment(pid)
        return (self.hash_base, root, depth + 1, path | (1 << depth))

    # -- wire ---------------------------------------------------------------
    # Unsplit: "PARTMAP <epoch> <count>" header + "<pid> <replica> [...]"
    # rows (every pid 0..count-1 exactly once, any order) + "END" — the
    # PR-15 format, byte-identical. Split: header gains the hash base
    # ("PARTMAP <epoch> <count> <base>") and every row carries the
    # assignment token ("<pid> <root>.<depth>.<path> <replica> [...]").
    # Old parsers fail LOUDLY on the 4-field header (arity error) instead
    # of routing h%P against a split map — a deliberate fail-closed.
    def wire(self) -> str:
        if not self.is_split:
            body = "".join(
                f"{pid} {' '.join(reps)}\r\n"
                for pid, reps in enumerate(self.replicas)
            )
            return f"PARTMAP {self.epoch} {self.count}\r\n{body}END\r\n"
        body = "".join(
            f"{pid} {r}.{d}.{p} {' '.join(reps)}\r\n"
            for pid, reps in enumerate(self.replicas)
            for r, d, p in [self.assignment(pid)]
        )
        return (
            f"PARTMAP {self.epoch} {self.count} {self.hash_base}\r\n"
            f"{body}END\r\n"
        )

    @classmethod
    def from_wire(cls, header: str, rows: list[str]) -> "PartitionMap":
        """Parse a PARTMAP response (header line + body rows, END already
        stripped). Every malformation raises :class:`PartitionMapError` —
        truncated or garbled dumps must never yield a partial map."""
        fields = header.split(" ")
        if len(fields) not in (3, 4) or fields[0] != "PARTMAP":
            raise PartitionMapError(f"malformed PARTMAP header: {header!r}")
        try:
            epoch, count = int(fields[1]), int(fields[2])
            base = int(fields[3]) if len(fields) == 4 else 0
        except ValueError:
            raise PartitionMapError(
                f"malformed PARTMAP header: {header!r}"
            ) from None
        split_wire = len(fields) == 4
        if epoch < 1 or count < 1 or (split_wire and base < 1):
            raise PartitionMapError(f"malformed PARTMAP header: {header!r}")
        if len(rows) != count:
            raise PartitionMapError(
                f"PARTMAP row count mismatch: header says {count}, "
                f"got {len(rows)}"
            )
        replicas: list[list[str] | None] = [None] * count
        assigns: list[tuple[int, int, int] | None] = [None] * count
        for row in rows:
            parts = [p for p in row.split(" ") if p]
            want = 3 if split_wire else 2
            if len(parts) < want:
                raise PartitionMapError(f"malformed PARTMAP row: {row!r}")
            try:
                pid = int(parts[0])
            except ValueError:
                raise PartitionMapError(
                    f"malformed PARTMAP row: {row!r}"
                ) from None
            if not 0 <= pid < count:
                raise PartitionMapError(
                    f"PARTMAP row partition {pid} out of range 0..{count - 1}"
                )
            if replicas[pid] is not None:
                raise PartitionMapError(f"duplicate PARTMAP row for {pid}")
            reps = parts[1:]
            if split_wire:
                assigns[pid] = _parse_assignment_token(parts[1], row)
                reps = parts[2:]
            replicas[pid] = [_check_addr(a) for a in reps]
        # len(rows) == count and no duplicates => every slot filled.
        return cls(
            epoch=epoch,
            replicas=[r for r in replicas if r is not None],
            base=base,
            assignments=(
                [a for a in assigns if a is not None] if split_wire else []
            ),
        ).validate()


def _parse_assignment_token(tok: str, ctx: str) -> tuple[int, int, int]:
    """``root.depth.path`` — three dot-joined decimal fields, nothing
    else. Range/cover checks happen in validate(); this only rejects
    shapes that could be a mangled replica address."""
    bits = tok.split(".")
    if len(bits) != 3 or not all(b.isdigit() for b in bits):
        raise PartitionMapError(f"malformed assignment token in {ctx!r}")
    return (int(bits[0]), int(bits[1]), int(bits[2]))


def parse_map_spec(spec: str, count: int, epoch: int = 1) -> PartitionMap:
    """Parse the ``[cluster] partition_map`` config spec:
    ``"0=host:port,host:port;1=host:port;..."`` — one ``pid=replicas``
    group per partition, ``;``-separated, replicas ``,``-separated. Every
    partition 0..count-1 must appear exactly once.

    Split maps extend the grammar (this is also the REBALANCE wire
    mapspec): an optional leading ``base=<B>`` group, and each pid may
    carry its assignment as ``pid@root.depth.path=replicas``. Groups
    without ``@`` default to the trivial ``(pid, 0, 0)``."""
    replicas: list[list[str] | None] = [None] * count
    assigns: list[tuple[int, int, int] | None] = [None] * count
    base = 0
    saw_assign = False
    for group in spec.split(";"):
        group = group.strip()
        if not group:
            continue
        pid_s, sep, reps_s = group.partition("=")
        if not sep:
            raise PartitionMapError(
                f"partition_map group needs pid=replicas: {group!r}"
            )
        if pid_s == "base":
            try:
                base = int(reps_s)
            except ValueError:
                raise PartitionMapError(
                    f"partition_map base must be numeric: {group!r}"
                ) from None
            if base < 1:
                raise PartitionMapError(
                    f"partition_map base must be >= 1: {group!r}"
                )
            continue
        pid_s, asep, assign_s = pid_s.partition("@")
        try:
            pid = int(pid_s)
        except ValueError:
            raise PartitionMapError(
                f"partition_map group needs a numeric pid: {group!r}"
            ) from None
        if not 0 <= pid < count:
            raise PartitionMapError(
                f"partition_map pid {pid} out of range 0..{count - 1}"
            )
        if replicas[pid] is not None:
            raise PartitionMapError(f"duplicate partition_map group for {pid}")
        if asep:
            assigns[pid] = _parse_assignment_token(assign_s, group)
            saw_assign = True
        reps = [r.strip() for r in reps_s.split(",") if r.strip()]
        if not reps:
            raise PartitionMapError(
                f"partition_map partition {pid} has no replicas"
            )
        replicas[pid] = [_check_addr(a) for a in reps]
    missing = [i for i, r in enumerate(replicas) if r is None]
    if missing:
        raise PartitionMapError(
            f"partition_map missing partitions: {missing}"
        )
    use_assigns = saw_assign or base > 0
    return PartitionMap(
        epoch=epoch,
        replicas=[r for r in replicas if r is not None],
        base=base,
        assignments=(
            [assigns[p] or (p, 0, 0) for p in range(count)]
            if use_assigns
            else []
        ),
    ).validate()


def format_map_spec(pmap: PartitionMap) -> str:
    """The inverse of :func:`parse_map_spec` — the one-line mapspec the
    REBALANCE JOIN/COMMIT verbs carry. Unsplit maps round-trip through
    the legacy grammar; split maps always carry base + every assignment
    so the receiver never guesses."""
    if not pmap.is_split:
        return ";".join(
            f"{pid}={','.join(reps)}" for pid, reps in enumerate(pmap.replicas)
        )
    groups = [f"base={pmap.hash_base}"]
    for pid, reps in enumerate(pmap.replicas):
        r, d, p = pmap.assignment(pid)
        groups.append(f"{pid}@{r}.{d}.{p}={','.join(reps)}")
    return ";".join(groups)


# -- durable map file ---------------------------------------------------------
# A rebalance's epoch flip COMMITS by persisting the new map here (tmp +
# fsync + rename, so the commit point is atomic and crash-safe). On boot a
# node overlays a persisted map NEWER than its config-derived one — a donor
# killed one instruction after the rename restarts already committed, while
# one killed before it restarts at the old epoch (= the rollback).

MAP_FILE_NAME = "partmap.spec"
_MAP_FILE_MAGIC = "MKVPARTMAP1"


def save_map_file(directory: str, pmap: PartitionMap, pid: int) -> str:
    """Atomically persist ``pmap`` (and this node's partition id under it)
    to ``<directory>/partmap.spec``. Returns the file path."""
    path = os.path.join(directory, MAP_FILE_NAME)
    tmp = path + ".tmp"
    body = (
        f"{_MAP_FILE_MAGIC}\n"
        f"epoch {pmap.epoch}\n"
        f"count {pmap.count}\n"
        f"pid {pid}\n"
        f"spec {format_map_spec(pmap)}\n"
    )
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        os.write(fd, body.encode("ascii"))
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    # The rename is the commit point; fsync the directory so it survives
    # a power cut, not just a process kill.
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    return path


def load_map_file(directory: str) -> Optional[tuple[PartitionMap, int]]:
    """Load a persisted ``(map, partition_id)`` from ``directory``, or
    None when no file exists. A PRESENT but malformed file raises
    :class:`PartitionMapError` — ownership must never be guessed from a
    half-written commit record (the atomic rename makes this unreachable
    short of disk corruption, which deserves a loud stop)."""
    path = os.path.join(directory, MAP_FILE_NAME)
    try:
        with open(path, "r", encoding="ascii") as f:
            lines = f.read().splitlines()
    except FileNotFoundError:
        return None
    except (OSError, UnicodeDecodeError) as e:
        raise PartitionMapError(f"{path}: unreadable map file: {e}")
    fields: dict[str, str] = {}
    if not lines or lines[0] != _MAP_FILE_MAGIC:
        raise PartitionMapError(f"{path}: bad map file magic")
    for ln in lines[1:]:
        if not ln:
            continue
        name, sep, value = ln.partition(" ")
        if not sep:
            raise PartitionMapError(f"{path}: malformed line {ln!r}")
        fields[name] = value
    try:
        epoch = int(fields["epoch"])
        count = int(fields["count"])
        pid = int(fields["pid"])
        spec = fields["spec"]
    except (KeyError, ValueError) as e:
        raise PartitionMapError(f"{path}: incomplete map file: {e}")
    pmap = parse_map_spec(spec, count, epoch)
    if not 0 <= pid < pmap.count:
        raise PartitionMapError(
            f"{path}: pid {pid} out of range for {pmap.count} partitions"
        )
    return pmap, pid

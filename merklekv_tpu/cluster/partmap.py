"""Versioned partition map: the routing table of partitioned cluster mode.

The keyspace is hashed into ``P`` partitions, each owned by its own
replica group (disjoint nodes, own replication topic, own per-partition
Merkle root — a replica holds ONLY its partition's keys, so its
whole-node root IS the partition root and anti-entropy, bootstrap,
overload and the staleness pump stay partition-local by construction).

The map is (epoch, partition -> replica list). Nodes serve it over the
``PARTMAP`` wire verb; smart clients and the thin router bootstrap from
any node and refresh whenever a node answers ``ERROR MOVED <pid>
<epoch>`` (the native guard's stale-routing refusal). The epoch is a
generation counter: rebalancing installs a new map with a bumped epoch,
and a MOVED answer carrying a newer epoch is the client's refresh signal.

``partition_of`` MUST stay bit-identical to the native guard
(server.cc::partition_of_key): first 8 bytes of SHA-256(key), big-endian,
mod P. Every router, client, bench driver, and the guard route with this
one function or MOVED ping-pongs forever.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

__all__ = [
    "partition_of",
    "PartitionMap",
    "parse_map_spec",
    "PartitionMapError",
]


class PartitionMapError(ValueError):
    """A partition map (wire dump or config spec) failed validation —
    wrong shape, missing partitions, out-of-range ids, malformed replica
    addresses. Raised instead of ever returning a PARTIAL map: routing on
    a half-parsed table is the silent-wrong-node bug the MOVED guard
    exists to kill."""


def partition_of(key: bytes | str, count: int) -> int:
    """key -> partition id (stable hash partitioning).

    First 8 bytes of SHA-256(key) as a big-endian u64, mod ``count`` —
    bit-identical to the native dispatch guard (server.cc)."""
    if isinstance(key, str):
        key = key.encode("utf-8", "surrogateescape")
    if count <= 0:
        raise ValueError(f"partition count must be positive, got {count}")
    return int.from_bytes(hashlib.sha256(key).digest()[:8], "big") % count


def _check_addr(addr: str) -> str:
    host, sep, port = addr.rpartition(":")
    if not sep or not host:
        raise PartitionMapError(f"replica address needs host:port: {addr!r}")
    try:
        p = int(port)
    except ValueError:
        raise PartitionMapError(
            f"replica address needs a numeric port: {addr!r}"
        ) from None
    if not 0 < p <= 65535:
        raise PartitionMapError(f"replica port out of range: {addr!r}")
    return addr


@dataclass
class PartitionMap:
    """Epoch-versioned partition -> replica-set table."""

    epoch: int = 1
    # replicas[pid] = ["host:port", ...] — index IS the partition id.
    replicas: list[list[str]] = field(default_factory=list)

    @property
    def count(self) -> int:
        return len(self.replicas)

    def validate(self) -> "PartitionMap":
        if self.epoch < 1:
            raise PartitionMapError(f"epoch must be >= 1, got {self.epoch}")
        if not self.replicas:
            raise PartitionMapError("partition map has no partitions")
        for pid, reps in enumerate(self.replicas):
            if not reps:
                raise PartitionMapError(f"partition {pid} has no replicas")
            for addr in reps:
                _check_addr(addr)
        return self

    def partition_for_key(self, key: bytes | str) -> int:
        return partition_of(key, self.count)

    def replicas_for_key(self, key: bytes | str) -> list[str]:
        return self.replicas[self.partition_for_key(key)]

    def partition_of_replica(self, addr: str) -> int | None:
        """The partition a replica address serves, or None when the
        address is not in the map."""
        for pid, reps in enumerate(self.replicas):
            if addr in reps:
                return pid
        return None

    # -- wire ---------------------------------------------------------------
    # "PARTMAP <epoch> <count>" header, one "<pid> <replica> [...]" row per
    # partition (every pid 0..count-1 exactly once, any order), "END".
    def wire(self) -> str:
        body = "".join(
            f"{pid} {' '.join(reps)}\r\n"
            for pid, reps in enumerate(self.replicas)
        )
        return f"PARTMAP {self.epoch} {self.count}\r\n{body}END\r\n"

    @classmethod
    def from_wire(cls, header: str, rows: list[str]) -> "PartitionMap":
        """Parse a PARTMAP response (header line + body rows, END already
        stripped). Every malformation raises :class:`PartitionMapError` —
        truncated or garbled dumps must never yield a partial map."""
        fields = header.split(" ")
        if len(fields) != 3 or fields[0] != "PARTMAP":
            raise PartitionMapError(f"malformed PARTMAP header: {header!r}")
        try:
            epoch, count = int(fields[1]), int(fields[2])
        except ValueError:
            raise PartitionMapError(
                f"malformed PARTMAP header: {header!r}"
            ) from None
        if epoch < 1 or count < 1:
            raise PartitionMapError(f"malformed PARTMAP header: {header!r}")
        if len(rows) != count:
            raise PartitionMapError(
                f"PARTMAP row count mismatch: header says {count}, "
                f"got {len(rows)}"
            )
        replicas: list[list[str] | None] = [None] * count
        for row in rows:
            parts = [p for p in row.split(" ") if p]
            if len(parts) < 2:
                raise PartitionMapError(f"malformed PARTMAP row: {row!r}")
            try:
                pid = int(parts[0])
            except ValueError:
                raise PartitionMapError(
                    f"malformed PARTMAP row: {row!r}"
                ) from None
            if not 0 <= pid < count:
                raise PartitionMapError(
                    f"PARTMAP row partition {pid} out of range 0..{count - 1}"
                )
            if replicas[pid] is not None:
                raise PartitionMapError(f"duplicate PARTMAP row for {pid}")
            replicas[pid] = [_check_addr(a) for a in parts[1:]]
        # len(rows) == count and no duplicates => every slot filled.
        return cls(epoch=epoch, replicas=[r for r in replicas if r is not None]).validate()


def parse_map_spec(spec: str, count: int, epoch: int = 1) -> PartitionMap:
    """Parse the ``[cluster] partition_map`` config spec:
    ``"0=host:port,host:port;1=host:port;..."`` — one ``pid=replicas``
    group per partition, ``;``-separated, replicas ``,``-separated. Every
    partition 0..count-1 must appear exactly once."""
    replicas: list[list[str] | None] = [None] * count
    for group in spec.split(";"):
        group = group.strip()
        if not group:
            continue
        pid_s, sep, reps_s = group.partition("=")
        if not sep:
            raise PartitionMapError(
                f"partition_map group needs pid=replicas: {group!r}"
            )
        try:
            pid = int(pid_s)
        except ValueError:
            raise PartitionMapError(
                f"partition_map group needs a numeric pid: {group!r}"
            ) from None
        if not 0 <= pid < count:
            raise PartitionMapError(
                f"partition_map pid {pid} out of range 0..{count - 1}"
            )
        if replicas[pid] is not None:
            raise PartitionMapError(f"duplicate partition_map group for {pid}")
        reps = [r.strip() for r in reps_s.split(",") if r.strip()]
        if not reps:
            raise PartitionMapError(
                f"partition_map partition {pid} has no replicas"
            )
        replicas[pid] = [_check_addr(a) for a in reps]
    missing = [i for i, r in enumerate(replicas) if r is None]
    if missing:
        raise PartitionMapError(
            f"partition_map missing partitions: {missing}"
        )
    return PartitionMap(
        epoch=epoch, replicas=[r for r in replicas if r is not None]
    ).validate()

"""Pub/sub event fabric behind a small Transport interface.

The reference replicates through an external MQTT broker over rumqttc
(replication.rs:115-143, topics "{prefix}/events"). This environment has no
egress and no broker, so the fabric is pluggable:

- ``InProcessBus`` — loopback fan-out inside one process (unit tests, and
  multi-node-in-one-process topologies like the integration suite's).
- ``TcpBroker`` + ``TcpTransport`` — a minimal self-hosted broker speaking
  length-framed (topic, payload) messages over TCP, QoS-0 fan-out to every
  connected client (MQTT-like enough for LWW replication, which tolerates
  loss by design — anti-entropy repairs). One broker serves a whole
  single-host cluster; multi-host works the same over DCN.

Delivery is at-most-once per connection; the replication layer's op_id
dedupe + LWW make redelivery and reordering safe either way.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from collections import deque

from merklekv_tpu.cluster.retry import TRANSPORT_HEAL, RetryPolicy
from merklekv_tpu.utils.tracing import get_metrics
from typing import Callable, Optional, Protocol

__all__ = ["Transport", "InProcessBus", "TcpBroker", "TcpTransport"]

Callback = Callable[[str, bytes], None]


class Transport(Protocol):
    def publish(self, topic: str, payload: bytes) -> None: ...
    def subscribe(self, topic_prefix: str, callback: Callback) -> None: ...
    def unsubscribe(self, callback: Callback) -> None: ...
    def close(self) -> None: ...


# ------------------------------------------------------------- in-process

class InProcessBus:
    """Fan-out bus inside one process. Delivery happens on a dispatcher
    thread, so publishers never run subscriber callbacks inline."""

    def __init__(self) -> None:
        self._subs: list[tuple[str, Callback]] = []
        self._mu = threading.Lock()
        self._q: list[tuple[str, bytes]] = []
        self._cv = threading.Condition(self._mu)
        self._closed = False
        self.callback_errors = 0
        self._thread = threading.Thread(target=self._dispatch, daemon=True)
        self._thread.start()

    def publish(self, topic: str, payload: bytes) -> None:
        with self._cv:
            if self._closed:
                return
            self._q.append((topic, payload))
            self._cv.notify()

    def subscribe(self, topic_prefix: str, callback: Callback) -> None:
        with self._mu:
            self._subs.append((topic_prefix, callback))

    def unsubscribe(self, callback: Callback) -> None:
        with self._mu:
            self._subs = [(p, c) for p, c in self._subs if c is not callback]

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._thread.join(timeout=2)

    def _dispatch(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if self._closed and not self._q:
                    return
                topic, payload = self._q.pop(0)
                subs = list(self._subs)
            for prefix, cb in subs:
                if topic.startswith(prefix):
                    try:
                        cb(topic, payload)
                    except Exception:
                        # Subscriber errors must not kill the bus, but a
                        # silently-eaten event is an invisible delivery gap —
                        # count it so tests/operators can see the drop.
                        self.callback_errors += 1


# ------------------------------------------------------------- TCP broker

def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


# Frames above this are rejected before any allocation: the header's 4-byte
# total is peer-controlled and must not size a buffer unchecked.
MAX_FRAME = 16 << 20

# Socket buffer sizing for every fabric socket (broker and clients). The
# kernel default (~208 KiB) holds only ~4K single-event frames; a
# replication burst that outruns the fan-out for a moment fills it, and a
# full receive buffer degrades loopback TCP into a persist-timer
# stop-and-go (~10 frames/s observed on a 4.x kernel) that outlives the
# burst by minutes. 4 MiB absorbs ~10^5 in-flight events, which keeps even
# the per-event compat mode (batch_max_events <= 1) out of that regime;
# the kernel silently caps the request where limits are lower.
SOCK_BUF_BYTES = 1 << 22


def _enlarge_sock_buffers(sock: socket.socket) -> None:
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, SOCK_BUF_BYTES)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, SOCK_BUF_BYTES)
    except OSError:
        pass  # best effort: a capped buffer only lowers the burst ceiling


def _read_frame(sock: socket.socket) -> Optional[tuple[str, bytes]]:
    head = _read_exact(sock, 6)
    if head is None:
        return None
    total, tlen = struct.unpack("<IH", head)
    if tlen > total or total > MAX_FRAME:
        return None
    body = _read_exact(sock, total)
    if body is None:
        return None
    return body[:tlen].decode("utf-8"), body[tlen:]


def _frame(topic: str, payload: bytes) -> bytes:
    t = topic.encode("utf-8")
    return struct.pack("<IH", len(t) + len(payload), len(t)) + t + payload


class TcpBroker:
    """Self-hosted fan-out broker: every frame from any client goes to every
    connected client (including the sender — src-based loop prevention is the
    subscriber's job, as with MQTT)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Before listen(): accepted sockets inherit the enlarged buffers.
        _enlarge_sock_buffers(self._listener)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()
        # cid -> (socket, per-socket send lock): concurrent publishers must
        # not interleave partial sendall()s on one subscriber's stream.
        self._clients: dict[int, tuple[socket.socket, threading.Lock]] = {}
        self._next_id = 0
        self._mu = threading.Lock()
        self._closed = False
        self._accept_thread = threading.Thread(target=self._accept, daemon=True)
        self._accept_thread.start()

    def _accept(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._mu:
                cid = self._next_id
                self._next_id += 1
                self._clients[cid] = (sock, threading.Lock())
            threading.Thread(
                target=self._serve, args=(cid, sock), daemon=True
            ).start()

    def _serve(self, cid: int, sock: socket.socket) -> None:
        while True:
            frame = _read_frame(sock)
            if frame is None:
                break
            data = _frame(*frame)
            with self._mu:
                targets = list(self._clients.items())
            for tid, (tsock, tlock) in targets:
                try:
                    with tlock:
                        tsock.sendall(data)
                except OSError:
                    self._drop(tid)
        self._drop(cid)

    def _drop(self, cid: int) -> None:
        with self._mu:
            entry = self._clients.pop(cid, None)
        if entry is not None:
            try:
                entry[0].close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            # shutdown BEFORE close: the accept thread is blocked inside
            # accept() and holds the kernel socket alive — a bare close()
            # leaves the port in LISTEN until that syscall returns (i.e.
            # forever), so a restarted broker can never rebind it.
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._mu:
            entries = list(self._clients.values())
            self._clients.clear()
        for s, _lk in entries:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


# Events published while the broker link is down wait here (per-transport
# bounded FIFO) and flush after healing — without this every write during
# an outage is silently gone and only anti-entropy ever repairs it. The
# bound keeps a long outage from eating the heap; overflow drops the
# OLDEST event (LWW: newer state supersedes older) and counts the drop.
OUTBOX_LIMIT = 8192


def _enable_tcp_keepalive(sock: socket.socket) -> None:
    """Kernel keepalive probes: a subscriber-only client never writes, so
    without these a silent partition (power loss, NAT drop — no RST) blocks
    recv forever and reconnect never triggers. ~15s idle + 3 x 5s probes
    bounds deafness to ~30s."""
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPIDLE, 15)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPINTVL, 5)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPCNT, 3)
    except (OSError, AttributeError):
        pass  # non-Linux: base SO_KEEPALIVE still applies


def _enqueue_outbox(t, topic: str, payload: bytes) -> None:
    with t._outbox_mu:
        if len(t._outbox) >= OUTBOX_LIMIT:
            t._outbox.popleft()
            t.outbox_dropped += 1
            get_metrics().inc("transport.outbox_dropped")
        t._outbox.append((topic, payload))


def _publish_or_queue(t, topic: str, payload: bytes) -> None:
    """Transport publish body: enqueue during a KNOWN outage (the reader
    flagged the link down), otherwise attempt the wire and enqueue on
    failure. An in-flight send that the kernel buffered just before an
    undetected death can still be lost — bounding that window needs
    broker acks, which is the one QoS-1 piece deliberately not taken on
    (anti-entropy repairs the residue; see the replicator docstring).

    Deliberate post-heal ordering relaxation: a publish issued while the
    outbox is still draining goes straight to the wire and can OVERTAKE
    queued pre-outage events. Receivers apply per-key LWW (ts + digest
    tiebreak), so the overtaken stale event can never clobber newer state;
    routing live publishes through the outbox until empty would instead
    stall the write path behind the whole backlog. Documented in
    docs/PROTOCOL.md ("Post-heal publish ordering")."""
    if t.link_down:
        _enqueue_outbox(t, topic, payload)
        # Enqueue/heal race: if the heal finished (and drained) between the
        # flag read and the append, nothing would ever flush this event —
        # drain opportunistically now that the link is back.
        if not t.link_down:
            _drain_outbox(t)
        return
    try:
        t._wire_send(topic, payload)
    except OSError:
        _enqueue_outbox(t, topic, payload)
        if not t.link_down:
            _drain_outbox(t)


def _drain_outbox(t) -> None:
    """Flush queued events through the healed link, FIFO. Stops (and
    re-queues the event in flight) if the link dies again mid-drain."""
    while True:
        with t._outbox_mu:
            if not t._outbox:
                return
            topic, payload = t._outbox.popleft()
        try:
            t._wire_send(topic, payload)
        except OSError:
            with t._outbox_mu:
                t._outbox.appendleft((topic, payload))
            return


def _heal_policy(t) -> RetryPolicy:
    """The transport's heal backoff as a RetryPolicy. Tests pin instance
    ``_BACKOFF_FIRST``/``_BACKOFF_MAX`` to stagger heal races — those
    legacy knobs keep winning over the shared policy's endpoints."""
    policy = getattr(t, "_policy", TRANSPORT_HEAL)
    return policy.with_overrides(
        first_delay=t._BACKOFF_FIRST, max_delay=t._BACKOFF_MAX
    )


def _dead_socket() -> socket.socket:
    """Placeholder for a link that is down from birth (broker not up yet):
    already closed, so the reader's first recv fails straight into the
    heal loop instead of blocking."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.close()
    return sock


def _heal_link(t, dial, on_connected=None) -> bool:
    """Shared reconnect engine for broker-client transports.

    ``t`` exposes ``_closed``, ``_send_mu``, ``_sock``, ``reconnects``, and
    a backoff policy (``_heal_policy``); ``dial()`` returns a fresh
    connected socket or raises OSError; ``on_connected`` runs after the
    swap (e.g. MQTT resubscribe). Returns False when ``close()`` ended the
    transport.
    """
    t.link_down = True
    policy = _heal_policy(t)
    attempt = 0
    while not t._closed:
        time.sleep(policy.backoff(attempt, getattr(t, "_heal_rng", None)))
        attempt += 1
        if t._closed:
            return False
        try:
            sock = dial()
        except OSError:
            continue
        # Unblock any publisher stuck in sendall() on the dead socket
        # BEFORE taking _send_mu: without a send timeout that sendall only
        # errors at the kernel's retransmission limit (~15-30 min), and it
        # HOLDS _send_mu — the swap would stall healing for that long.
        try:
            t._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        with t._send_mu:
            if t._closed:
                # close() ran while we were dialing: the old socket is
                # already shut down; do not leak the fresh one.
                sock.close()
                return False
            old = t._sock
            t._sock = sock
        try:
            old.close()
        except OSError:
            pass
        t.link_down = False
        t.reconnects += 1
        get_metrics().inc("transport.reconnects")
        if on_connected is not None:
            on_connected()
        return True
    return False


class TcpTransport:
    """Client for TcpBroker implementing the Transport interface.

    Self-healing: when the broker link drops (broker restart, network
    blip), the reader reconnects with capped exponential backoff and the
    fabric resumes — the reference's rumqttc event loop does the same
    (/root/reference/src/replication.rs:148-166). Events published during
    a detected outage wait in a bounded outbox and flush after the heal
    (only the narrow undetected-death window is lossy; anti-entropy
    repairs that residue). ``reconnects`` / ``outbox_dropped`` count the
    healed outages and overflow drops for observability.

    A broker that is down at CONSTRUCTION time is the same outage one
    second early: the transport starts with ``link_down=True``, queues
    publishes in the outbox, and the reader's heal loop dials with the
    same backoff — so nodes and broker can start in any order."""

    # Heal backoff (shared cluster policy, cluster/retry.py). The legacy
    # _BACKOFF_FIRST/_BACKOFF_MAX knobs derive from it and remain the
    # per-instance override hook tests use to stagger heal races.
    _policy = TRANSPORT_HEAL
    _BACKOFF_FIRST = TRANSPORT_HEAL.first_delay
    _BACKOFF_MAX = TRANSPORT_HEAL.max_delay

    def __init__(self, host: str, port: int, timeout: float = 5.0) -> None:
        self._host, self._port, self._timeout = host, port, timeout
        self._subs: list[tuple[str, Callback]] = []
        self._mu = threading.Lock()
        self._send_mu = threading.Lock()
        self._closed = False
        self.callback_errors = 0
        self.reconnects = 0
        self._outbox: deque[tuple[str, bytes]] = deque()
        self._outbox_mu = threading.Lock()
        self.outbox_dropped = 0
        self.link_down = False
        try:
            self._sock = self._connect()
        except OSError:
            # Broker not up yet: start degraded and let the reader's heal
            # loop keep dialing — startup ordering is not a requirement.
            get_metrics().inc("transport.start_degraded")
            self._sock = _dead_socket()
            self.link_down = True
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        if sock.getsockname() == sock.getpeername():
            # TCP self-connect: dialing a broker port in the ephemeral range
            # while it is down can simultaneous-connect to ITSELF — the
            # socket then squats the port and blocks the broker's rebind.
            sock.close()
            raise ConnectionRefusedError("self-connect (broker down)")
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _enlarge_sock_buffers(sock)
        _enable_tcp_keepalive(sock)
        return sock

    def _reconnect(self) -> bool:
        """Re-dial until the broker answers or close() is called."""
        return _heal_link(self, self._connect, lambda: _drain_outbox(self))

    def publish(self, topic: str, payload: bytes) -> None:
        _publish_or_queue(self, topic, payload)

    @property
    def outbox_depth(self) -> int:
        """Events queued awaiting a broker heal (the outbox-depth gauge)."""
        with self._outbox_mu:
            return len(self._outbox)

    def _wire_send(self, topic: str, payload: bytes) -> None:
        with self._send_mu:
            self._sock.sendall(_frame(topic, payload))

    def subscribe(self, topic_prefix: str, callback: Callback) -> None:
        with self._mu:
            self._subs.append((topic_prefix, callback))

    def unsubscribe(self, callback: Callback) -> None:
        with self._mu:
            self._subs = [(p, c) for p, c in self._subs if c is not callback]

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def _read_loop(self) -> None:
        while not self._closed:
            frame = _read_frame(self._sock)
            if frame is None:
                if self._closed or not self._reconnect():
                    return
                continue
            topic, payload = frame
            with self._mu:
                subs = list(self._subs)
            for prefix, cb in subs:
                if topic.startswith(prefix):
                    try:
                        cb(topic, payload)
                    except Exception:
                        # Count swallowed subscriber errors: a dropped event
                        # here would otherwise vanish without a trace.
                        self.callback_errors += 1


def make_transport(
    broker: str,
    port: int,
    kind: str = "framed",
    client_id: str = "",
    username: str = "",
    password: str = "",
) -> Transport:
    """Config-driven transport selection.

    broker "local"/"inproc"/"" -> private InProcessBus; otherwise ``kind``
    picks the wire: "framed" (default, the self-hosted TcpBroker fabric) or
    "mqtt" (real MQTT 3.1.1 — join an existing mosquitto-style deployment,
    the reference's fabric, replication.rs:115-143)."""
    if broker in ("local", "inproc", ""):
        return InProcessBus()
    if kind == "mqtt":
        from merklekv_tpu.cluster.transport_mqtt import MqttTransport

        return MqttTransport(
            broker, port, client_id=client_id,
            username=username, password=password,
        )
    if kind != "framed":
        # A typo'd kind silently speaking the wrong wire at a real broker
        # would leave replication dead with no error anywhere (publish is
        # QoS-0 and swallows transport failures by design).
        raise ValueError(f"unknown replication transport {kind!r}")
    return TcpTransport(broker, port)

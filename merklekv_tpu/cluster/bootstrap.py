"""Node bootstrap: verified snapshot shipping + delta sync.

A new (or long-dead, or interior-WAL-corrupted) replica rebuilding purely
via anti-entropy hits the bisect walk's pathological worst case — every
subtree diverges, so the walk degenerates toward O(n) wire bytes and the
joiner serves stale/empty reads for the whole window. This module applies
the "decouple and batch tree maintenance" idea from Asynchronous Merkle
Trees (arXiv:2311.17441, PAPERS.md) to node lifecycle instead: reuse the
storage plane's Merkle-stamped snapshots as a bulk-transfer format, verify
the stamped root on the JOINER before a single read serves, and close the
post-stamp gap with the ordinary bisect walk — which now only descends
into the delta.

State machine (one run per (re)boot):

    DISCOVER  pick a donor: SNAPMETA every candidate (health-up peers
              first); ERROR answers are the capability-fallback signal
              (old peer / no durable storage / no snapshot) — a candidate
              pool with zero capable donors degrades to the plain
              anti-entropy walk, same discipline as TREELEVEL.
    FETCH     SNAPCHUNK range reads, CRC-framed; the byte offset is the
              checkpoint, so a dropped/throttled link resumes at the
              verified prefix (retry.py BOOTSTRAP_FETCH policy). Donor
              death past the retry budget fails over to the next donor.
    VERIFY    decode the assembled bytes + recompute the Merkle root via
              the bulk rebuild path; a stamp mismatch QUARANTINES the
              donor as suspect (never retried this run, reported to the
              health table) and the next donor is tried. The node serves
              ZERO reads before this passes.
    DELTA     apply the verified state through the LWW verbs (one native
              batch crossing per slab), open the read gate, replay the
              replication frames buffered during the transfer, then run a
              bisect walk against the donor clipped — by tree equality —
              to the post-stamp delta.
    LIVE      converged; the periodic anti-entropy loop takes over.

Failure is never worse than the status quo ante: any path that cannot
ship-and-verify a snapshot ends in the plain walk the node would have run
anyway, and the read gate always reopens.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from merklekv_tpu.client import (
    ChunkIntegrityError,
    MerkleKVClient,
    MerkleKVError,
    ProtocolError,
)
from merklekv_tpu.cluster.retry import BOOTSTRAP_FETCH, Deadline, RetryPolicy
from merklekv_tpu.utils.tracing import get_metrics, span

__all__ = ["BootstrapSession", "BootstrapReport", "STATE_CODES"]

# Gauge encoding of the state machine (bootstrap.state).
STATE_CODES = {
    "idle": 0,
    "discover": 1,
    "fetch": 2,
    "verify": 3,
    "delta": 4,
    "live": 5,
    "failed": -1,
}

# Ops per native apply_batch crossing when installing a verified snapshot.
_APPLY_SLAB = 8192


@dataclass
class BootstrapReport:
    reason: str = ""
    # "snapshot": verified bulk transfer + delta walk; "walk": no donor
    # could serve a snapshot, plain anti-entropy fallback; "failed": no
    # donor reachable at all (the periodic loop keeps trying).
    mode: str = ""
    donor: str = ""
    donors_tried: list[str] = field(default_factory=list)
    # Donors whose snapshot failed stamp/CRC verification — quarantined
    # for this run and reported degraded to the health table.
    suspects: list[str] = field(default_factory=list)
    snapshot_seq: int = 0
    snapshot_items: int = 0
    snapshot_tombstones: int = 0
    root: str = ""
    bytes_fetched: int = 0  # raw snapshot bytes assembled
    chunks: int = 0
    chunk_retries: int = 0
    donor_failovers: int = 0
    # Total client-measured request+response bytes across every donor
    # connection AND the delta walk — the number the chaos test compares
    # against a walk-only rebuild.
    wire_bytes: int = 0
    delta_divergent: int = -1  # -1: no delta walk ran
    seconds: float = 0.0
    details: list[str] = field(default_factory=list)


class BootstrapSession:
    """One bootstrap run for one node. Thread-safe introspection via
    ``state`` / ``report``; drive with :meth:`run` (blocking — the cluster
    node wraps it in a daemon thread)."""

    def __init__(
        self,
        engine,
        sync_manager,
        peers: list[str],
        cfg,  # BootstrapConfig
        merkle_engine: str = "auto",
        health=None,  # Optional[PeerHealthMonitor]
        # Applied-state fan-out: list[(key, value|None, ts)] per slab —
        # the cluster node journals these to the WAL and stages them into
        # the device mirror (bootstrap applies bypass the server's event
        # queue, exactly like anti-entropy repairs).
        batch_listener: Optional[Callable[[list], None]] = None,
        # Fires ONCE, the moment verified state is fully applied (or the
        # session commits to the walk fallback): the cluster node reopens
        # the read gate and replays buffered replication frames here.
        on_serving: Optional[Callable[[], None]] = None,
        retry: Optional[RetryPolicy] = None,
    ) -> None:
        self._engine = engine
        self._sync = sync_manager
        self._peers = list(peers)
        self._cfg = cfg
        self._merkle_engine = merkle_engine
        self._health = health
        self._batch_listener = batch_listener
        self._on_serving = on_serving
        self._served = False
        self._retry = retry if retry is not None else BOOTSTRAP_FETCH
        self._stop = threading.Event()
        self._state = "idle"
        self._state_mu = threading.Lock()
        self.report: Optional[BootstrapReport] = None

    # -- introspection --------------------------------------------------------
    @property
    def state(self) -> str:
        with self._state_mu:
            return self._state

    def state_code(self) -> int:
        return STATE_CODES.get(self.state, 0)

    def stop(self) -> None:
        self._stop.set()

    def _enter(self, state: str) -> None:
        with self._state_mu:
            self._state = state
        # Flight recorder: bootstrap phases are the classic "died mid-join"
        # forensic question — the spill's tail names how far the session
        # got (discover/fetch/verify/delta/live/failed).
        from merklekv_tpu.obs.flightrec import record

        record("bootstrap", state=state)

    def _serving(self) -> None:
        """Open the gate exactly once per run (idempotent safety net: the
        runner's finally block calls this too, so a crashed session can
        never leave the node unreadable)."""
        if self._served:
            return
        self._served = True
        if self._on_serving is not None:
            try:
                self._on_serving()
            except Exception:
                pass  # the gate hook must never kill the session

    # -- main -----------------------------------------------------------------
    def run(self, reason: str) -> BootstrapReport:
        report = BootstrapReport(reason=reason)
        self.report = report
        t0 = time.perf_counter()
        metrics = get_metrics()
        try:
            with span("bootstrap", reason=reason) as rec:
                self._run(report)
                rec["mode"] = report.mode
                rec["donor"] = report.donor
                rec["bytes_fetched"] = report.bytes_fetched
                rec["wire_bytes"] = report.wire_bytes
            metrics.inc("bootstrap.completed")
        except Exception as e:
            self._enter("failed")
            report.mode = report.mode or "failed"
            report.details.append(f"bootstrap error: {e!r}")
            metrics.inc("bootstrap.errors")
        finally:
            self._serving()
            report.seconds = time.perf_counter() - t0
        return report

    def _candidates(self) -> list[str]:
        """Donor order: health-up peers first, then unknown/degraded, then
        confirmed-down (a down peer may have just restarted — still worth
        one SNAPMETA before surrendering to the walk)."""
        if self._health is None:
            return list(self._peers)
        order = {"up": 0, "unknown": 1, "degraded": 1, "down": 2}
        status = {h.peer: h.status for h in self._health.snapshot()}
        return sorted(
            self._peers, key=lambda p: order.get(status.get(p, "unknown"), 1)
        )

    def _run(self, report: BootstrapReport) -> None:
        metrics = get_metrics()
        self._enter("discover")
        reachable: list[str] = []
        building: list[str] = []

        def attempt(peer: str, wait_build: bool) -> bool:
            host, _, port_s = peer.rpartition(":")
            client = MerkleKVClient(
                host, int(port_s), timeout=self._retry.op_timeout
            )
            try:
                client.connect()
            except Exception as e:
                report.details.append(f"{peer}: unreachable ({e!r})")
                client.close()
                return False
            if peer not in report.donors_tried:
                report.donors_tried.append(peer)
            if peer not in reachable:
                reachable.append(peer)
            try:
                return self._try_donor(
                    client, peer, report, building, wait_build
                )
            finally:
                report.wire_bytes += client.bytes_sent + client.bytes_received
                client.close()

        def finish_snapshot(peer: str) -> None:
            # Close the post-stamp gap; the donor first, then any other
            # reachable peer — a donor dying right after the last chunk
            # must not leave the delta silently unclosed under a
            # "snapshot" success banner.
            others = [p for p in reachable if p != peer]
            if not any(self._delta(p, report) for p in [peer] + others):
                report.details.append(
                    "delta sync failed against every reachable peer; "
                    "periodic anti-entropy closes the gap"
                )
            self._enter("live")
            report.mode = "snapshot"

        # Pass 1: one SNAPMETA per candidate — a donor mid-build of its
        # first artifact answers "building" and is SET ASIDE, never
        # head-of-line-blocking a donor whose artifact is ready to ship.
        for peer in self._candidates():
            if self._stop.is_set():
                return
            if attempt(peer, wait_build=False):
                finish_snapshot(report.donor)
                return
        # Pass 2: nothing ready anywhere — now it is worth waiting out a
        # background build (bounded) before surrendering to the walk.
        for peer in building:
            if self._stop.is_set():
                return
            if attempt(peer, wait_build=True):
                finish_snapshot(report.donor)
                return
        # No donor could ship a verifiable snapshot: degrade to the plain
        # anti-entropy walk against the first reachable non-suspect peer —
        # the exact rebuild the node would have run without this subsystem.
        metrics.inc("bootstrap.fallbacks")
        self._serving()
        targets = [p for p in reachable if p not in report.suspects]
        # A quarantined donor's DATA plane is still trustworthy for a
        # key-level walk (values are re-hashed locally); prefer clean peers
        # but fall back to suspects rather than not converging at all.
        targets += [p for p in reachable if p in report.suspects]
        targets += [p for p in self._peers if p not in reachable]
        for peer in targets:
            if self._stop.is_set():
                return
            if self._delta(peer, report):
                self._enter("live")
                report.mode = "walk"
                return
        self._enter("failed")
        report.mode = "failed"
        report.details.append("no peer reachable; periodic loop will retry")

    # -- donor transfer -------------------------------------------------------
    def _try_donor(
        self,
        client: MerkleKVClient,
        peer: str,
        report: BootstrapReport,
        building: list[str],
        wait_build: bool,
    ) -> bool:
        """Full FETCH + VERIFY + apply against one donor. True when the
        verified snapshot is installed; False to try the next donor. A
        donor answering "building" is appended to ``building`` (unless
        ``wait_build``, which polls the build out)."""
        from merklekv_tpu.storage import snapshot as snapmod

        metrics = get_metrics()
        try:
            if wait_build:
                seq, _wal_seq, size, stamped_root = (
                    self._snap_meta_poll(client)
                )
            else:
                seq, _wal_seq, size, stamped_root = client.snap_meta()
        except ProtocolError as e:
            if "retry" in str(e).lower():
                if wait_build:
                    # Pass 2 already waited the build bound out; a donor
                    # still answering "building" (persistently failing
                    # ticker — ENOSPC and the like) must NOT re-enter the
                    # building list or the poll never ends and the read
                    # gate never reopens.
                    report.details.append(
                        f"{peer}: snapshot still building past the wait "
                        "bound; giving up on this donor"
                    )
                    return False
                # First artifact building in the donor's background: defer
                # — another candidate may have one ready right now.
                building.append(peer)
                report.details.append(f"{peer}: snapshot building; deferred")
                return False
            # Capability fallback: old peer, no durable storage, or no
            # snapshot on disk — never an integrity signal.
            report.details.append(f"{peer}: cannot serve snapshot ({e})")
            metrics.inc("bootstrap.capability_misses")
            return False
        except (MerkleKVError, OSError) as e:
            report.details.append(f"{peer}: SNAPMETA died ({e!r})")
            return False

        self._enter("fetch")
        blob = self._fetch(client, peer, seq, size, report)
        if blob is None:
            report.donor_failovers += 1
            metrics.inc("bootstrap.donor_failovers")
            return False

        self._enter("verify")
        with span("bootstrap.verify", peer=peer) as rec:
            try:
                snap = snapmod.parse_snapshot_bytes(blob, f"{peer}#snap-{seq}")
                if snap.root_hex != stamped_root:
                    # The file's own stamp disagrees with the advertised
                    # meta — same trust failure as a recompute mismatch.
                    raise snapmod.RootMismatchError(
                        f"{peer}#snap-{seq}", stamped_root, snap.root_hex
                    )
                verified = snapmod.verify_snapshot(
                    snap, engine=self._merkle_engine
                )
            except (
                snapmod.SnapshotCorruptError,
                snapmod.RootMismatchError,
            ) as e:
                # QUARANTINE: a donor whose stamped artifact does not hash
                # to its own stamp is suspect — try the next donor, tell
                # the health table, and refuse to go LIVE on its state.
                report.suspects.append(peer)
                report.details.append(f"{peer}: snapshot rejected ({e})")
                metrics.inc("bootstrap.verify_failures")
                if self._health is not None:
                    self._health.mark_degraded(
                        peer, f"bootstrap snapshot rejected: {e}"
                    )
                return False
            rec["items"] = len(snap.items)
            rec["root"] = verified[:16]

        self._apply(snap)
        report.donor = peer
        report.snapshot_seq = seq
        report.snapshot_items = len(snap.items)
        report.snapshot_tombstones = len(snap.tombstones)
        report.root = verified
        metrics.inc("bootstrap.snapshots_installed")
        # Reads may serve now: everything installed is verified, and the
        # buffered replication frames replay through the same LWW verbs.
        self._serving()
        return True

    # How long DISCOVER waits out a donor answering "snapshot not ready
    # (building); retry": the donor kicked its first artifact to the
    # background ticker rather than blocking the request handler with an
    # O(keyspace) write — a bounded poll here is what keeps a fresh
    # cluster's first rejoin on the bulk path instead of cascading a
    # useless snapshot build onto every donor.
    _BUILD_WAIT_S = 120.0

    def _snap_meta_poll(
        self, client: MerkleKVClient
    ) -> tuple[int, int, int, str]:
        deadline = Deadline(self._BUILD_WAIT_S)
        attempt = 0
        while True:
            try:
                return client.snap_meta()
            except ProtocolError as e:
                if (
                    "retry" not in str(e).lower()
                    or deadline.expired()
                    or self._stop.is_set()
                ):
                    raise
                time.sleep(deadline.clamp(self._retry.backoff(attempt)))
                attempt += 1

    def _fetch(
        self,
        client: MerkleKVClient,
        peer: str,
        seq: int,
        size: int,
        report: BootstrapReport,
    ) -> Optional[bytes]:
        """SNAPCHUNK loop with per-offset retries. The offset is the
        checkpoint: an integrity failure or dead stream refetches only the
        current chunk (reconnecting on transport death), never the
        assembled prefix. Returns None once the donor budget is spent."""
        metrics = get_metrics()
        deadline = self._retry.deadline()
        parts: list[bytes] = []
        offset = 0
        attempts = 0
        while offset < size:
            if self._stop.is_set() or deadline.expired():
                report.details.append(
                    f"{peer}: fetch abandoned at {offset}/{size}"
                )
                return None
            try:
                raw = client.snap_chunk(seq, offset, self._cfg.chunk_bytes)
            except ProtocolError as e:
                # ERROR mid-transfer: the artifact vanished donor-side
                # (restart past the pin TTL) — re-discover elsewhere.
                report.details.append(f"{peer}: chunk refused ({e})")
                return None
            except (ChunkIntegrityError, MerkleKVError, OSError) as e:
                attempts += 1
                report.chunk_retries += 1
                metrics.inc("bootstrap.chunk_retries")
                if attempts >= self._cfg.chunk_retries:
                    report.details.append(
                        f"{peer}: chunk {offset} failed {attempts}x ({e!r})"
                    )
                    return None
                time.sleep(deadline.clamp(self._retry.backoff(attempts - 1)))
                if not isinstance(e, ChunkIntegrityError):
                    # Dead/desynced stream: reconnect before the retry
                    # (the byte counters survive — same client object).
                    try:
                        client.close()
                        client.connect()
                    except Exception:
                        pass  # next snap_chunk raises; retries burn down
                continue
            if not raw:
                # Offset inside the advertised size but EOF on disk: the
                # donor's file is not what SNAPMETA promised.
                report.details.append(
                    f"{peer}: short snapshot ({offset}/{size})"
                )
                return None
            attempts = 0
            parts.append(raw)
            offset += len(raw)
            report.chunks += 1
            report.bytes_fetched += len(raw)
            metrics.inc("bootstrap.chunks")
            metrics.inc("bootstrap.bytes_fetched", len(raw))
        return b"".join(parts)

    # -- install + delta ------------------------------------------------------
    def _apply(self, snap) -> None:
        """Install the verified snapshot through the engine's LWW verbs in
        native batch crossings — conditional installs, so local writes that
        raced ahead of the transfer (and buffered replication frames
        journaled during it) keep winning per-key LWW."""
        ops: list[tuple[bytes, Optional[bytes], int]] = [
            (k, v, ts) for k, v, ts in snap.items
        ] + [(k, None, ts) for k, ts in snap.tombstones]
        for i in range(0, len(ops), _APPLY_SLAB):
            slab = ops[i : i + _APPLY_SLAB]
            flags = self._engine.apply_batch(slab)
            if self._batch_listener is not None:
                applied = [op for op, ok in zip(slab, flags) if ok]
                if applied:
                    try:
                        self._batch_listener(applied)
                    except Exception:
                        pass  # fan-out must not kill the install

    def _delta(self, peer: str, report: BootstrapReport) -> bool:
        """Close the post-stamp gap with one anti-entropy cycle against
        ``peer``. After a verified install the trees agree everywhere but
        the delta, so the bisect walk descends only into it."""
        self._enter("delta")
        host, _, port_s = peer.rpartition(":")
        before_s, before_r = self._sync_bytes()
        try:
            rep = self._sync.sync_once(host, int(port_s))
        except Exception as e:
            report.details.append(f"{peer}: delta sync failed ({e!r})")
            get_metrics().inc("bootstrap.delta_errors")
            return False
        finally:
            after_s, after_r = self._sync_bytes()
            report.wire_bytes += (after_s - before_s) + (after_r - before_r)
        report.delta_divergent = rep.divergent
        report.details.append(
            f"{peer}: delta mode={rep.mode} divergent={rep.divergent}"
        )
        return True

    @staticmethod
    def _sync_bytes() -> tuple[int, int]:
        snap = get_metrics().snapshot()["counters"]
        return snap.get("sync.bytes_sent", 0), snap.get(
            "sync.bytes_received", 0
        )

"""Overload protection: the node-wide degradation ladder and its monitor.

The north star is heavy traffic; this module is what keeps a node ALIVE
under it. Instead of crashing on resource exhaustion — a connection flood
exhausting threads, a hot keyspace exhausting RAM, a full disk killing the
WAL drain — the node walks a degradation ladder and sheds the cheapest
load first:

    live       everything serves.
    shedding   write verbs answer ``ERROR BUSY <why> retry`` (retryable;
               reads, the management plane, and anti-entropy serving stay
               open), and background work (anti-entropy cycles, snapshot
               compaction) defers.
    read_only  write verbs answer ``ERROR READONLY <why>`` — the node
               preserves what it has instead of accepting writes it
               cannot hold or journal.
    draining   read_only + new connections refused BUSY (shutdown).

The ladder's inputs are **watermark signals**, one per resource:

- *memory*: the engine's O(1) approximate resident bytes against
  ``[server] memory_soft_bytes`` / ``memory_hard_bytes`` (soft -> shed
  writes, hard -> read-only), with hysteresis (``recovery_ratio``) so the
  node doesn't flap at the boundary;
- *disk*: :class:`~merklekv_tpu.storage.store.DurableStore` folds its
  free-bytes watermarks and any live ENOSPC/EIO condition into a level
  (see ``DurableStore.overload_level``);
- *admission*: enforced natively (``max_connections``/``max_pipeline`` in
  the server's accept/read path) — it never enters the ladder because a
  refused connection must cost nothing.

The native server enforces the pushed level on the request path; this
module only decides it. Everything is visible where state already flows:
``/healthz`` (``degradation`` field), METRICS (``node.degradation`` line),
the ``node.degradation`` gauge, STATS (``degradation`` + shed counters),
and ``top`` (STATE / SHED/s columns).

Philosophy (after "Asynchronous Merkle Trees", PAPERS.md): the hot path
may deliberately drop work under pressure because the anti-entropy plane
repairs whatever was shed once the node recovers — shedding is safe
exactly because repair is cheap.
"""

from __future__ import annotations

import sys
import threading
from typing import Optional

from merklekv_tpu.utils.tracing import get_metrics

__all__ = [
    "LIVE",
    "SHEDDING",
    "READ_ONLY",
    "DRAINING",
    "LEVEL_NAMES",
    "REASON_CODES",
    "DegradationLadder",
    "OverloadMonitor",
]

# The ladder's rungs — numeric order IS severity order (the ladder takes
# the max across sources), and the codes match the native enum
# (server.h Degradation) and the METRICS ``node.degradation`` line.
LIVE, SHEDDING, READ_ONLY, DRAINING = 0, 1, 2, 3

LEVEL_NAMES = {
    LIVE: "live",
    SHEDDING: "shedding",
    READ_ONLY: "read_only",
    DRAINING: "draining",
}

# Reason string -> native DegradeReason code (rides in the BUSY/READONLY
# error text so clients can tell transient shed from shutdown).
REASON_CODES = {"": 0, "memory": 1, "disk": 2, "draining": 3, "admin": 4}


class DegradationLadder:
    """Thread-safe fold of per-resource degradation signals.

    Each source (``memory``, ``disk``, ``admin``) contributes a level;
    the node's level is the max. The reason reported is the worst
    contributor's, ties broken by source name for determinism.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._sources: dict[str, tuple[int, str]] = {}

    def set_source(self, name: str, level: int, reason: str = "") -> None:
        with self._mu:
            if level <= LIVE:
                self._sources.pop(name, None)
            else:
                self._sources[name] = (int(level), reason or name)

    def state(self) -> tuple[int, str]:
        """(level, reason) of the worst contributor; (LIVE, "") if none."""
        with self._mu:
            if not self._sources:
                return LIVE, ""
            worst = max(
                self._sources.items(), key=lambda kv: (kv[1][0], kv[0])
            )
            return worst[1][0], worst[1][1]

    def level(self) -> int:
        return self.state()[0]

    def name(self) -> str:
        return LEVEL_NAMES.get(self.level(), "live")


class OverloadMonitor:
    """Polls the watermark signals and pushes the folded level natively.

    One daemon thread, cadence ``[server] watermark_interval_seconds``.
    Each tick: read the engine's approximate resident bytes (O(1)), ask
    the durable store for its disk verdict, fold through the ladder with
    per-source hysteresis, and — only on a transition — push the level to
    the native server (one atomic store) and log it loudly. Between
    transitions a tick costs two atomic reads and a statvfs.
    """

    def __init__(
        self,
        ladder: DegradationLadder,
        engine,  # NativeEngine
        server,  # NativeServer
        server_cfg,  # config.ServerConfig
        storage=None,  # Optional[DurableStore]
        interval: Optional[float] = None,
        partition_id: Optional[int] = None,
    ) -> None:
        self._ladder = ladder
        self._engine = engine
        self._server = server
        self._cfg = server_cfg
        self._storage = storage
        # Partitioned cluster mode: ladder flips additionally record
        # partition_degraded / partition_healed flight events naming THIS
        # node's partition — the blackbox signal that an incident is
        # partition-local (one partition's replicas flip) rather than
        # cluster-wide (every partition flips at once).
        self._partition_id = partition_id
        self._interval = (
            interval
            if interval is not None
            else server_cfg.watermark_interval_seconds
        )
        self._mem_level = LIVE  # hysteresis state for the memory signal
        # Test hook (parallel to the engine's MKV_MAX_TOMBS_PER_SHARD):
        # MKV_MAX_ENGINE_BYTES forces the memory HARD watermark — and,
        # when no soft watermark is configured, a soft one at half — so
        # the chaos suite triggers the memory ladder deterministically
        # with a handful of writes instead of gigabytes.
        import os as _os

        env = _os.environ.get("MKV_MAX_ENGINE_BYTES", "")
        self._hard_override: Optional[int] = None
        if env:
            try:
                self._hard_override = max(1, int(env))
            except ValueError:
                self._hard_override = None
        self._pushed: Optional[tuple[int, str]] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "OverloadMonitor":
        if self._thread is None:
            self.poll_once()  # push the initial level before serving
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="mkv-overload-monitor"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop_evt.wait(max(0.02, self._interval)):
            try:
                self.poll_once()
            except Exception:
                # The monitor must never die silently — a dead monitor
                # would freeze the node at its current rung.
                get_metrics().inc("node.overload_monitor_errors")

    # -- evaluation ---------------------------------------------------------
    def poll_once(self) -> int:
        """One evaluation + push; returns the folded level (tests call
        this directly instead of sleeping out the ticker)."""
        self._ladder.set_source(
            "memory", self._memory_level(), "memory"
        )
        if self._storage is not None:
            lvl, why = self._storage.overload_level()
            self._ladder.set_source("disk", lvl, why or "disk")
        level, reason = self._ladder.state()
        if self._pushed != (level, reason):
            prev = self._pushed[0] if self._pushed else LIVE
            self._server.set_degradation(
                level, REASON_CODES.get(reason, REASON_CODES["admin"])
            )
            self._pushed = (level, reason)
            if level != prev:
                get_metrics().inc("node.degradation_changes")
                # Flight recorder: ladder flips are exactly the "what was
                # the node doing before it died" signal a post-mortem
                # timeline starts from.
                from merklekv_tpu.obs.flightrec import record

                record(
                    "degradation",
                    prev=LEVEL_NAMES.get(prev, prev),
                    new=LEVEL_NAMES.get(level, level),
                    reason=reason,
                )
                if self._partition_id is not None:
                    # Partition-scoped view of the same flip: leaving live
                    # degrades ONE partition's capacity, returning heals
                    # it. Boundary crossings only — rung-to-rung moves
                    # while already degraded stay "degradation" events.
                    if prev == LIVE and level > LIVE:
                        get_metrics().inc("partition.degraded_total")
                        record(
                            "partition_degraded",
                            partition=self._partition_id,
                            level=LEVEL_NAMES.get(level, level),
                            reason=reason,
                        )
                    elif prev > LIVE and level == LIVE:
                        get_metrics().inc("partition.healed_total")
                        record(
                            "partition_healed",
                            partition=self._partition_id,
                        )
                print(
                    f"overload: {LEVEL_NAMES.get(prev, prev)} -> "
                    f"{LEVEL_NAMES.get(level, level)}"
                    + (f" ({reason})" if reason else ""),
                    file=sys.stderr,
                    flush=True,
                )
        return level

    def _memory_level(self) -> int:
        """Memory watermark with hysteresis: enter shedding at soft, enter
        read-only at hard, and only recover once usage falls below
        ``watermark * recovery_ratio`` — a node hovering at the boundary
        must not flap BUSY/OK per request."""
        soft = self._cfg.memory_soft_bytes
        hard = self._cfg.memory_hard_bytes
        if self._hard_override is not None:
            hard = self._hard_override
            if not soft:
                soft = max(1, hard // 2)
        if not soft and not hard:
            self._mem_level = LIVE
            return LIVE
        # getattr: NativeEngine exposes _h (None after close — calling
        # through it would FFI a dead pointer); engine doubles without the
        # attribute are simply read. Any failure holds the current rung
        # (never silently freezes it forever: the next tick retries, and
        # repeated failures surface via node.overload_monitor_errors when
        # they escape to the poll loop).
        if getattr(self._engine, "_h", True) is None:
            usage = 0  # closed engine: nothing resident
        else:
            try:
                usage = self._engine.memory_usage()
            except Exception:
                return self._mem_level  # transient: hold the rung
        r = self._cfg.recovery_ratio
        lvl = self._mem_level
        if hard and usage >= hard:
            lvl = READ_ONLY
        elif lvl == READ_ONLY and (not hard or usage < hard * r):
            lvl = SHEDDING  # step down one rung; re-evaluated below
        if lvl == SHEDDING and (not soft or usage < soft * r):
            lvl = LIVE
        if lvl == LIVE and soft and usage >= soft:
            lvl = SHEDDING
        self._mem_level = lvl
        return lvl

    # -- verdicts for background work ---------------------------------------
    def should_pause_background(self) -> bool:
        """Anti-entropy cycles defer while the node is above ANY watermark:
        a cycle allocates leaf maps a memory-pressured node must not, and
        journals repairs a disk-full node cannot."""
        return self._ladder.level() >= SHEDDING

    def memory_pressure(self) -> bool:
        """Snapshot compaction defers only under MEMORY pressure (a
        snapshot materializes the whole keyspace host-side); under DISK
        pressure compaction is exactly what frees WAL segments, so it must
        keep running."""
        return self._mem_level >= SHEDDING

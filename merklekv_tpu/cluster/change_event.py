"""Canonical replication record + wire codecs.

Schema matches the reference ChangeEvent
(/root/reference/src/change_event.rs:59-79):
  {v: u16, op, key: str, val: Optional[bytes], ts: u64 (ns), src: str,
   op_id: 16 bytes (uuid4), prev: Optional[32 bytes], ttl: Optional[u64]}
`val` carries the POST-OP result so application is idempotent
(change_event.rs:17-19).

Codecs (change_event.rs:127-172 analog): CBOR is the wire default; a compact
length-prefixed binary format stands in for bincode; JSON (base64 for bytes)
for debuggability. ``decode_any`` tries CBOR -> binary -> JSON. The CBOR
encoder below emits standard definite-length RFC 8949 items (maps with text
keys, uints, byte/text strings, null), so third-party CBOR tooling can read
events off the wire.

Batch framing: a drained replication batch travels as ONE versioned CBOR
envelope ``{v, src, events: [...]}`` (``encode_batch_cbor``) instead of one
publish per event — the publisher coalesces per key first
(``coalesce_events``: every event carries its post-op value, so the last
SET/DEL per key alone reproduces that key's final state). Receivers use
``decode_events``, which accepts both the envelope and every legacy
single-event format, so mixed-version clusters stay wire-compatible: an
old publisher's single events keep applying here, while an old subscriber
counts a new publisher's envelopes as decode errors and anti-entropy
repairs what it missed (see docs/PROTOCOL.md "Replication framing").
"""

from __future__ import annotations

import base64
import json
import struct
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

__all__ = [
    "OpKind",
    "ChangeEvent",
    "BATCH_ENVELOPE_VERSION",
    "coalesce_events",
    "encode_cbor",
    "decode_cbor",
    "encode_batch_cbor",
    "encode_binary",
    "decode_binary",
    "encode_json",
    "decode_json",
    "decode_any",
    "decode_events",
    "decode_events_meta",
]


class OpKind(str, Enum):
    SET = "set"
    DEL = "del"
    INCR = "incr"
    DECR = "decr"
    APPEND = "append"
    PREPEND = "prepend"
    # Local extension: staged by the native server for device-mirror
    # invalidation; never published on the wire (the reference replicates
    # only the six ops above, replication.rs:197-254).
    TRUNCATE = "truncate"


@dataclass
class ChangeEvent:
    op: OpKind
    key: str
    val: Optional[bytes]  # post-op value; None for deletions
    ts: int  # unix nanoseconds (or logical clock); only ordering matters
    src: str  # originating node id (loop prevention)
    op_id: bytes = field(default_factory=lambda: uuid.uuid4().bytes)
    v: int = 1
    prev: Optional[bytes] = None  # optional 32-byte Merkle hash
    ttl: Optional[int] = None

    def __post_init__(self) -> None:
        if len(self.op_id) != 16:
            raise ValueError("op_id must be 16 bytes")
        if self.prev is not None and len(self.prev) != 32:
            raise ValueError("prev must be 32 bytes")

    @classmethod
    def new(
        cls,
        op: OpKind,
        key: str,
        val: Optional[bytes],
        src: str,
        ts: Optional[int] = None,
    ) -> "ChangeEvent":
        return cls(op=op, key=key, val=val, src=src,
                   ts=time.time_ns() if ts is None else ts)


# ------------------------------------------------------------------ CBOR

def _cbor_head(major: int, arg: int) -> bytes:
    if arg < 24:
        return bytes([(major << 5) | arg])
    if arg < 0x100:
        return bytes([(major << 5) | 24, arg])
    if arg < 0x10000:
        return bytes([(major << 5) | 25]) + struct.pack(">H", arg)
    if arg < 0x100000000:
        return bytes([(major << 5) | 26]) + struct.pack(">I", arg)
    return bytes([(major << 5) | 27]) + struct.pack(">Q", arg)


def _cbor_uint(v: int) -> bytes:
    return _cbor_head(0, v)


def _cbor_bytes(b: bytes) -> bytes:
    return _cbor_head(2, len(b)) + b


def _cbor_text(s: str) -> bytes:
    e = s.encode("utf-8")
    return _cbor_head(3, len(e)) + e


def _cbor_text_or_bytes(s: str) -> bytes:
    """Key/src fields: a valid-UTF-8 string is a standard text item; a
    surrogateescape-decoded raw key (non-UTF-8 wire bytes, from
    replicator._to_event) is emitted as a BYTE string — RFC 8949 requires
    text items to be valid UTF-8, and smuggling raw bytes into one would
    make strict third-party decoders drop the whole event."""
    try:
        e = s.encode("utf-8")
        return _cbor_head(3, len(e)) + e
    except UnicodeEncodeError:
        return _cbor_bytes(s.encode("utf-8", "surrogateescape"))


_CBOR_NULL = b"\xf6"


def _event_map_cbor(ev: ChangeEvent, include_src: bool = True) -> bytes:
    """One event as a CBOR map. Inside a batch envelope ``src`` is carried
    once on the envelope, so per-event maps omit it (include_src=False)."""
    pairs = [
        (b"\x61v", _cbor_uint(ev.v)),
        (b"\x62op", _cbor_text(ev.op.value)),
        (b"\x63key", _cbor_text_or_bytes(ev.key)),
        (b"\x63val", _CBOR_NULL if ev.val is None else _cbor_bytes(ev.val)),
        (b"\x62ts", _cbor_uint(ev.ts)),
    ]
    if include_src:
        pairs.append((b"\x63src", _cbor_text_or_bytes(ev.src)))
    pairs += [
        (b"\x65op_id", _cbor_bytes(ev.op_id)),
        (b"\x64prev", _CBOR_NULL if ev.prev is None else _cbor_bytes(ev.prev)),
        (b"\x63ttl", _CBOR_NULL if ev.ttl is None else _cbor_uint(ev.ttl)),
    ]
    out = _cbor_head(5, len(pairs))
    for k, v in pairs:
        out += k + v
    return out


def encode_cbor(ev: ChangeEvent) -> bytes:
    return _event_map_cbor(ev)


# ------------------------------------------------------------ batch frame

# Version of the batch envelope FORMAT (distinct from the per-event v
# field): receivers refuse unknown versions loudly instead of misapplying
# half-understood frames.
BATCH_ENVELOPE_VERSION = 1


def coalesce_events(
    events: list[ChangeEvent],
) -> tuple[list[ChangeEvent], int]:
    """Per-key coalescing for one wire frame: a later SET/DEL on a key
    supersedes every earlier op on it — safe because events carry POST-OP
    values, so the last event alone reproduces the key's final state (and
    receivers are per-key LWW anyway). Returns (kept events in stable
    order, number coalesced away)."""
    last: dict[str, int] = {}
    for i, ev in enumerate(events):
        last[ev.key] = i
    kept = [ev for i, ev in enumerate(events) if last[ev.key] == i]
    return kept, len(events) - len(kept)


def encode_batch_cbor(
    events: list[ChangeEvent],
    src: str,
    hwm_seq: Optional[int] = None,
    hwm_ts: Optional[int] = None,
    trace: Optional[str] = None,
) -> bytes:
    """Batch envelope ``{v, src, events: [...]}``: one wire frame for a
    whole drained batch. ``src`` rides on the envelope once; per-event maps
    omit it (the decoder reinstates it).

    Optional additive fields (same envelope version — old decoders ignore
    unknown map keys):

    - ``hseq``/``hts``: the publisher's **publish high-water mark** —
      cumulative events put on the wire INCLUDING this frame, and the
      publish wall clock (unix ns). Appliers derive per-peer
      ``replication.lag_events`` / ``replication.lag_ms`` from them
      (obs/lag.py).
    - ``tc``: a causal trace-context token (obs/tracewire.py) so a traced
      write's replication apply stitches into the originating trace.
    """
    body = bytearray(_cbor_head(4, len(events)))
    for ev in events:
        body += _event_map_cbor(ev, include_src=False)
    extra: list[tuple[bytes, bytes]] = []
    if hwm_seq is not None:
        extra.append((b"\x64hseq", _cbor_uint(hwm_seq)))
    if hwm_ts is not None:
        extra.append((b"\x63hts", _cbor_uint(hwm_ts)))
    if trace:
        extra.append((b"\x62tc", _cbor_text(trace)))
    out = bytearray(_cbor_head(5, 3 + len(extra)))
    out += b"\x61v" + _cbor_uint(BATCH_ENVELOPE_VERSION)
    out += b"\x63src" + _cbor_text_or_bytes(src)
    for k, v in extra:
        out += k + v
    out += b"\x66events" + bytes(body)
    return bytes(out)


class _CborReader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("truncated CBOR")
        b = self.data[self.pos : self.pos + n]
        self.pos += n
        return b

    def _head(self) -> tuple[int, int]:
        b = self._take(1)[0]
        major, info = b >> 5, b & 0x1F
        if info < 24:
            return major, info
        if info == 24:
            return major, self._take(1)[0]
        if info == 25:
            return major, struct.unpack(">H", self._take(2))[0]
        if info == 26:
            return major, struct.unpack(">I", self._take(4))[0]
        if info == 27:
            return major, struct.unpack(">Q", self._take(8))[0]
        raise ValueError(f"unsupported CBOR info {info}")

    def item(self):
        start = self.pos
        b = self.data[self.pos] if self.pos < len(self.data) else None
        if b is None:
            raise ValueError("truncated CBOR")
        if b == 0xF6:  # null
            self.pos += 1
            return None
        if b == 0xF4:
            self.pos += 1
            return False
        if b == 0xF5:
            self.pos += 1
            return True
        major, arg = self._head()
        if major == 0:
            return arg
        if major == 1:
            return -1 - arg
        if major == 2:
            return self._take(arg)
        if major == 3:
            # Lenient on inbound text (a peer's corrupt bytes degrade to a
            # representable key instead of killing the decode); our own
            # emitter never produces invalid text items (_cbor_text_or_bytes).
            return self._take(arg).decode("utf-8", "surrogateescape")
        if major == 4:
            return [self.item() for _ in range(arg)]
        if major == 5:
            return {self.item(): self.item() for _ in range(arg)}
        raise ValueError(f"unsupported CBOR major {major} at {start}")


def decode_cbor(data: bytes) -> ChangeEvent:
    reader = _CborReader(data)
    m = reader.item()
    if not isinstance(m, dict):
        raise ValueError("CBOR event must be a map")
    return _from_map(m)


def _as_key_str(x) -> str:
    """key/src arrive as text items, or byte strings for non-UTF-8 keys
    (see _cbor_text_or_bytes); both normalize to the surrogateescape str
    form the rest of the pipeline uses."""
    if isinstance(x, (bytes, bytearray)):
        return bytes(x).decode("utf-8", "surrogateescape")
    return x


def _from_map(m: dict) -> ChangeEvent:
    val = m.get("val")
    if val is not None and not isinstance(val, (bytes, bytearray)):
        # A corrupt frame can decode "val" into a non-bytes CBOR item;
        # letting it through would blow up deep in the applier's FFI
        # instead of at the decode boundary where errors are counted.
        raise ValueError(f"event val must be bytes, got {type(val).__name__}")
    try:
        return ChangeEvent(
            v=int(m["v"]),
            op=OpKind(m["op"]),
            key=_as_key_str(m["key"]),
            val=None if val is None else bytes(val),
            ts=int(m["ts"]),
            src=_as_key_str(m["src"]),
            op_id=bytes(m["op_id"]),
            prev=None if m.get("prev") is None else bytes(m["prev"]),
            ttl=None if m.get("ttl") is None else int(m["ttl"]),
        )
    except (KeyError, TypeError) as e:
        raise ValueError(f"malformed event map: {e}") from e


# ---------------------------------------------------------------- binary

_BIN_MAGIC = b"MKB1"


def encode_binary(ev: ChangeEvent) -> bytes:
    """Compact fixed-order binary codec (bincode-role analog)."""
    key = ev.key.encode("utf-8", "surrogateescape")
    src = ev.src.encode("utf-8", "surrogateescape")
    out = bytearray(_BIN_MAGIC)
    op_code = list(OpKind).index(ev.op)
    out += struct.pack("<HBQ", ev.v, op_code, ev.ts)
    out += struct.pack("<I", len(key)) + key
    out += struct.pack("<I", len(src)) + src
    out += ev.op_id
    if ev.val is None:
        out += b"\x00"
    else:
        out += b"\x01" + struct.pack("<I", len(ev.val)) + ev.val
    out += b"\x00" if ev.prev is None else b"\x01" + ev.prev
    out += b"\x00" if ev.ttl is None else b"\x01" + struct.pack("<Q", ev.ttl)
    return bytes(out)


def decode_binary(data: bytes) -> ChangeEvent:
    if data[:4] != _BIN_MAGIC:
        raise ValueError("bad magic")
    pos = 4

    def take(n: int) -> bytes:
        nonlocal pos
        if pos + n > len(data):
            raise ValueError("truncated binary event")
        b = data[pos : pos + n]
        pos += n
        return b

    v, op_code, ts = struct.unpack("<HBQ", take(11))
    (klen,) = struct.unpack("<I", take(4))
    key = take(klen).decode("utf-8", "surrogateescape")
    (slen,) = struct.unpack("<I", take(4))
    src = take(slen).decode("utf-8", "surrogateescape")
    op_id = take(16)
    val = None
    if take(1) == b"\x01":
        (vlen,) = struct.unpack("<I", take(4))
        val = take(vlen)
    prev = take(32) if take(1) == b"\x01" else None
    ttl = struct.unpack("<Q", take(8))[0] if take(1) == b"\x01" else None
    return ChangeEvent(v=v, op=list(OpKind)[op_code], key=key, val=val,
                       ts=ts, src=src, op_id=op_id, prev=prev, ttl=ttl)


# ------------------------------------------------------------------ JSON

def encode_json(ev: ChangeEvent) -> bytes:
    def b64(b: Optional[bytes]):
        return None if b is None else base64.b64encode(b).decode()

    return json.dumps(
        {
            "v": ev.v,
            "op": ev.op.value,
            "key": ev.key,
            "val": b64(ev.val),
            "ts": ev.ts,
            "src": ev.src,
            "op_id": b64(ev.op_id),
            "prev": b64(ev.prev),
            "ttl": ev.ttl,
        },
        separators=(",", ":"),
    ).encode("utf-8")


def decode_json(data: bytes) -> ChangeEvent:
    m = json.loads(data.decode("utf-8"))
    if not isinstance(m, dict):
        raise ValueError("JSON event must be an object")

    def u64(x):
        return None if x is None else base64.b64decode(x)

    m = dict(m)
    m["val"] = u64(m.get("val"))
    m["op_id"] = u64(m.get("op_id"))
    m["prev"] = u64(m.get("prev"))
    return _from_map(m)


def decode_any(data: bytes) -> ChangeEvent:
    """CBOR -> binary -> JSON, like the reference's decode_any
    (change_event.rs:159-172)."""
    for dec in (decode_cbor, decode_binary, decode_json):
        try:
            return dec(data)
        except Exception:
            continue
    raise ValueError("undecodable change event")


def _events_from_envelope(m: dict) -> list[ChangeEvent]:
    v = m.get("v")
    if v != BATCH_ENVELOPE_VERSION:
        raise ValueError(f"unsupported batch envelope version {v!r}")
    evs = m.get("events")
    if not isinstance(evs, list):
        raise ValueError("batch envelope 'events' must be an array")
    src = _as_key_str(m.get("src", ""))
    out = []
    for em in evs:
        if not isinstance(em, dict):
            raise ValueError("batch envelope event must be a map")
        if "src" not in em:
            em = dict(em)
            em["src"] = src
        out.append(_from_map(em))
    return out


def decode_events(data: bytes) -> list[ChangeEvent]:
    """Replication inbound decode: a batch envelope yields its events; any
    legacy single-event payload (CBOR/binary/JSON) yields a one-event list
    — old publishers stay wire-compatible with batching subscribers.
    Raises ValueError for undecodable frames AND for envelopes of an
    unknown version or malformed shape (a half-understood frame must be
    counted and dropped whole, never partially applied)."""
    events, _meta = decode_events_meta(data)
    return events


def decode_events_meta(data: bytes) -> tuple[list[ChangeEvent], dict]:
    """``decode_events`` plus the envelope's additive metadata: ``src``,
    the publish high-water mark (``hseq``/``hts``) and the causal trace
    token (``tc``) when present. Legacy single-event payloads yield the
    event's own ``src`` and no HWM."""
    m = None
    try:
        m = _CborReader(data).item()
    except Exception:
        pass
    if isinstance(m, dict) and "events" in m:
        events = _events_from_envelope(m)
        meta: dict = {"src": _as_key_str(m.get("src", ""))}
        if isinstance(m.get("hseq"), int):
            meta["hseq"] = m["hseq"]
        if isinstance(m.get("hts"), int):
            meta["hts"] = m["hts"]
        tc = m.get("tc")
        if isinstance(tc, str):
            meta["tc"] = tc
        return events, meta
    ev = decode_any(data)
    return [ev], {"src": ev.src}

"""MQTT 3.1.1 transport: join a real MQTT deployment as the event fabric.

The reference replicates through any MQTT broker (rumqttc -> mosquitto,
/root/reference/src/replication.rs:115-143). The default fabric here is the
self-hosted length-framed TcpBroker (transport.py) — but a node configured
with ``[replication] transport = "mqtt"`` speaks actual MQTT 3.1.1 wire
frames, so it can join an existing mosquitto/EMQX/HiveMQ deployment (QoS-0;
the anti-entropy backstop repairs drops, same as the framed fabric).

Implemented subset (all of what replication needs):
  CONNECT/CONNACK (clean session, optional username/password),
  PUBLISH QoS-0 in both directions, SUBSCRIBE/SUBACK with a trailing
  multi-level wildcard, PINGREQ/PINGRESP keepalive, DISCONNECT.

``MqttBroker`` is a frame-accurate MQTT 3.1.1 broker (QoS-0 fan-out,
'#'/'+' filters): CLI-runnable via ``python -m merklekv_tpu.broker
--protocol mqtt`` so an all-MQTT cluster runs self-contained, and used
in-process by the interop tests (no external mosquitto in this image).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Optional

from merklekv_tpu.cluster.retry import TRANSPORT_HEAL
from merklekv_tpu.cluster.transport import (
    _dead_socket,
    _drain_outbox,
    _enable_tcp_keepalive,
    _enlarge_sock_buffers,
    _heal_link,
    _publish_or_queue,
)
from merklekv_tpu.utils.tracing import get_metrics

__all__ = ["MqttTransport", "MqttBroker", "StubMqttBroker"]

Callback = Callable[[str, bytes], None]

# Packet types (high nibble of the fixed header).
_CONNECT = 0x10
_CONNACK = 0x20
_PUBLISH = 0x30
_SUBSCRIBE = 0x82  # QoS-1 control packet per spec (required flags 0b0010)
_SUBACK = 0x90
_PUBACK = 0x40
_PUBREC = 0x50
_PUBREL = 0x60  # client frame arrives with required flags 0b0010 (0x62)
_PUBCOMP = 0x70
_PINGREQ = 0xC0
_PINGRESP = 0xD0
_DISCONNECT = 0xE0


def _encode_varlen(n: int) -> bytes:
    out = bytearray()
    while True:
        byte = n % 128
        n //= 128
        if n:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _utf8(s: str) -> bytes:
    e = s.encode("utf-8")
    return struct.pack(">H", len(e)) + e


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Exactly ``n`` bytes, or None on EOF/error.

    A recv DEADLINE is not EOF: an idle-but-healthy link (slow broker,
    PINGRESP delayed under load) raises ``socket.timeout`` to the caller
    when nothing has been read yet — the caller decides whether the quiet
    crossed the missed-PINGRESP deadline. A timeout MID-read returns None:
    the stream is torn between frames and only a teardown realigns it."""
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if buf:
                return None
            raise
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def _read_packet(sock: socket.socket) -> Optional[tuple[int, bytes]]:
    """One MQTT control packet -> (fixed header byte, payload bytes).

    Raises ``socket.timeout`` only while waiting for a packet to START
    (idle link); a stall mid-packet returns None (stream misaligned)."""
    head = _read_exact(sock, 1)  # socket.timeout here = idle, propagate
    if head is None:
        return None
    try:
        # Remaining Length: up to 4 varint bytes.
        mult, length = 1, 0
        for _ in range(4):
            b = _read_exact(sock, 1)
            if b is None:
                return None
            length += (b[0] & 0x7F) * mult
            if not (b[0] & 0x80):
                break
            mult *= 128
        else:
            return None  # malformed varint
        body = _read_exact(sock, length) if length else b""
    except socket.timeout:
        return None  # stalled mid-packet: only a reconnect realigns
    if body is None:
        return None
    return head[0], body


def _topic_matches(filt: str, topic: str) -> bool:
    """MQTT 3.1.1 filter matching ('#' multi-level, '+' single-level;
    '#' also matches the parent level, per spec 4.7.1.2)."""
    if filt == topic:
        return True
    fparts = filt.split("/")
    tparts = topic.split("/")
    for i, fp in enumerate(fparts):
        if fp == "#":
            return True
        if i >= len(tparts):
            return False
        if fp != "+" and fp != tparts[i]:
            return False
    return len(fparts) == len(tparts)


class MqttTransport:
    """Transport (transport.py Protocol) over MQTT 3.1.1, QoS-0."""

    # Same heal policy as TcpTransport (cluster/retry.py): first retry
    # almost immediately, cap below the anti-entropy interval. The legacy
    # knobs stay as the per-instance test override hook.
    _policy = TRANSPORT_HEAL
    _BACKOFF_FIRST = TRANSPORT_HEAL.first_delay
    _BACKOFF_MAX = TRANSPORT_HEAL.max_delay

    def __init__(
        self,
        host: str,
        port: int = 1883,
        client_id: str = "",
        username: str = "",
        password: str = "",
        keepalive: int = 30,
        timeout: float = 10.0,
    ) -> None:
        self._host, self._port, self._timeout = host, port, timeout
        self._client_id = client_id or f"mkv-{id(self):x}"
        self._username, self._password = username, password
        self._subs: list[tuple[str, Callback]] = []
        self._mu = threading.Lock()
        self._send_mu = threading.Lock()
        self._closed = False
        self._keepalive = keepalive
        self.callback_errors = 0
        self.reconnects = 0
        self._outbox = deque()
        self._outbox_mu = threading.Lock()
        self.outbox_dropped = 0
        self.link_down = False
        self._packet_id = 0
        self._last_inbound = time.monotonic()

        try:
            self._sock = self._dial_and_handshake()
        except OSError:
            # Broker down at boot (ConnectionError from a refused CONNACK
            # included): start degraded; the reader's heal loop dials,
            # handshakes, and resubscribes with backoff — node-before-
            # broker startup ordering is supported.
            get_metrics().inc("transport.start_degraded")
            self._sock = _dead_socket()
            self.link_down = True

        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self._pinger = threading.Thread(target=self._ping_loop, daemon=True)
        self._pinger.start()

    def _dial_and_handshake(self) -> socket.socket:
        """TCP connect + CONNECT/CONNACK. Raises on refusal."""
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        if sock.getsockname() == sock.getpeername():
            # TCP self-connect while the broker is down (see
            # transport.TcpTransport._connect) — fail the attempt.
            sock.close()
            raise ConnectionRefusedError("self-connect (broker down)")
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _enlarge_sock_buffers(sock)  # burst headroom (transport.py note)
        # Kernel-level liveness too: with keepalive=0 (app-level keepalive
        # disabled per spec) this is the ONLY silent-partition detection.
        _enable_tcp_keepalive(sock)
        flags = 0x02  # clean session
        payload = _utf8(self._client_id)
        if self._username:
            flags |= 0x80
            payload += _utf8(self._username)
            if self._password:
                flags |= 0x40
                payload += _utf8(self._password)
        var = (
            _utf8("MQTT") + bytes([4, flags])
            + struct.pack(">H", self._keepalive)
        )
        body = var + payload
        sock.sendall(bytes([_CONNECT]) + _encode_varlen(len(body)) + body)
        try:
            pkt = _read_packet(sock)
        except socket.timeout:
            pkt = None  # no CONNACK inside the dial timeout
        if pkt is None or (pkt[0] & 0xF0) != _CONNACK:
            sock.close()
            raise ConnectionError("MQTT: no CONNACK")
        if len(pkt[1]) < 2 or pkt[1][1] != 0:
            rc = pkt[1][1] if len(pkt[1]) >= 2 else -1
            sock.close()
            raise ConnectionError(f"MQTT: connection refused rc={rc}")
        # Recv PROBE interval, not a teardown deadline: the pinger elicits a
        # PINGRESP every keepalive/2, so a healthy link has inbound traffic
        # at that cadence. Each recv timeout only wakes the read loop to
        # CHECK the missed-PINGRESP deadline (2x keepalive since the last
        # inbound byte) — a slow-but-alive broker no longer costs a full
        # teardown/re-handshake/resubscribe per quiet spell, while a silent
        # partition (no RST — power loss, NAT drop) is still detected and
        # reconnected within ~2x keepalive. keepalive=0 means keepalive
        # DISABLED per spec 3.1.2.10 — no deadline then.
        sock.settimeout(
            max(self._keepalive / 2.0, 1.0) if self._keepalive else None
        )
        self._last_inbound = time.monotonic()
        return sock

    def _reconnect(self) -> bool:
        """Re-dial + handshake + re-SUBSCRIBE every live subscription —
        clean-session brokers forget filters across connections, so a
        reconnect without resubscribe would heal the link but stay deaf
        (the reference's rumqttc resubscribes the same way)."""
        return _heal_link(self, self._dial_and_handshake, self._on_healed)

    def _on_healed(self) -> None:
        # Resubscribe FIRST (a clean-session broker forgot the filters),
        # then flush events queued during the outage.
        with self._mu:
            prefixes = [p for p, _ in self._subs]
        for prefix in prefixes:
            self._send_subscribe(prefix)
        _drain_outbox(self)

    def _send_subscribe(self, topic_prefix: str) -> None:
        with self._mu:
            self._packet_id = self._packet_id % 0xFFFF + 1
            pid = self._packet_id
        body = struct.pack(">H", pid) + _utf8(topic_prefix + "/#") + b"\x00"
        with self._send_mu:
            try:
                self._send_packet_locked(_SUBSCRIBE, body)
            except OSError:
                pass  # the read loop notices the dead link and reconnects

    # -- Transport interface --------------------------------------------------
    def publish(self, topic: str, payload: bytes) -> None:
        _publish_or_queue(self, topic, payload)

    @property
    def outbox_depth(self) -> int:
        """Events queued awaiting a broker heal (the outbox-depth gauge)."""
        with self._outbox_mu:
            return len(self._outbox)

    def _wire_send(self, topic: str, payload: bytes) -> None:
        body = _utf8(topic) + payload  # QoS-0: no packet id
        with self._send_mu:
            self._send_packet_locked(_PUBLISH, body)

    def subscribe(self, topic_prefix: str, callback: Callback) -> None:
        with self._mu:
            self._subs.append((topic_prefix, callback))
        # '#' matches the prefix level itself and everything below it —
        # the "{prefix}/events/#" shape the reference subscribes
        # (replication.rs:142-143).
        self._send_subscribe(topic_prefix)

    def unsubscribe(self, callback: Callback) -> None:
        with self._mu:
            self._subs = [(p, c) for p, c in self._subs if c is not callback]

    def close(self) -> None:
        self._closed = True
        with self._send_mu:
            try:
                self._send_packet_locked(_DISCONNECT, b"")
            except OSError:
                pass
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    # -- internals ------------------------------------------------------------
    def _send_packet(self, header: int, body: bytes) -> None:
        with self._send_mu:
            self._send_packet_locked(header, body)

    def _send_packet_locked(self, header: int, body: bytes) -> None:
        try:
            self._sock.sendall(
                bytes([header]) + _encode_varlen(len(body)) + body
            )
        except OSError:
            # A failed sendall may have written PART of the frame; the
            # stream is misaligned and every later write would feed the
            # broker garbage. Poison the socket so the read loop reconnects.
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise

    def _ping_loop(self) -> None:
        if not self._keepalive:
            return  # keepalive=0: disabled per spec
        interval = max(self._keepalive // 2, 1)
        while not self._closed:
            time.sleep(interval)
            if self._closed:
                return
            with self._send_mu:
                try:
                    self._send_packet_locked(_PINGREQ, b"")
                except OSError:
                    # Dead link: the read loop owns reconnection; keep the
                    # pinger alive so keepalive resumes on the new socket.
                    continue

    def _read_loop(self) -> None:
        while not self._closed:
            try:
                pkt = _read_packet(self._sock)
            except socket.timeout:
                # Quiet link, not a condemned one: only reconnect once the
                # missed-PINGRESP deadline (2x keepalive without ANY
                # inbound byte) has passed — a healthy-but-slow broker just
                # waits for the next PINGRESP instead of paying a teardown.
                if self._keepalive and (
                    time.monotonic() - self._last_inbound
                    > 2.0 * self._keepalive
                ):
                    get_metrics().inc("transport.pingresp_misses")
                    pkt = None  # condemned: fall through to reconnect
                else:
                    get_metrics().inc("transport.slow_broker_waits")
                    continue
            if pkt is None:
                if self._closed or not self._reconnect():
                    return
                continue
            self._last_inbound = time.monotonic()
            header, body = pkt
            ptype = header & 0xF0
            if ptype != _PUBLISH:
                continue  # CONNACK dups / SUBACK / PINGRESP need no action
            qos = (header >> 1) & 0x03
            if len(body) < 2:
                continue
            (tlen,) = struct.unpack(">H", body[:2])
            if len(body) < 2 + tlen:
                continue
            topic = body[2 : 2 + tlen].decode("utf-8", "surrogateescape")
            off = 2 + tlen
            if qos:
                off += 2  # packet id (broker may deliver QoS>0 publishes)
            payload = body[off:]
            with self._mu:
                subs = list(self._subs)
            for prefix, cb in subs:
                if topic.startswith(prefix):
                    try:
                        cb(topic, payload)
                    except Exception:
                        self.callback_errors += 1


class MqttBroker:
    """Frame-accurate MQTT 3.1.1 broker (QoS-0 fan-out).

    Speaks real wire frames on real sockets: CONNECT->CONNACK,
    SUBSCRIBE->SUBACK, PUBLISH fan-out honoring '#'/'+' filters,
    PINGREQ->PINGRESP. No retained messages, sessions, or QoS>0 flows —
    the event fabric is QoS-0 by design (anti-entropy repairs drops)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()
        self._mu = threading.Lock()
        # cid -> (socket, send lock, [topic filters], {in-flight QoS-2 pids})
        self._clients: dict[
            int, tuple[socket.socket, threading.Lock, list, set]
        ] = {}
        self._next = 0
        self._closed = False
        self.connects = 0
        self.publishes = 0
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _enlarge_sock_buffers(sock)
            with self._mu:
                cid = self._next
                self._next += 1
                self._clients[cid] = (sock, threading.Lock(), [], set())
            threading.Thread(
                target=self._serve, args=(cid, sock), daemon=True
            ).start()

    def _serve(self, cid: int, sock: socket.socket) -> None:
        try:
            while True:
                pkt = _read_packet(sock)
                if pkt is None:
                    break
                if not self._handle_packet(cid, *pkt):
                    break
        except Exception:
            # A malformed frame must cost the SENDER its connection, never
            # the broker: fall through to the cleanup either way.
            pass
        self._drop(cid)

    def _handle_packet(self, cid: int, header: int, body: bytes) -> bool:
        """One control packet; False ends the connection."""
        ptype = header & 0xF0
        if ptype == _CONNECT & 0xF0:
            self.connects += 1
            self._send(cid, bytes([_CONNACK, 2, 0, 0]))
        elif ptype == _SUBSCRIBE & 0xF0:
            pid = body[:2]
            filters, rcs = [], b""
            off = 2
            while off + 2 <= len(body):
                (flen,) = struct.unpack(">H", body[off : off + 2])
                f = body[off + 2 : off + 2 + flen].decode("utf-8")
                off += 2 + flen + 1  # + requested QoS byte
                filters.append(f)
                rcs += b"\x00"  # granted QoS 0
            with self._mu:
                if cid in self._clients:
                    self._clients[cid][2].extend(filters)
            suback = pid + rcs
            self._send(
                cid,
                bytes([_SUBACK]) + _encode_varlen(len(suback)) + suback,
            )
        elif ptype == _PUBLISH:
            self.publishes += 1
            qos = (header >> 1) & 0x03
            (tlen,) = struct.unpack(">H", body[:2])
            topic = body[2 : 2 + tlen].decode("utf-8", "surrogateescape")
            payload_off = 2 + tlen
            if qos:
                # QoS>0 sender (e.g. mosquitto_pub -q 1): acknowledge, and
                # strip the packet id so subscribers get a clean QoS-0
                # body — leaving it would prepend 2 stray bytes to every
                # fanned-out payload.
                pid_bytes = body[payload_off : payload_off + 2]
                payload_off += 2
                if qos == 1:
                    self._send(cid, bytes([_PUBACK, 2]) + pid_bytes)
                else:  # QoS 2: PUBREC now, PUBCOMP on the sender's PUBREL
                    # Exactly-once inbound half: a DUP re-send of a packet
                    # id still in flight (the sender lost our PUBREC) must
                    # be re-acked but NOT fanned out twice. The pid clears
                    # on PUBREL, freeing it for reuse per spec.
                    (pid,) = struct.unpack(">H", pid_bytes)
                    with self._mu:
                        entry = self._clients.get(cid)
                        dup = entry is not None and pid in entry[3]
                        if entry is not None:
                            entry[3].add(pid)
                    self._send(cid, bytes([_PUBREC, 2]) + pid_bytes)
                    if dup:
                        return True  # already fanned out on first receipt
            out_body = (
                body if not qos else body[: 2 + tlen] + body[payload_off:]
            )
            frame = (
                bytes([_PUBLISH]) + _encode_varlen(len(out_body)) + out_body
            )
            with self._mu:
                targets = list(self._clients.items())
            for tid, (_s, _lk, filters, _pids) in targets:
                if any(_topic_matches(f, topic) for f in filters):
                    self._send(tid, frame)
        elif ptype == _PUBREL & 0xF0:
            if len(body) >= 2:
                (pid,) = struct.unpack(">H", body[:2])
                with self._mu:
                    entry = self._clients.get(cid)
                    if entry is not None:
                        entry[3].discard(pid)
            self._send(cid, bytes([_PUBCOMP, 2]) + body[:2])
        elif ptype == _PINGREQ & 0xF0:
            self._send(cid, bytes([_PINGRESP, 0]))
        elif ptype == _DISCONNECT & 0xF0:
            return False
        return True

    def _send(self, cid: int, frame: bytes) -> None:
        with self._mu:
            entry = self._clients.get(cid)
        if entry is None:
            return
        sock, lock = entry[0], entry[1]
        try:
            with lock:
                sock.sendall(frame)
        except OSError:
            self._drop(cid)

    def _drop(self, cid: int) -> None:
        with self._mu:
            entry = self._clients.pop(cid, None)
        if entry is not None:
            try:
                entry[0].close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            # shutdown BEFORE close — see TcpBroker.close: the blocked
            # accept() otherwise keeps the port in LISTEN forever.
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._mu:
            entries = list(self._clients.values())
            self._clients.clear()
        for s, *_rest in entries:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


# Historical name from when the broker lived test-side only.
StubMqttBroker = MqttBroker

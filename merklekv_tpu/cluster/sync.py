"""Anti-entropy: make the local store converge to a remote peer.

Reference analog: /root/reference/src/sync.rs. Its hot loops are pathological:
snapshotting rebuilds the Merkle tree per insert (O(n^2 log n) hashing,
sync.rs:104-119) and every remote key is GET over a FRESH TCP connection
(sync.rs:192-214). Here:

  - the local snapshot is one native-engine export (sorted, no hashing on
    insert);
  - the remote snapshot is one connection: SCAN + batched MGET;
  - leaf hashing is batched — hashlib for small keyspaces, the TPU engine
    (one vmapped SHA-256 program) beyond a threshold;
  - the diff is the device multi-replica comparison (merkle/diff.py);
  - the periodic loop is actually wired (the reference's start_sync_loop is
    dead code, sync.rs:90-99).

Semantics match sync_once: one-way local := remote for every divergent key
(sync.rs:74-83), including deletion of local-only keys.

Transfer strategy (the fix for the reference's core flaw — its README
documents an O(log n) hash-walk, README.md:310-372, but the code ships the
entire keyspace as values on every divergence, sync.rs:150-214):

  1. root compare — equal roots, zero transfer;
  2. LEAFHASHES — fetch per-key digests (32 bytes/key, not values), diff,
     then MGET only the divergent keys; bandwidth is proportional to
     divergence, not keyspace size;
  3. ``--full`` (or a peer without LEAFHASHES) — full snapshot transfer,
     the reference behavior, kept as an explicit escape hatch;
  4. ``--verify`` — re-compare Merkle roots after repair; mismatch raises.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.merkle.encoding import leaf_hash
from merklekv_tpu.native_bindings import NativeEngine
from merklekv_tpu.utils.tracing import get_metrics, span

__all__ = ["SyncManager", "SyncReport", "MultiSyncReport"]

# Below this many union keys the device round-trip costs more than hashlib
# (measured: a 10K-key cycle is ~2.7x faster on the host path even with a
# local chip's dispatch latency amortized — batched SHA-256 only wins once
# the keyspace is large enough to fill the device). Deployments with
# different host/device latency can tune MKV_DEVICE_THRESHOLD.
_DEVICE_THRESHOLD = int(os.environ.get("MKV_DEVICE_THRESHOLD", 1 << 16))


@dataclass
class MultiSyncReport:
    peers: list[str] = field(default_factory=list)
    union_keys: int = 0
    divergent_union: int = 0  # keys where ANY replica disagrees
    # peer -> divergence count vs local; unreachable peers are absent.
    per_peer_divergent: dict[str, int] = field(default_factory=dict)
    set_keys: int = 0
    deleted_keys: int = 0  # keys removed because a peer's tombstone won LWW
    values_fetched: int = 0
    seconds: float = 0.0
    details: list[str] = field(default_factory=list)


@dataclass
class SyncReport:
    peer: str = ""
    remote_keys: int = 0
    local_keys: int = 0
    divergent: int = 0
    set_keys: int = 0
    deleted_keys: int = 0
    values_fetched: int = 0  # values transferred (== divergent when hash-first)
    mode: str = ""  # "noop" | "hash-first" | "full" | "full-fallback"
    verified: Optional[bool] = None  # post-sync root recheck (--verify)
    seconds: float = 0.0
    details: list[str] = field(default_factory=list)


def _leaf_map_device(items: list[tuple[bytes, bytes]]) -> dict[bytes, bytes]:
    from merklekv_tpu.utils.jaxenv import ensure_platform

    ensure_platform()
    from merklekv_tpu.merkle.jax_engine import leaf_digests
    from merklekv_tpu.ops.sha256 import digests_to_bytes

    import numpy as np

    digests = leaf_digests([k for k, _ in items], [v for _, v in items])
    return dict(zip((k for k, _ in items), digests_to_bytes(np.asarray(digests))))


def _leaf_map(items: list[tuple[bytes, bytes]], use_device: bool) -> dict[bytes, bytes]:
    if use_device:
        return _leaf_map_device(items)
    return {k: leaf_hash(k, v) for k, v in items}


def _decode_leaf_map(
    raw: dict[str, tuple[Optional[str], int]]
) -> dict[bytes, tuple[Optional[bytes], int]]:
    """LEAFHASHES wire payload -> {key bytes: (digest bytes | None, ts)}.

    A None digest is a TOMBSTONE: the peer deleted the key at ts, and that
    deletion competes in LWW arbitration like any write."""
    return {
        k.encode("utf-8", "surrogateescape"): (
            bytes.fromhex(h) if h is not None else None,
            ts,
        )
        for k, (h, ts) in raw.items()
    }


class SyncManager:
    def __init__(
        self,
        engine: NativeEngine,
        device: str = "auto",  # "auto" | "cpu" | "tpu"
        mget_batch: int = 512,
        timeout: float = 30.0,
        repair_listener=None,  # Callable[[bytes, Optional[bytes]], None]
    ) -> None:
        self._engine = engine
        self._device = device
        self._mget_batch = mget_batch
        self._timeout = timeout
        # Repairs write through the engine bindings, bypassing the server's
        # event queue — anything mirroring the keyspace (the device Merkle
        # tree) must be told explicitly or it serves stale roots forever.
        self._repair_listener = repair_listener
        self._loop_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.last_report: Optional[SyncReport] = None
        self.last_multi_report: Optional[MultiSyncReport] = None

    # -- one-shot ------------------------------------------------------------
    def sync_once(
        self, host: str, port: int, full: bool = False, verify: bool = False
    ) -> SyncReport:
        with span("anti_entropy.sync_once", peer=f"{host}:{port}") as rec:
            report = self._sync_once(host, port, full, verify)
            rec["divergent"] = report.divergent
            get_metrics().inc("anti_entropy.syncs")
            get_metrics().inc("anti_entropy.keys_repaired",
                              report.set_keys + report.deleted_keys)
            return report

    def _sync_once(
        self, host: str, port: int, full: bool, verify: bool
    ) -> SyncReport:
        t0 = time.perf_counter()
        report = SyncReport(peer=f"{host}:{port}")

        with MerkleKVClient(host, port, timeout=self._timeout) as client:
            # Root comparison first, on the same connection the snapshot
            # would use: equal Merkle roots mean equal keyspaces, so no
            # snapshot travels at all. (The reference documents a
            # hash-compare walk but ships full-state transfer
            # unconditionally — SURVEY §3.4.)
            local_root = self._engine.merkle_root()
            local_hex = local_root.hex() if local_root is not None else "0" * 64
            try:
                roots_equal = client.hash() == local_hex
            except Exception as e:
                # A peer that serves data but not HASH still syncs — but
                # record the degradation instead of hiding it.
                get_metrics().inc("anti_entropy.probe_failures")
                report.details.append(f"hash probe failed: {e!r}")
                roots_equal = False
            if roots_equal:
                report.mode = "noop"
                report.verified = True if verify else None
                report.seconds = time.perf_counter() - t0
                report.details.append("roots equal; no transfer")
                self.last_report = report
                return report

            if full:
                report.mode = "full"
                self._sync_full(client, report)
            else:
                remote_hashes = self._fetch_remote_hashes(client, report)
                if remote_hashes is None:
                    report.mode = "full-fallback"
                    self._sync_full(client, report)
                else:
                    report.mode = "hash-first"
                    self._sync_hash_first(client, remote_hashes, report)

            if verify:
                local_root = self._engine.merkle_root()
                local_hex = (
                    local_root.hex() if local_root is not None else "0" * 64
                )
                report.verified = client.hash() == local_hex
                if not report.verified:
                    get_metrics().inc("anti_entropy.verify_failures")
                    report.seconds = time.perf_counter() - t0
                    self.last_report = report
                    raise RuntimeError(
                        f"sync verify failed: roots differ after repair "
                        f"(peer {report.peer})"
                    )

        report.seconds = time.perf_counter() - t0
        self.last_report = report
        return report

    # -- hash-first path ------------------------------------------------------
    def _fetch_remote_hashes(
        self, client: MerkleKVClient, report: SyncReport
    ) -> Optional[dict[bytes, tuple[bytes, int]]]:
        """Peer (leaf digest, last-write ts) map, or None if the peer can't
        serve LEAFHASHES."""
        try:
            # Decode INSIDE the try: a malformed digest line (corrupt peer,
            # or a future wire extension this reader doesn't know) must
            # degrade to the full-transfer fallback, not kill the cycle.
            return _decode_leaf_map(client.leaf_hashes_ts())
        except Exception as e:
            report.details.append(f"LEAFHASHES unsupported: {e!r}")
            get_metrics().inc("anti_entropy.leafhash_fallbacks")
            return None

    def _sync_hash_first(
        self,
        client: MerkleKVClient,
        remote_hashes: dict[bytes, tuple[Optional[bytes], int]],
        report: SyncReport,
    ) -> None:
        local = {k: v for k, v in self._engine.snapshot()}
        # Live digests and tombstones arrive in one LEAFHASHES payload;
        # pairwise semantics stay strict local := remote over the LIVE
        # keyspace, with remote tombstone timestamps adopted so the copied
        # deletion keeps its original LWW position.
        remote_digests = {
            k: d for k, (d, _) in remote_hashes.items() if d is not None
        }
        remote_tombs = {
            k: ts for k, (d, ts) in remote_hashes.items() if d is None
        }
        report.remote_keys = len(remote_digests)
        report.local_keys = len(local)

        use_device = self._use_device(len(set(local) | set(remote_digests)))
        local_hashes = _leaf_map(sorted(local.items()), use_device)
        divergent = self._diff(local_hashes, remote_digests, use_device)
        report.divergent = len(divergent)

        to_fetch = [k for k in divergent if k in remote_digests]
        values = self._fetch_values(client, to_fetch)
        report.values_fetched = len(values)
        for k in divergent:
            if k in remote_digests:
                if k in values:
                    # Propagate the peer's last-write ts with the value so
                    # LWW ordering metadata survives the repair.
                    self._repair_set(k, values[k], remote_hashes[k][1])
                    report.set_keys += 1
                # else: deleted on the peer between LEAFHASHES and MGET;
                # the next cycle repairs it.
            else:
                self._repair_delete(k, tomb_ts=remote_tombs.get(k))
                report.deleted_keys += 1

    # -- full path (reference behavior; --full or LEAFHASHES-less peer) -------
    def _sync_full(self, client: MerkleKVClient, report: SyncReport) -> None:
        remote = self._fetch_remote(client)
        local = {k: v for k, v in self._engine.snapshot()}
        report.remote_keys = len(remote)
        report.local_keys = len(local)
        report.values_fetched = len(remote)

        use_device = self._use_device(len(set(local) | set(remote)))
        local_hashes = _leaf_map(sorted(local.items()), use_device)
        remote_hashes = _leaf_map(sorted(remote.items()), use_device)
        divergent = self._diff(local_hashes, remote_hashes, use_device)
        report.divergent = len(divergent)

        for k in divergent:
            if k in remote:
                self._repair_set(k, remote[k])
                report.set_keys += 1
            else:
                self._repair_delete(k)
                report.deleted_keys += 1

    def _repair_set(self, k: bytes, v: bytes, ts: Optional[int] = None) -> None:
        if ts is None:
            self._engine.set(k, v)
        else:
            self._engine.set_with_ts(k, v, ts)
        if self._repair_listener is not None:
            self._repair_listener(k, v)

    def _repair_set_lww(self, k: bytes, v: bytes, ts: int) -> bool:
        """Conditional install for multi-peer repair: a local write or
        deletion racing ahead of the fetched winner must not be clobbered."""
        applied = self._engine.set_if_newer(k, v, ts)
        if applied and self._repair_listener is not None:
            self._repair_listener(k, v)
        return applied

    def _repair_delete(self, k: bytes, tomb_ts: Optional[int] = None) -> None:
        """Pairwise repair deletion. With the peer's tombstone ts, adopt it
        (the deletion keeps its LWW position); without one this is a MIRROR
        copy of absence — delete_quiet, because fabricating a tombstone at
        "now" would later kill disjoint writes cluster-wide."""
        if tomb_ts is None:
            if not hasattr(self._engine, "delete_quiet"):
                self._engine.delete(k)  # engine doubles without quiet mode
            else:
                self._engine.delete_quiet(k)
        elif not hasattr(self._engine, "delete_with_ts"):
            self._engine.delete(k)  # engine doubles without ts-carrying ops
        else:
            self._engine.delete_with_ts(k, tomb_ts)
        if self._repair_listener is not None:
            self._repair_listener(k, None)

    def _repair_delete_lww(self, k: bytes, ts: int, was_present: bool) -> bool:
        """Conditional deletion for multi-peer repair (peer tombstone won).

        The listener fires on EVERY applied delete, not just when the
        start-of-cycle snapshot saw the key: a replication event may have
        installed it mid-cycle, and the device mirror must drop what the
        engine just dropped (apply_one(k, None) is a no-op for absent
        keys). ``was_present`` only scopes the report count."""
        applied = self._engine.delete_if_newer(k, ts)
        if applied and self._repair_listener is not None:
            self._repair_listener(k, None)
        return applied and was_present

    # -- multi-peer cycle -----------------------------------------------------
    def sync_multi(self, peers: list[str]) -> MultiSyncReport:
        """One anti-entropy cycle against ALL peers at once.

        Gathers every peer's (leaf hash, last-write ts) pairs AND tombstones
        (deletion records with timestamps), stacks the live digests with the
        local map into one ``[R, N]`` divergence program (merkle/diff.py),
        then arbitrates each divergent key by **per-key LWW** over the
        deterministic order ``(ts, liveness, digest)``: newest timestamp
        wins; at equal timestamps a live value beats a tombstone; live ties
        break toward the lexicographically larger digest. Only the winning
        values are fetched — grouped per peer so each value travels once —
        and installed conditionally (set_if_newer) WITH the winner's
        timestamp so ordering metadata propagates and racing local writes
        survive. A winning tombstone deletes locally (delete_if_newer), so
        a deletion whose replication event was dropped still converges
        cluster-wide instead of being resurrected by peers holding the old
        value. BARE absence (no value, no tombstone) still never wins: a
        fresh write seen by one node is never destroyed by peers that
        merely haven't received it yet. Every node running this same
        deterministic rule converges the cluster to the LWW-merged union
        keyspace. Timestamps are wall clocks — cross-node skew trades
        accuracy for availability exactly like the reference's replication
        LWW (replication.rs:289-290).

        The reference has no analog: its sync is strictly pairwise and
        full-transfer, and a deletion it hasn't replicated is undone
        forever (/root/reference/src/sync.rs:56-87,74-83).
        """
        with span("anti_entropy.sync_multi", peers=",".join(peers)) as rec:
            report = self._sync_multi(peers)
            rec["divergent"] = report.divergent_union
            get_metrics().inc("anti_entropy.multi_syncs")
            get_metrics().inc(
                "anti_entropy.keys_repaired",
                report.set_keys + report.deleted_keys,
            )
            return report

    def _sync_multi(self, peers: list[str]) -> MultiSyncReport:
        import numpy as np

        from merklekv_tpu.merkle.diff import (
            align_replicas,
            divergence_masks,
            divergence_masks_np,
        )

        t0 = time.perf_counter()
        report = MultiSyncReport(peers=list(peers))

        # Gather peer leaf-hash+ts maps; a down peer is skipped this cycle.
        clients: list[Optional[MerkleKVClient]] = []
        peer_hashes: list[dict[bytes, tuple[Optional[bytes], int]]] = []

        def drop_peer(c: Optional[MerkleKVClient], why: str) -> None:
            # Every early-exit path must release the socket: this loop runs
            # every anti-entropy cycle, and an unclosed client per cycle is
            # a steady fd leak.
            if c is not None:
                c.close()
            report.details.append(why)
            clients.append(None)
            peer_hashes.append({})

        for peer in peers:
            host, _, port = peer.rpartition(":")
            c: Optional[MerkleKVClient] = None
            try:
                c = MerkleKVClient(host, int(port), timeout=self._timeout)
                c.connect()
            except Exception as e:
                drop_peer(c, f"{peer}: unreachable ({e!r})")
                continue
            try:
                decoded = _decode_leaf_map(c.leaf_hashes_ts())
            except Exception:
                # Peer serves data but not LEAFHASHES (the pairwise path's
                # full-transfer fallback, here too): fetch its snapshot and
                # hash locally. Entries carry ts 0 ("unknown age"), so the
                # peer contributes missing keys to the union but loses
                # every LWW race — it can never overwrite fresher state.
                get_metrics().inc("anti_entropy.leafhash_fallbacks")
                try:
                    remote = self._fetch_remote(c)
                    decoded = {
                        k: (d, 0)
                        for k, d in _leaf_map(
                            sorted(remote.items()), False
                        ).items()
                    }
                    report.details.append(
                        f"{peer}: LEAFHASHES unsupported; full snapshot"
                    )
                except Exception as e:
                    drop_peer(c, f"{peer}: unreachable ({e!r})")
                    continue
            clients.append(c)
            peer_hashes.append(decoded)
        live = [i for i, c in enumerate(clients) if c is not None]
        try:
            if not live:
                report.seconds = time.perf_counter() - t0
                return report

            local = {k: v for k, v in self._engine.snapshot()}
            use_device = self._use_device(
                len(set(local).union(*[set(p) for p in peer_hashes]))
            )
            local_hashes = _leaf_map(sorted(local.items()), use_device)

            # Replica 0 = local; only live peers join the arbitration.
            # Each peer's payload splits into live digests (alignment input)
            # and tombstones (deletion candidates for the LWW round).
            peer_maps = [peer_hashes[i] for i in live]
            peer_live = [
                {k: (d, ts) for k, (d, ts) in pm.items() if d is not None}
                for pm in peer_maps
            ]
            peer_tombs = [
                {k: ts for k, (d, ts) in pm.items() if d is None}
                for pm in peer_maps
            ]
            local_tombs = dict(self._engine.tombstones())
            replicas = [local_hashes] + [
                {k: d for k, (d, _) in pl.items()} for pl in peer_live
            ]
            aligned = align_replicas(replicas)
            report.union_keys = aligned.n_keys
            if aligned.n_keys == 0:
                report.seconds = time.perf_counter() - t0
                return report
            if use_device:
                from merklekv_tpu.utils.jaxenv import ensure_platform

                ensure_platform()
                masks = np.asarray(
                    divergence_masks(aligned.digests, aligned.present)
                )
            else:
                masks = divergence_masks_np(aligned.digests, aligned.present)
            report.per_peer_divergent = {
                peers[i]: int(masks[slot].sum())
                for slot, i in enumerate(live, start=1)
            }
            divergent = np.nonzero(masks.any(axis=0))[0]
            report.divergent_union = int(len(divergent))

            # Vectorized per-key LWW among replicas holding the key OR a
            # tombstone for it (bare absence never wins — see docstring).
            # Candidate order is (ts, liveness, digest words): liveness 1
            # for a value, 0 for a tombstone, so a value wins timestamp
            # ties — matching the engine's set_if_newer/del_if_newer rule.
            # The former per-key Python loop was O(divergent x replicas)
            # tuple comparisons + one FFI get_ts per key — at the
            # 10M/1%-divergence scale that is ~100K iterations per cycle;
            # here winner selection is 10 elementwise passes over [R, D].
            n_div = len(divergent)
            n_rep = len(replicas)
            keys_div = [aligned.keys[i] for i in divergent]
            sub = np.ascontiguousarray(
                aligned.digests[:, divergent, :]
            ).astype(">u4")
            raw_digests = sub.tobytes()

            def dig(r: int, j: int) -> bytes:
                off = (r * n_div + j) * 32
                return raw_digests[off : off + 32]

            pres = aligned.present[:, divergent]  # [R, D] bool
            # Local last-write timestamps: one bulk export when much of the
            # keyspace diverged, per-key FFI reads when divergence is small
            # relative to the keyspace (a 10M-entry dict per cycle would
            # dwarf a few thousand C calls).
            if n_div * 8 >= len(local):
                local_ts_map = dict(self._engine.key_timestamps())

                def local_ts(k: bytes) -> int:
                    return local_ts_map.get(k, 0)
            else:
                def local_ts(k: bytes) -> int:
                    return self._engine.get_ts(k) or 0

            # Timestamps clamp to int64 max: the matrix is int64 (-1 = no
            # candidate) and a peer with a corrupt clock reporting a uint64
            # ts >= 2^63 must lose gracefully in arbitration, not abort the
            # whole cycle with an OverflowError.
            _I64MAX = (1 << 63) - 1
            ts_m = np.zeros((n_rep, n_div), np.int64)
            ts_m[0] = [
                min(local_ts(k), _I64MAX)
                if p
                else min(local_tombs.get(k, -1), _I64MAX)
                for k, p in zip(keys_div, pres[0])
            ]
            for slot in range(1, n_rep):
                pl, pt = peer_live[slot - 1], peer_tombs[slot - 1]
                ts_m[slot] = [
                    min(pl[k][1], _I64MAX) if p else min(pt.get(k, -1), _I64MAX)
                    for k, p in zip(keys_div, pres[slot])
                ]
            live_m = pres.astype(np.int64)
            valid = ts_m >= 0  # a value or a recorded tombstone

            # Successive narrowing to the (ts, liveness, w0..w7) maximum.
            cand = valid.copy()
            words = sub.astype(np.int64)  # [R, D, 8], big-endian word order
            for crit in (ts_m, live_m, *(words[:, :, w] for w in range(8))):
                masked = np.where(cand, crit, np.int64(-1))
                cand &= masked == masked.max(axis=0)[None, :]
            winner_slot = np.argmax(cand, axis=0)  # first max row; digest
            # ties beyond word 7 mean identical winning state on both rows.
            any_valid = valid.any(axis=0)
            winner_ts_arr = ts_m[winner_slot, np.arange(n_div)]
            winner_live_arr = live_m[winner_slot, np.arange(n_div)] == 1

            # wants[peer_slot] = (key, winner_ts) pairs that peer serves.
            wants: dict[int, list[tuple[bytes, int]]] = {}
            for j in np.nonzero(any_valid)[0]:
                key = keys_div[j]
                ws = int(winner_slot[j])
                winner_ts = int(winner_ts_arr[j])
                local_present = bool(pres[0, j])
                if not winner_live_arr[j]:
                    # A deletion won: apply it locally unless local state is
                    # newer (delete_if_newer re-checks under the shard lock).
                    if self._repair_delete_lww(key, winner_ts, local_present):
                        report.deleted_keys += 1
                    continue
                if ws == 0:
                    continue  # local already holds the winning state
                winner = dig(ws, j)
                if local_present and dig(0, j) == winner:
                    continue  # same digest locally; nothing to fetch
                wants.setdefault(live[ws - 1], []).append((key, winner_ts))

            for r, pairs in wants.items():
                values = self._fetch_values(clients[r], [k for k, _ in pairs])
                report.values_fetched += len(values)
                for k, ts in pairs:
                    if k in values:
                        if self._repair_set_lww(k, values[k], ts):
                            report.set_keys += 1
        finally:
            for c in clients:
                if c is not None:
                    c.close()

        report.seconds = time.perf_counter() - t0
        self.last_multi_report = report
        return report

    def _use_device(self, n_union: int) -> bool:
        return self._device == "tpu" or (
            self._device == "auto" and n_union >= _DEVICE_THRESHOLD
        )

    def _diff(
        self,
        local_hashes: dict[bytes, bytes],
        remote_hashes: dict[bytes, bytes],
        use_device: bool,
    ) -> list[bytes]:
        if use_device:
            from merklekv_tpu.utils.jaxenv import ensure_platform

            ensure_platform()
            from merklekv_tpu.merkle.diff import diff_keys_pair

            return diff_keys_pair(local_hashes, remote_hashes)
        keys = set(local_hashes) | set(remote_hashes)
        return sorted(
            k for k in keys if local_hashes.get(k) != remote_hashes.get(k)
        )

    def _fetch_remote(self, c: MerkleKVClient) -> dict[bytes, bytes]:
        """Snapshot over an already-open connection: SCAN, then batched MGET."""
        return self._mget_all(c, c.scan())

    def _fetch_values(
        self, c: MerkleKVClient, keys: list[bytes]
    ) -> dict[bytes, bytes]:
        """Targeted value fetch for the divergent set only."""
        return self._mget_all(
            c, [k.decode("utf-8", "surrogateescape") for k in keys]
        )

    def _mget_all(
        self, c: MerkleKVClient, keys: list[str]
    ) -> dict[bytes, bytes]:
        out: dict[bytes, bytes] = {}
        for i in range(0, len(keys), self._mget_batch):
            batch = keys[i : i + self._mget_batch]
            for k, v in c.mget(batch).items():
                if v is None:
                    # MGET's wire format can't distinguish a missing key
                    # from a literal "NOT_FOUND" value; GET can (the
                    # "VALUE " prefix). The key came from SCAN/LEAFHASHES,
                    # so only a concurrent delete or that literal value
                    # lands here.
                    v = c.get(k)
                    if v is None:
                        continue
                out[k.encode("utf-8", "surrogateescape")] = v.encode(
                    "utf-8", "surrogateescape"
                )
        return out

    # -- periodic loop ---------------------------------------------------------
    def start_loop(
        self,
        peers: list[str],
        interval_seconds: float,
        multi_peer: bool = False,
        peer_up=None,  # Callable[[str], bool] from the health monitor
    ) -> None:
        """Periodic anti-entropy: pairwise per peer, or one fused
        multi-peer arbitration cycle when ``multi_peer`` is set.

        ``peer_up`` (the failure detector's verdict) lets a cycle skip
        confirmed-down peers instead of paying a connect timeout each; the
        monitor keeps probing, so a recovered peer rejoins the next cycle.
        """

        def up(peer: str) -> bool:
            if peer_up is None:
                return True
            try:
                return bool(peer_up(peer))
            except Exception:
                return True  # a broken detector must not stall repairs

        def run() -> None:
            while not self._stop.wait(interval_seconds):
                live_peers = [p for p in peers if up(p)]
                skipped = len(peers) - len(live_peers)
                if skipped:
                    get_metrics().inc("anti_entropy.down_peer_skips", skipped)
                if multi_peer:
                    if not live_peers:
                        continue
                    try:
                        self.sync_multi(live_peers)
                    except Exception:
                        # Retried next round — but never silently: a loop
                        # that throws every cycle looks like a healthy
                        # no-op without this counter.
                        get_metrics().inc("anti_entropy.loop_errors")
                    continue
                for peer in live_peers:
                    if self._stop.is_set():
                        return
                    host, _, port = peer.rpartition(":")
                    try:
                        self.sync_once(host, int(port))
                    except Exception:
                        # Peer down: anti-entropy retries next round; failure
                        # detection surfaces through last_report staleness.
                        get_metrics().inc("anti_entropy.loop_errors")
                        continue

        self._stop.clear()
        self._loop_thread = threading.Thread(
            target=run, daemon=True, name="mkv-anti-entropy"
        )
        self._loop_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None

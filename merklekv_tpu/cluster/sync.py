"""Anti-entropy: make the local store converge to a remote peer.

Reference analog: /root/reference/src/sync.rs. Its hot loops are pathological:
snapshotting rebuilds the Merkle tree per insert (O(n^2 log n) hashing,
sync.rs:104-119) and every remote key is GET over a FRESH TCP connection
(sync.rs:192-214). Here:

  - the local snapshot is one native-engine export (sorted, no hashing on
    insert);
  - the remote snapshot is one connection: SCAN + batched MGET;
  - leaf hashing is batched — hashlib for small keyspaces, the TPU engine
    (one vmapped SHA-256 program) beyond a threshold;
  - the diff is the device multi-replica comparison (merkle/diff.py);
  - the periodic loop is actually wired (the reference's start_sync_loop is
    dead code, sync.rs:90-99).

Semantics match sync_once: one-way local := remote for every divergent key
(sync.rs:74-83), including deletion of local-only keys.

Transfer strategy (the fix for the reference's core flaw — its README
documents an O(log n) hash-walk, README.md:310-372, but the code ships the
entire keyspace as values on every divergence, sync.rs:150-214):

  1. root compare — equal roots, zero transfer;
  2. LEAFHASHES — fetch per-key digests (32 bytes/key, not values), diff,
     then MGET only the divergent keys; bandwidth is proportional to
     divergence, not keyspace size;
  3. ``--full`` (or a peer without LEAFHASHES) — full snapshot transfer,
     the reference behavior, kept as an explicit escape hatch;
  4. ``--verify`` — re-compare Merkle roots after repair; mismatch raises.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from merklekv_tpu.client import (
    MerkleKVClient,
    MerkleKVError,
    MovedError,
    ProtocolError,
)
from merklekv_tpu.cluster.retry import SYNC_PEER, Deadline, RetryPolicy
from merklekv_tpu.merkle.encoding import leaf_hash
from merklekv_tpu.native_bindings import NativeEngine
from merklekv_tpu.obs import tracewire
from merklekv_tpu.obs.trace import (
    CycleTrace,
    PeerTrace,
    cycle_scope,
    get_trace_buffer,
    next_cycle_id,
)
from merklekv_tpu.utils import jaxenv
from merklekv_tpu.utils.tracing import get_metrics, span

__all__ = ["SyncManager", "SyncReport", "MultiSyncReport", "SyncSession"]

# Below this many union keys the device round-trip costs more than hashlib
# (measured: a 10K-key cycle is ~2.7x faster on the host path even with a
# local chip's dispatch latency amortized — batched SHA-256 only wins once
# the keyspace is large enough to fill the device). Deployments with
# different host/device latency can tune MKV_DEVICE_THRESHOLD.
_DEVICE_THRESHOLD = int(os.environ.get("MKV_DEVICE_THRESHOLD", 1 << 16))


@dataclass
class MultiSyncReport:
    peers: list[str] = field(default_factory=list)
    union_keys: int = 0
    divergent_union: int = 0  # keys where ANY replica disagrees
    # peer -> divergence count vs local; unreachable peers are absent.
    per_peer_divergent: dict[str, int] = field(default_factory=dict)
    set_keys: int = 0
    deleted_keys: int = 0  # keys removed because a peer's tombstone won LWW
    values_fetched: int = 0
    seconds: float = 0.0
    details: list[str] = field(default_factory=list)
    # Peers whose repair stream died mid-cycle: their remaining work is
    # checkpointed as a SyncSession and they are reported to the health
    # monitor; the rest of the cycle proceeds.
    degraded: list[str] = field(default_factory=list)
    resumed_peers: list[str] = field(default_factory=list)


@dataclass
class SyncReport:
    peer: str = ""
    remote_keys: int = 0
    local_keys: int = 0
    divergent: int = 0
    set_keys: int = 0
    deleted_keys: int = 0
    values_fetched: int = 0  # values transferred (== divergent when hash-first)
    # "noop" | "bisect" | "hash-paged" | "hash-first" | "full" |
    # "full-fallback"
    mode: str = ""
    verified: Optional[bool] = None  # post-sync root recheck (--verify)
    resumed: bool = False  # this cycle continued an interrupted session
    # Version-stamp plane (bounded-staleness donors): the engine version
    # the donor's served tree reflected at the last stamped fetch, how far
    # its live engine trailed it, whether this cycle escalated a stale
    # donor tree to a forced refresh, and whether mid-walk churn was
    # absorbed by clipping to the verified frontier instead of abandoning
    # the walk.
    donor_tree_version: int = 0
    donor_tree_lag: int = 0
    forced_refreshes: int = 0
    walk_clipped: bool = False
    seconds: float = 0.0
    # Wire cost of the whole cycle (client-measured request/response bytes,
    # reconnects included) — the number the bisection walk shrinks from
    # O(n) to O(divergence·log n).
    bytes_sent: int = 0
    bytes_received: int = 0
    # Bisection-walk observability: tree nodes compared and walk rounds
    # (one round per level batch of TREELEVEL fetches).
    nodes_compared: int = 0
    rounds: int = 0
    details: list[str] = field(default_factory=list)


@dataclass
class SyncSession:
    """Checkpoint of an interrupted per-peer repair.

    When a peer dies (or an injected fault kills the stream) mid-repair,
    the work already applied stays applied and the REMAINING divergent
    keys are kept here; the next cycle against the peer repairs these
    first — resuming from the last verified leaf instead of restarting
    the whole diff. Conceptually the pending list is the frontier of
    not-yet-verified subtrees: divergent leaves are repaired in sorted
    order, so everything before the checkpoint is an already-converged
    prefix of the tree.
    """

    peer: str
    pending_sets: list[tuple[bytes, int]]  # (key, last-write ts) to fetch
    repaired: int = 0  # keys applied before the interruption
    attempts: int = 0
    # Paged hash-scan resume position (exclusive): every key <= cursor was
    # verified/repaired before the interruption, so the next cycle's walk
    # starts here instead of refetching the whole hash list. b"" = the walk
    # had not begun (or the peer doesn't serve HASHPAGE).
    cursor: bytes = b""
    # Adaptive page size carried across cycles: a walk that died shrinks
    # the next attempt's pages (less exposure per round trip on a hostile
    # link); clean pages grow it back toward the configured maximum.
    # 0 = start from the SyncManager default.
    page_size: int = 0
    # The interrupted cycle was a bisection walk: resume re-enters the walk
    # (clipping already-verified intervals at the cursor) instead of the
    # paged scan — mode is sticky across a resume so a hostile link can't
    # silently downgrade the transfer strategy.
    walk: bool = False
    created_unix: float = field(default_factory=time.time)


# A session that keeps failing (or outlives remote churn) is abandoned and
# the next cycle runs a fresh full diff — resume is an optimization, never
# a correctness dependency (the root compare after resume re-verifies).
_SESSION_MAX_ATTEMPTS = 8
_SESSION_MAX_AGE_S = 600.0


def _leaf_map_device(items: list[tuple[bytes, bytes]]) -> dict[bytes, bytes]:
    from merklekv_tpu.utils.jaxenv import ensure_platform

    ensure_platform()
    from merklekv_tpu.merkle.jax_engine import leaf_digests
    from merklekv_tpu.ops.sha256 import digests_to_bytes

    import numpy as np

    digests = leaf_digests([k for k, _ in items], [v for _, v in items])
    return dict(zip((k for k, _ in items), digests_to_bytes(np.asarray(digests))))


def _leaf_map(items: list[tuple[bytes, bytes]], use_device: bool) -> dict[bytes, bytes]:
    if use_device and not jaxenv.device_failed():
        try:
            return _leaf_map_device(items)
        except Exception as e:
            # TPU/Pallas init failure degrades to host hashing (one-time
            # warning) instead of killing every anti-entropy cycle.
            jaxenv.note_device_failure(e, "leaf hashing")
    return {k: leaf_hash(k, v) for k, v in items}


def _decode_leaf_map(
    raw: dict[str, tuple[Optional[str], int]]
) -> dict[bytes, tuple[Optional[bytes], int]]:
    """LEAFHASHES wire payload -> {key bytes: (digest bytes | None, ts)}.

    A None digest is a TOMBSTONE: the peer deleted the key at ts, and that
    deletion competes in LWW arbitration like any write."""
    return {
        k.encode("utf-8", "surrogateescape"): (
            bytes.fromhex(h) if h is not None else None,
            ts,
        )
        for k, (h, ts) in raw.items()
    }


class SyncManager:
    def __init__(
        self,
        engine: NativeEngine,
        device: str = "auto",  # "auto" | "cpu" | "tpu"
        mget_batch: int = 512,
        timeout: Optional[float] = None,
        # Callable[[bytes, Optional[bytes], Optional[int]], None]:
        # (key, value|None, LWW ts|None). The ts is the EXACT timestamp
        # the repair installed (peer write ts / tombstone ts), so a WAL
        # can journal it without a racy engine read-back; None means the
        # repair carried no ordering metadata (legacy full transfer,
        # delete_quiet absence copy).
        repair_listener=None,
        retry: Optional[RetryPolicy] = None,
        on_peer_degraded: Optional[Callable[[str, str], None]] = None,
        hash_page: int = 512,
        mode: str = "auto",
        bisect_threshold: int = 8192,
        on_cycle_converged: Optional[Callable[[], None]] = None,
        # LWW clock-skew guard at the repair-install boundary, same bound
        # as the replicator's ([replication] max_skew_ms). Without it a
        # future-poisoned timestamp clamped on the replication path would
        # simply RE-ENTER through anti-entropy: the poisoning peer still
        # holds the raw ts in its engine, and a walk/arbitration against
        # it would install that ts here, re-fencing the key. 0 disables.
        max_skew_ms: int = 0,
        # Bounded-trailing tolerance for stamped donors ([device]
        # max_staleness_versions): a donor whose served tree reports a lag
        # past this many engine mutations gets ONE forced-refresh re-probe
        # before the walk descends (its pump is presumed wedged or swamped;
        # walking a deeply stale tree would repair against ancient state).
        # 0 selects the default.
        tree_lag_limit: int = 0,
        # Partitioned cluster mode: the partition this node owns. When
        # set, every HASH/TREELEVEL the walk sends carries the pt=<pid>
        # address, so a peer that no longer owns this partition (stale
        # routing, mid-rebalance) answers ERROR MOVED instead of serving
        # a DIFFERENT partition's tree — a walk comparing against the
        # wrong partition would quietly mirror its whole keyspace as
        # divergence. None = unpartitioned (no token).
        partition_id: "Optional[int]" = None,
    ) -> None:
        self._engine = engine
        self._device = device
        self._partition_id = partition_id
        self._mget_batch = mget_batch
        # Pairwise transfer strategy when roots differ: "auto" bisects the
        # tree (TREELEVEL walk) once the local keyspace reaches
        # bisect_threshold keys and pages below it; "bisect"/"page" force a
        # strategy. A peer without TREELEVEL always degrades to paging.
        self._mode = mode
        self._bisect_threshold = bisect_threshold
        # Keys per HASHPAGE fetch in the paged pairwise walk. Smaller pages
        # bound how much verified progress one dead stream can destroy (a
        # page is the resume granularity); larger pages amortize round
        # trips on clean links.
        self._hash_page = hash_page
        # Per-op timeout / connect retries / per-peer cycle deadline come
        # from ONE policy object (cluster/retry.py) instead of scattered
        # constants; an explicit timeout still wins for callers that need
        # a different socket budget.
        self._retry = retry if retry is not None else SYNC_PEER
        self._timeout = timeout if timeout is not None else self._retry.op_timeout
        # Repairs write through the engine bindings, bypassing the server's
        # event queue — anything mirroring the keyspace (the device Merkle
        # tree) must be told explicitly or it serves stale roots forever.
        self._repair_listener = repair_listener
        # Mid-sync failure hook: the peer is reported degraded (health.py
        # flips its table entry) while its checkpointed session waits.
        self._on_peer_degraded = on_peer_degraded
        # Convergence hook for the lag plane (obs/lag.py): fired by the
        # periodic loop after a FULL CLEAN PASS — every configured peer
        # synced this round with no exception, checkpoint, degradation,
        # or down-peer skip. Only full coverage may clear dropped-frame
        # lag residue: a single pairwise cycle against peer A proves
        # nothing about events a partitioned peer B published (A may be
        # missing them too), so firing per cycle would mask exactly the
        # divergence the SLO exists to surface.
        self._on_cycle_converged = on_cycle_converged
        self._max_skew_ns = max(0, int(max_skew_ms)) * 1_000_000
        self._tree_lag_limit = (
            int(tree_lag_limit) if tree_lag_limit > 0
            else self._DEFAULT_TREE_LAG_LIMIT
        )
        self._sessions: dict[str, SyncSession] = {}
        # First-checkpoint time per peer, surviving resume/re-checkpoint
        # churn: a re-checkpoint builds a fresh SyncSession, and without
        # this the 10-minute abandonment clock would restart every cycle.
        self._session_born: dict[str, float] = {}
        # Peers degraded during the current cycle — lets the loop's
        # catch-all skip a second, reason-losing _degrade for failures the
        # cycle already reported.
        self._degraded_this_cycle: set[str] = set()
        self._loop_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.last_report: Optional[SyncReport] = None
        self.last_multi_report: Optional[MultiSyncReport] = None

    # -- causal tracing -------------------------------------------------------
    @staticmethod
    def _cycle_trace_scope():
        """(scope, ctx) — a fresh trace root for one anti-entropy cycle,
        or a no-op scope with ctx None when propagation is disabled
        ([observability] trace_propagation). The ctx rides separately
        because the cycle summary is appended AFTER the scope exits (the
        flight recorder stamps its trace id for cross-node spill joins)."""
        import contextlib

        if not tracewire.propagation_enabled():
            return contextlib.nullcontext(), None
        scope = tracewire.trace_scope(tracewire.new_context())
        return scope, scope.ctx

    def _attach_trace(self, client: MerkleKVClient) -> MerkleKVClient:
        """Give the client the live token provider — every cluster verb it
        sends carries the active trace context — and turn on version
        stamps, so tree fetches report the engine version the donor's
        served tree reflects (both ride the same capability fallback
        against old peers). On a partitioned node the client also carries
        the pt=<pid> partition address (no fallback — see MerkleKVClient.
        partition_id)."""
        client.trace_provider = tracewire.current_token
        client.version_stamps = True
        client.partition_id = self._partition_id
        return client

    @staticmethod
    def _settle_trace_capability(client: MerkleKVClient) -> None:
        """Prove (or disprove) the peer's trace capability with a
        fail-closed zero-width TREELEVEL probe before any verb whose
        trailing token an old peer would misread as a real argument
        (LEAFHASHES prefix, HASHPAGE cursor) — see client._traced_request
        require_settled. No-op when untraced or already settled."""
        if (
            not tracewire.propagation_enabled()
            or tracewire.current() is None
            or client._peer_traced is not None
        ):
            return
        try:
            client.tree_level(0, 0, 0)
        except MovedError:
            # The probe carries the pt= partition address: a MOVED answer
            # means this peer serves a DIFFERENT partition, and every verb
            # the caller would send next (LEAFHASHES/HASHPAGE) is
            # unguarded — surface it, never settle-and-continue.
            raise
        except Exception:
            pass  # capability state is settled either way

    # -- failure bookkeeping --------------------------------------------------
    def _degrade(self, peer: str, reason: str) -> None:
        get_metrics().inc("anti_entropy.peer_degraded")
        self._degraded_this_cycle.add(peer)
        if self._on_peer_degraded is not None:
            try:
                self._on_peer_degraded(peer, reason)
            except Exception:
                pass  # a broken health hook must never stall repairs

    def session_for(self, peer: str) -> Optional[SyncSession]:
        """The checkpointed session for ``peer`` (introspection/tests)."""
        return self._sessions.get(peer)

    def _checkpoint(
        self,
        peer: str,
        pending: list[tuple[bytes, int]],
        repaired: int,
        attempts: int = 0,
        cursor: bytes = b"",
        page_size: int = 0,
        walk: bool = False,
    ) -> None:
        self._sessions[peer] = SyncSession(
            peer=peer,
            pending_sets=pending,
            repaired=repaired,
            attempts=attempts,
            cursor=cursor,
            page_size=page_size,
            walk=walk,
            created_unix=self._session_born.setdefault(peer, time.time()),
        )
        get_metrics().inc("anti_entropy.sessions_checkpointed")

    def _take_session(self, peer: str) -> Optional[SyncSession]:
        """Pop a resumable session, discarding it when stale/exhausted."""
        sess = self._sessions.pop(peer, None)
        if sess is None:
            return None
        if (
            sess.attempts >= _SESSION_MAX_ATTEMPTS
            or time.time() - sess.created_unix > _SESSION_MAX_AGE_S
        ):
            get_metrics().inc("anti_entropy.sessions_abandoned")
            self._session_born.pop(peer, None)
            return None
        sess.attempts += 1
        return sess

    def _session_done(self, peer: str) -> None:
        """Clear the abandonment clock once no session remains for the
        peer (fully drained or abandoned) — a stale birth time would make
        some future, unrelated session look instantly over-age."""
        if peer not in self._sessions:
            self._session_born.pop(peer, None)

    # -- one-shot ------------------------------------------------------------
    def sync_once(
        self, host: str, port: int, full: bool = False, verify: bool = False
    ) -> SyncReport:
        # Correlated trace: one cycle id for the whole pairwise cycle —
        # every span emitted inside (walk, repairs, journaling) is stamped
        # with it, and the cycle's per-peer outcome lands in the TRACE ring
        # buffer whether the cycle succeeds, degrades, or raises.
        peer = f"{host}:{port}"
        trace = PeerTrace(peer=peer)
        started, t0 = time.time(), time.perf_counter()
        cid = next_cycle_id()
        # Bind the scope so its trace id survives into the finally — the
        # summary is appended after the scope exits, and the flight
        # recorder needs the id for cross-node spill joins.
        tscope, tctx = self._cycle_trace_scope()
        try:
            # Causal trace root for the whole cycle: spans inside stitch
            # under it, and the clients' trace tokens carry it to the peer
            # so the donor's serve spans land under the SAME trace id.
            with tscope, cycle_scope(cid), \
                    span("anti_entropy.sync_once", peer=peer) as rec:
                report = self._sync_once(host, port, full, verify,
                                         trace=trace)
                rec["divergent"] = report.divergent
                get_metrics().inc("anti_entropy.syncs")
                get_metrics().inc("anti_entropy.keys_repaired",
                                  report.set_keys + report.deleted_keys)
                return report
        except Exception as e:
            # A cycle that left a checkpoint is resuming by design —
            # "degraded", not "error" (which means the cycle lost its work).
            trace.outcome = "degraded" if peer in self._sessions else "error"
            if not trace.error:
                trace.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            get_trace_buffer().append(CycleTrace(
                cycle_id=cid, kind="pairwise", started_unix=started,
                seconds=time.perf_counter() - t0, peers=[trace],
                trace_id=tctx.trace_id if tctx is not None else 0,
            ))

    def _sync_once(
        self,
        host: str,
        port: int,
        full: bool,
        verify: bool,
        trace: Optional[PeerTrace] = None,
    ) -> SyncReport:
        t0 = time.perf_counter()
        peer = f"{host}:{port}"
        report = SyncReport(peer=peer)
        deadline = self._retry.deadline()
        self._degraded_this_cycle.discard(peer)

        client = self._attach_trace(
            MerkleKVClient(host, port, timeout=self._timeout)
        )
        try:
            self._retry.run(
                client.connect,
                retry_on=(OSError, MerkleKVError),
                deadline=deadline,
            )
        except Exception as e:
            self._degrade(peer, f"connect failed: {e!r}")
            raise
        try:
            # A previous cycle's interrupted repair resumes FIRST: the
            # checkpointed frontier is repaired from the last verified
            # leaf, then the root compare below re-verifies convergence
            # (resume is a fast-path, never a correctness dependency).
            sess = self._take_session(peer)
            if sess is not None:
                report.resumed = True
                report.details.append(
                    f"resuming session: {len(sess.pending_sets)} pending, "
                    f"{sess.repaired} already repaired, "
                    f"cursor {sess.cursor!r}, attempt {sess.attempts}"
                )
                get_metrics().inc("anti_entropy.sessions_resumed")

            # One cycle survives stream deaths: every failure below leaves
            # a checkpoint (cursor + unapplied page remainder), so instead
            # of surrendering the cycle the loop reconnects — the old
            # socket's desync dies with it — and continues from the
            # checkpoint, until the cycle deadline or reconnect budget is
            # spent. Only then does the error propagate, with the session
            # retained for the NEXT cycle to resume.
            reconnects = 0
            while True:
                try:
                    if sess is not None and sess.pending_sets:
                        # Conditional installs: the checkpoint may be older
                        # than fresh local writes — LWW must still win. A
                        # re-checkpoint on failure carries the scan cursor
                        # forward so the unfinished walk is not lost.
                        self._repair_sets_resumable(
                            client, peer, sess.pending_sets, report,
                            deadline, lww=True,
                            already_repaired=sess.repaired,
                            prior_attempts=sess.attempts, cursor=sess.cursor,
                            walk=sess.walk,
                        )
                        sess.pending_sets = []
                        if peer in self._sessions:
                            # Deadline expired mid-resume: the remainder is
                            # already checkpointed (and the peer degraded);
                            # entering the walk below would see the same
                            # expired deadline and clobber that checkpoint
                            # with an empty pending list.
                            report.seconds = time.perf_counter() - t0
                            self.last_report = report
                            return report

                    # Root comparison on the same connection the snapshot
                    # would use: equal Merkle roots mean equal keyspaces,
                    # so no snapshot travels at all. Skipped when resuming
                    # mid-walk — the unwalked tail makes inequality near
                    # certain, and the probe is one more round trip a
                    # hostile link can kill. (The reference documents a
                    # hash-compare walk but ships full-state transfer
                    # unconditionally — SURVEY §3.4.)
                    mid_walk = sess is not None and sess.cursor != b""
                    if not mid_walk:
                        local_root = self._engine.merkle_root()
                        local_hex = (
                            local_root.hex()
                            if local_root is not None
                            else "0" * 64
                        )
                        try:
                            roots_equal = client.hash() == local_hex
                        except MovedError:
                            # Partition mismatch is a ROUTING refusal, not
                            # a degraded probe: the peer serves a DIFFERENT
                            # partition, and falling through to a transfer
                            # would mirror its disjoint keyspace as
                            # divergence (mass quiet-deletes + foreign
                            # imports). Abort the cycle loudly instead.
                            get_metrics().inc("anti_entropy.moved_peers")
                            raise
                        except Exception as e:
                            # A peer that serves data but not HASH still
                            # syncs — record the degradation, don't hide it.
                            get_metrics().inc("anti_entropy.probe_failures")
                            report.details.append(f"hash probe failed: {e!r}")
                            roots_equal = False
                        if roots_equal:
                            report.mode = "noop"
                            report.verified = True if verify else None
                            report.seconds = time.perf_counter() - t0
                            report.details.append("roots equal; no transfer")
                            self.last_report = report
                            return report

                    if full:
                        report.mode = "full"
                        self._sync_full(client, report)
                    else:
                        start = sess.cursor if sess is not None else b""
                        prior = sess.attempts if sess is not None else 0
                        # Subtree bisection first (mode permitting): walk
                        # the peer's tree top-down, descend only into
                        # divergent subtrees, and fetch leaf hashes +
                        # values for divergent key ranges only — wire
                        # bytes ∝ divergence·log n, not keyspace size.
                        walked, precomputed = False, None
                        if self._want_walk(sess):
                            walked, precomputed = self._sync_bisect(
                                client, report, deadline,
                                start=start, prior_attempts=prior,
                                start_page=(
                                    sess.page_size if sess is not None else 0
                                ),
                            )
                        paged = walked or self._sync_hash_paged(
                            client, report, deadline,
                            start=start,
                            prior_attempts=prior,
                            start_page=(
                                sess.page_size if sess is not None else 0
                            ),
                            precomputed=precomputed,
                        )
                        if not paged:
                            # Peer predates HASHPAGE: monolithic
                            # LEAFHASHES, then full transfer as the last
                            # resort — all-or-nothing paths, kept only for
                            # old peers.
                            remote_hashes = self._fetch_remote_hashes(
                                client, report
                            )
                            if remote_hashes is None:
                                report.mode = "full-fallback"
                                self._sync_full(client, report)
                            else:
                                report.mode = "hash-first"
                                self._sync_hash_first(
                                    client, remote_hashes, report, deadline
                                )
                    break
                except (MerkleKVError, OSError):
                    nsess = self._sessions.pop(peer, None)
                    out_of_budget = (
                        reconnects >= self._MAX_CYCLE_RECONNECTS
                        or deadline.expired()
                        or self._stop.is_set()
                    )
                    if nsess is None or out_of_budget:
                        if nsess is not None:
                            self._sessions[peer] = nsess  # keep for next cycle
                        raise
                    time.sleep(deadline.clamp(
                        self._retry.backoff(reconnects)
                    ))
                    reconnects += 1
                    client.close()
                    try:
                        self._retry.run(
                            client.connect,
                            retry_on=(OSError, MerkleKVError),
                            deadline=deadline,
                        )
                    except Exception:
                        self._sessions[peer] = nsess  # keep for next cycle
                        raise
                    sess = nsess
                    get_metrics().inc("anti_entropy.cycle_reconnects")
                    report.details.append(
                        f"stream died; reconnected (#{reconnects}), "
                        f"resuming at cursor {nsess.cursor!r} with "
                        f"{len(nsess.pending_sets)} pending"
                    )

            if verify and peer in self._sessions:
                # The cycle deliberately checkpointed mid-walk (deadline
                # expiry): roots are necessarily unequal, and raising here
                # would misreport the designed resume path as corruption.
                report.verified = False
                report.details.append(
                    "verify skipped: cycle checkpointed mid-walk; "
                    "resuming next cycle"
                )
            elif verify:
                local_root = self._engine.merkle_root()
                local_hex = (
                    local_root.hex() if local_root is not None else "0" * 64
                )
                report.verified = client.hash() == local_hex
                if not report.verified:
                    get_metrics().inc("anti_entropy.verify_failures")
                    report.seconds = time.perf_counter() - t0
                    self.last_report = report
                    raise RuntimeError(
                        f"sync verify failed: roots differ after repair "
                        f"(peer {report.peer})"
                    )
        finally:
            # Wire-byte accounting for the WHOLE cycle (probe, hash/tree
            # fetches, repairs, reconnects — the client counters survive
            # reconnects because the same client object re-dials).
            report.bytes_sent = client.bytes_sent
            report.bytes_received = client.bytes_received
            get_metrics().inc("sync.bytes_sent", report.bytes_sent)
            get_metrics().inc("sync.bytes_received", report.bytes_received)
            if trace is not None:
                trace.mode = report.mode
                trace.bytes_sent = report.bytes_sent
                trace.bytes_received = report.bytes_received
                trace.rounds = report.rounds
                trace.divergent = report.divergent
                trace.repairs = report.set_keys + report.deleted_keys
                if (peer in self._sessions
                        or peer in self._degraded_this_cycle):
                    trace.outcome = "degraded"
                elif report.mode == "noop":
                    trace.outcome = "noop"
            client.close()
            self._session_done(peer)

        report.seconds = time.perf_counter() - t0
        self.last_report = report
        return report

    # -- subtree-bisection walk (large-keyspace pairwise path) ----------------
    # Frontier cap: past this many divergent nodes per level the descent
    # stops early and repairs coarser intervals — massive divergence makes
    # deeper bisection pure overhead (the leaf fetches dominate anyway).
    _MAX_WALK_FRONTIER = 2048
    # Finest subtree the descent isolates before switching to leaf pages.
    # One more level costs ~134 wire bytes per divergent range (two more
    # interior digests) and saves half the range's leaf rows (~95 bytes
    # each), so descending pays until the span is a handful of keys; 16
    # keeps the last hop cheap without a round trip per single leaf.
    _WALK_LEAF_SPAN = 16
    # Default forced-refresh threshold when [device] max_staleness_versions
    # is unset: a donor tree trailing its engine by this many mutations is
    # past any sane pump window — deep enough that diffing against it
    # mostly finds already-healed divergence.
    _DEFAULT_TREE_LAG_LIMIT = 4096

    def _want_walk(self, sess: Optional[SyncSession]) -> bool:
        """Transfer-strategy selection for this cycle. A mid-walk resume
        stays in its recorded mode (the checkpointed cursor's semantics
        depend on it); otherwise config decides, with "auto" bisecting only
        once the keyspace is large enough that a tree walk's extra round
        trips beat shipping the whole hash list."""
        if sess is not None and sess.cursor:
            return sess.walk
        if self._mode == "page":
            return False
        if self._mode == "bisect":
            return True
        try:
            return self._engine.dbsize() >= self._bisect_threshold
        except Exception:
            return False

    def _sync_bisect(
        self,
        client: MerkleKVClient,
        report: SyncReport,
        deadline: Optional[Deadline],
        start: bytes,
        prior_attempts: int = 0,
        start_page: int = 0,
    ) -> tuple[bool, Optional[tuple[list[bytes], dict[bytes, bytes]]]]:
        """Top-down Merkle walk: start at the peer's tree root, descend
        only into divergent subtrees (TREELEVEL fetches, one batch per
        level), then repair each divergent LEAF RANGE with range-bounded
        HASHPAGE pages + targeted MGET — so wire bytes scale with
        divergence·log n instead of keyspace size. Positional node
        comparison is exact for value divergence; a structural change
        (insert/delete) shifts every position to its right, so those
        subtrees all read divergent and collapse into one contiguous
        repair range — never worse than the hash-list transfer, and the
        repair itself stays key-based (bounded pages), so it is correct
        either way.

        Boundary-key invariant the leaf fetch relies on: a node that
        COMPARES EQUAL pins keys and positions — local position i then
        holds exactly the remote's key i for every position the node
        covers — so divergent ranges are bounded by locally-known keys.

        Returns ``(walked, local_precomputed)``: ``walked`` False when the
        peer can't serve TREELEVEL (or is empty, or its keyspace churned
        mid-walk) — the caller degrades to the paged hash scan, handing it
        the already-computed (keys, leaf hashes) so the fallback doesn't
        re-hash the keyspace this cycle. Transport errors checkpoint
        (cursor, walk=True) and propagate, exactly like the paged walk, so
        the reconnect loop and cross-cycle resume machinery apply
        unchanged."""
        peer = report.peer
        with span("anti_entropy.walk", peer=peer) as rec:
            out, precomputed = self._sync_bisect_inner(
                client, report, deadline, start, prior_attempts, start_page
            )
            rec["walked"] = out
            rec["rounds"] = report.rounds
            rec["nodes_compared"] = report.nodes_compared
            rec["divergent"] = report.divergent
            return out, precomputed

    def _sync_bisect_inner(
        self,
        client: MerkleKVClient,
        report: SyncReport,
        deadline: Optional[Deadline],
        start: bytes,
        prior_attempts: int,
        start_page: int,
    ) -> tuple[bool, Optional[tuple[list[bytes], dict[bytes, bytes]]]]:
        from merklekv_tpu.merkle.cpu import build_levels, ref_level_sizes

        peer = report.peer
        metrics = get_metrics()
        # Progress baseline: repairs applied BEFORE the walk (resumed
        # pending sets) don't count as this walk's progress — only a cursor
        # that advanced past the checkpoint or fresh repairs re-earn
        # retries (the paged walk's rule); a walk that keeps dying at the
        # same frontier must accumulate attempts toward abandonment.
        base_repairs = report.set_keys + report.deleted_keys

        def attempts_now(cursor: bytes) -> int:
            progressed = (
                cursor != start
                or report.set_keys + report.deleted_keys > base_repairs
            )
            return 0 if progressed else prior_attempts

        def fail_checkpoint(cursor: bytes, why: str) -> None:
            # Descent failures are not page-stream faults, so the carried
            # page size passes through unshrunk.
            self._checkpoint(peer, [], 0, attempts_now(cursor),
                             cursor=cursor, page_size=start_page, walk=True)
            self._degrade(peer, why)
            metrics.inc("anti_entropy.interrupted_repairs")

        # Capability probe + remote leaf count: a zero-width TREELEVEL. An
        # old peer answers ERROR (degrade to paging); an empty peer is
        # cheaper to mirror with the paged scan. The probe also settles the
        # version-stamp capability and reports how far the donor's served
        # tree trails its live engine.
        try:
            _, remote_n = client.tree_level(0, 0, 0)
        except MovedError:
            # Partition mismatch mid-cycle (ownership moved between the
            # HASH probe and this one): NEVER degrade to the paged scan —
            # HASHPAGE/LEAFHASHES carry no partition address, so the
            # fallback would mirror the wrong partition's keyspace. Abort
            # the cycle like the root probe does.
            get_metrics().inc("anti_entropy.moved_peers")
            raise
        except ProtocolError:
            return False, None  # no TREELEVEL on this peer
        except (MerkleKVError, OSError) as e:
            fail_checkpoint(start, f"tree walk probe died: {e!r}")
            raise
        stamp = client.last_stamp
        if stamp is not None and stamp[1] > self._tree_lag_limit:
            # Bounded trailing exceeded: the donor's pump is wedged or
            # swamped, and a walk against its ancient tree would mostly
            # rediscover divergence the live engine already healed.
            # Escalate ONCE to a forced refresh (vs=03 drains the donor's
            # pump synchronously) and walk the fresh tree.
            try:
                _, remote_n = client.tree_level(0, 0, 0, force=True)
            except MovedError:
                get_metrics().inc("anti_entropy.moved_peers")
                raise  # same rule as the plain probe above
            except ProtocolError:
                return False, None
            except (MerkleKVError, OSError) as e:
                fail_checkpoint(start, f"tree walk force-probe died: {e!r}")
                raise
            report.forced_refreshes += 1
            metrics.inc("sync.forced_refreshes")
            report.details.append(
                f"{peer}: donor tree lag {stamp[1]} > "
                f"{self._tree_lag_limit}; forced refresh"
            )
            stamp = client.last_stamp
        if stamp is not None:
            report.donor_tree_version, report.donor_tree_lag = stamp
        if remote_n <= 0:
            return False, None

        report.mode = "bisect"

        # Local reference tree: one leaf-digest pass over the snapshot
        # (device-batched when the keyspace is large enough) and one
        # host-side node reduction. The paged scan pays the same leaf
        # pass; the node levels are what let this cycle SKIP shipping the
        # leaf digests of converged subtrees.
        local_items = self._engine.snapshot()
        local_keys = [k for k, _ in local_items]
        local_hashes = _leaf_map(
            local_items, self._use_device(len(local_items))
        )
        local_levels = build_levels([local_hashes[k] for k in local_keys])
        report.local_keys = len(local_items)
        precomputed = (local_keys, local_hashes)

        sizes = ref_level_sizes(remote_n)
        height = len(sizes)

        def local_node(level: int, idx: int) -> Optional[bytes]:
            if level < len(local_levels) and idx < len(local_levels[level]):
                return local_levels[level][idx]
            return None

        # Descend until a subtree spans only _WALK_LEAF_SPAN leaves; the
        # remaining tail is one small bounded leaf fetch per range.
        stop_level = 0
        while (1 << (stop_level + 1)) <= self._WALK_LEAF_SPAN:
            stop_level += 1
        stop_level = min(stop_level, height - 1)

        level = height - 1
        divergent = [0]  # the root differs (HASH compare, or mid-walk resume)
        clipped = False
        while level > stop_level and divergent:
            child_level = level - 1
            m_child = sizes[child_level]
            cand: list[int] = []
            for idx in divergent:
                lo = 2 * idx
                if lo < m_child:
                    cand.append(lo)
                if lo + 1 < m_child:
                    cand.append(lo + 1)
                # lo + 1 >= m_child: odd-promotion — the parent IS cand[lo].
            # One TREELEVEL fetch per contiguous index run (a sparse
            # frontier stays sparse on the wire).
            runs: list[tuple[int, int]] = []
            for idx in cand:
                if runs and runs[-1][1] == idx:
                    runs[-1] = (runs[-1][0], idx + 1)
                else:
                    runs.append((idx, idx + 1))
            remote_dig: dict[int, bytes] = {}
            for rlo, rhi in runs:
                try:
                    rows, n_now = client.tree_level(child_level, rlo, rhi)
                except ProtocolError as e:
                    # Mid-walk protocol garbage = corrupted stream (the
                    # probe already proved the verb): keep the verified
                    # cursor and abort the cycle.
                    fail_checkpoint(start, f"tree walk corrupted: {e!r}")
                    raise
                except (MerkleKVError, OSError) as e:
                    fail_checkpoint(start, f"tree walk died: {e!r}")
                    raise
                if n_now != remote_n:
                    if client.last_stamp is not None:
                        # Bounded trailing from a stamped donor: its pump
                        # republished mid-walk (versions moved within the
                        # staleness window), shifting leaf positions. The
                        # frontier verified SO FAR is still sound — CLIP:
                        # stop descending and repair the parent-level
                        # divergent intervals with key-bounded pages
                        # (churn-tolerant by construction) instead of
                        # abandoning the walk to a full paged scan.
                        report.details.append(
                            f"{peer}: keyspace churned mid-walk "
                            f"({remote_n} -> {n_now}); clipping to the "
                            f"verified frontier at level {level}"
                        )
                        report.walk_clipped = True
                        metrics.inc("sync.walk_clips")
                        clipped = True
                        break
                    # Unstamped (old) donor: no way to tell bounded
                    # trailing from unbounded churn — degrade to the paged
                    # scan, which tolerates churn natively (reusing this
                    # cycle's local hashes).
                    report.details.append(
                        f"{peer}: keyspace churned mid-walk "
                        f"({remote_n} -> {n_now}); paging instead"
                    )
                    report.mode = ""
                    return False, precomputed
                if client.last_stamp is not None:
                    report.donor_tree_version, report.donor_tree_lag = (
                        client.last_stamp
                    )
                for i, hx in rows:
                    remote_dig[i] = bytes.fromhex(hx)
            if clipped:
                # Keep `divergent`/`level` at the last FULLY-compared
                # parent frontier (the partial child fetches are from the
                # republished tree and must not mix in).
                break
            report.rounds += 1
            metrics.inc("sync.rounds")
            nxt = []
            for idx in cand:
                report.nodes_compared += 1
                if local_node(child_level, idx) != remote_dig.get(idx):
                    nxt.append(idx)
            metrics.inc("sync.nodes_compared", len(cand))
            divergent = nxt
            level = child_level
            if len(divergent) > self._MAX_WALK_FRONTIER:
                break  # massive divergence: coarse intervals win from here

        if not divergent:
            # Tree levels agree below the root but HASH differed: racing
            # writes between the probe and the walk. Nothing provably
            # divergent — the next cycle re-compares.
            report.details.append(f"{peer}: walk found no divergent subtree")
            return True, precomputed

        # Divergent nodes -> merged contiguous leaf intervals [a, b).
        span_len = 1 << level
        intervals: list[tuple[int, int]] = []
        for idx in sorted(divergent):
            a = idx * span_len
            b = min((idx + 1) * span_len, remote_n)
            if intervals and intervals[-1][1] >= a:
                intervals[-1] = (intervals[-1][0], b)
            else:
                intervals.append((a, b))

        # Repair each interval with range-bounded pages. Interval
        # boundaries come from VERIFIED positions (the invariant above), so
        # everything outside the fetched ranges is already converged.
        page_size = start_page
        for a, b in intervals:
            after = b"" if a == 0 else local_keys[a - 1]
            upto: Optional[bytes] = None
            if b < remote_n and b < len(local_keys):
                upto = local_keys[b]
            if start and after < start:
                after = start  # resume clip: prefix <= cursor is verified
            if upto is not None and upto <= after:
                continue  # fully repaired before the interruption
            # The adaptive page size carries across intervals (and, via the
            # checkpoint, across cycles): a hostile link shrinks it, clean
            # pages grow it back — same resilience rule as the paged scan.
            page_size = self._repair_range(
                client, report, deadline, after, upto, local_keys,
                local_hashes, attempts_now, start_page=page_size,
            )
            if peer in self._sessions:
                # Deadline checkpoint inside the range repair.
                return True, precomputed
        return True, precomputed

    def _repair_range(
        self,
        client: MerkleKVClient,
        report: SyncReport,
        deadline: Optional[Deadline],
        after: bytes,
        upto: Optional[bytes],
        local_keys: list[bytes],
        local_hashes: dict[bytes, bytes],
        attempts_now: Callable[[bytes], int],
        start_page: int = 0,
    ) -> int:
        """Converge one key range (after, upto) against the peer: bounded
        HASHPAGE pages, deletions applied engine-side, divergent values
        fetched in mget batches — the same page discipline (checkpoint
        shape AND adaptive sizing: halve after a dead stream, double after
        a clean page) as the full paged walk, scoped to a divergent
        subtree. Returns the final page size so the caller threads it
        through the remaining intervals (and checkpoints carry it across
        cycles)."""
        import bisect

        peer = report.peer
        size = min(start_page or self._hash_page, self._hash_page)
        size = max(size, self._MIN_HASH_PAGE)

        def shrunk() -> int:
            return max(self._MIN_HASH_PAGE, size // 2)

        cursor = after
        while True:
            if deadline is not None and deadline.expired():
                self._checkpoint(peer, [], 0, attempts_now(cursor),
                                 cursor=cursor, page_size=size, walk=True)
                self._degrade(peer, "per-peer cycle deadline expired")
                report.details.append(
                    f"{peer}: deadline expired mid-walk; cursor "
                    f"{cursor!r} checkpointed"
                )
                return size
            bounded = upto is not None and cursor != b""
            try:
                rows, done = client.leaf_hashes_page(
                    size,
                    cursor.decode("utf-8", "surrogateescape"),
                    upto=(
                        upto.decode("utf-8", "surrogateescape")
                        if bounded
                        else None
                    ),
                )
            except ProtocolError as e:
                self._checkpoint(peer, [], 0, attempts_now(cursor),
                                 cursor=cursor, page_size=shrunk(),
                                 walk=True)
                self._degrade(peer, f"walk leaf stream corrupted: {e!r}")
                get_metrics().inc("anti_entropy.interrupted_repairs")
                raise
            except (MerkleKVError, OSError) as e:
                self._checkpoint(peer, [], 0, attempts_now(cursor),
                                 cursor=cursor, page_size=shrunk(),
                                 walk=True)
                self._degrade(peer, f"walk leaf stream died: {e!r}")
                get_metrics().inc("anti_entropy.interrupted_repairs")
                raise
            if upto is not None and not bounded:
                # The wire can't carry a bound with an empty cursor: trim
                # client-side; anything trimmed proves the range ended.
                kept = []
                for k, h, ts in rows:
                    if k.encode("utf-8", "surrogateescape") >= upto:
                        done = True
                        break
                    kept.append((k, h, ts))
                rows = kept

            page: list[tuple[bytes, Optional[bytes], int]] = [
                (
                    k.encode("utf-8", "surrogateescape"),
                    bytes.fromhex(h) if h is not None else None,
                    ts,
                )
                for k, h, ts in rows
            ]
            page_keys = {k for k, _, _ in page}
            # Covered local range: (cursor, last page key], extended to the
            # range end once the peer reports the range exhausted.
            lo = bisect.bisect_right(local_keys, cursor)
            if done:
                hi = (
                    bisect.bisect_left(local_keys, upto)
                    if upto is not None
                    else len(local_keys)
                )
            else:
                hi = (
                    bisect.bisect_right(local_keys, page[-1][0])
                    if page
                    else lo
                )

            to_set: list[tuple[bytes, int]] = []
            for k, digest, ts in page:
                if digest is None:
                    # ts-0 sentinel: state unknown server-side; skip (the
                    # next cycle repairs it) — same rule as the paged walk.
                    if ts != 0 and k in local_hashes:
                        self._repair_delete(k, tomb_ts=ts)
                        report.deleted_keys += 1
                        report.divergent += 1
                    continue
                report.remote_keys += 1
                if local_hashes.get(k) != digest:
                    to_set.append((k, ts))
            for k in local_keys[lo:hi]:
                if k not in page_keys:
                    self._repair_delete(k)
                    report.deleted_keys += 1
                    report.divergent += 1
            report.divergent += len(to_set)

            next_cursor = page[-1][0] if page else cursor
            try:
                self._repair_sets_resumable(
                    client, peer, to_set, report, deadline, lww=False,
                    cursor=next_cursor, walk=True,
                )
            except Exception:
                # The value-fetch checkpoint can't know the page size;
                # stamp the shrunk one onto the session it just stored.
                sess = self._sessions.get(peer)
                if sess is not None:
                    sess.page_size = shrunk()
                raise
            if peer in self._sessions:
                # Deadline checkpoint inside the repair loop — not a link
                # fault, so the page size carries over unshrunk.
                self._sessions[peer].page_size = size
                return size
            cursor = next_cursor
            size = min(self._hash_page, size * 2)
            if done:
                return size

    # -- paged hash walk (small-keyspace pairwise path) -----------------------
    _MIN_HASH_PAGE = 16
    # In-cycle reconnect budget: a hostile link can kill every page stream;
    # the cycle keeps reconnecting and resuming from its checkpoint until
    # this cap (or the cycle deadline) is hit, then leaves the session for
    # the next cycle. Backoff between reconnects comes from the retry
    # policy, so the cap mostly guards against pathological fast-fail loops.
    _MAX_CYCLE_RECONNECTS = 32

    def _sync_hash_paged(
        self,
        client: MerkleKVClient,
        report: SyncReport,
        deadline: Optional[Deadline],
        start: bytes,
        prior_attempts: int = 0,
        start_page: int = 0,
        precomputed: Optional[tuple[list[bytes], dict[bytes, bytes]]] = None,
    ) -> bool:
        """Cursor-paged pairwise repair: fetch the peer's hash list one
        sorted key-range page at a time (HASHPAGE), repairing each page
        before fetching the next. The cursor only advances past a page once
        its repairs are applied, so the prefix <= cursor is a VERIFIED
        subtree: an injected fault or peer death mid-walk checkpoints
        (cursor, unapplied page remainder) and the next cycle resumes there
        instead of restarting. The page size adapts — halved after a dead
        stream (smaller exposure per round trip on a hostile link), doubled
        after a clean page, bounded by [``_MIN_HASH_PAGE``, ``hash_page``] —
        so progress stays monotonic under fault rates that would kill any
        all-or-nothing transfer. Returns False when the peer does not serve
        HASHPAGE (caller degrades to the monolithic paths)."""
        peer = report.peer
        # Pure-page cycles never send a fixed-arity traced verb, so the
        # donor's HASHPAGE spans would stay untraced (require_settled)
        # without this one probe per cycle.
        self._settle_trace_capability(client)
        # The local snapshot + hash pass is deferred until the first page
        # proves the peer serves HASHPAGE: against an old peer this path
        # bails to the monolithic fallback, which computes its own
        # snapshot/hashes — hashing up front would double that cost every
        # cycle for the whole upgrade window. A degraded bisection walk
        # hands over the (keys, hashes) it already computed this cycle, so
        # the fallback never re-hashes the keyspace.
        local_keys: list[bytes] = []
        local_hashes: Optional[dict[bytes, bytes]] = None
        if precomputed is not None:
            local_keys, local_hashes = precomputed
            report.local_keys = len(local_keys)
        report.mode = "hash-paged"

        import bisect

        cursor = start
        size = min(start_page or self._hash_page, self._hash_page)
        size = max(size, self._MIN_HASH_PAGE)
        pages = 0

        def progressed() -> bool:
            return cursor != start or report.set_keys + report.deleted_keys > 0

        def shrunk() -> int:
            return max(self._MIN_HASH_PAGE, size // 2)

        def attempts_now() -> int:
            return 0 if progressed() else prior_attempts

        while True:
            if deadline is not None and deadline.expired():
                self._checkpoint(peer, [], 0, attempts_now(),
                                 cursor=cursor, page_size=size)
                self._degrade(peer, "per-peer cycle deadline expired")
                report.details.append(
                    f"{peer}: deadline expired mid-walk; cursor "
                    f"{cursor!r} checkpointed after {pages} pages"
                )
                return True
            try:
                rows, done = client.leaf_hashes_page(
                    size, cursor.decode("utf-8", "surrogateescape")
                )
            except ProtocolError as e:
                if pages == 0 and "unknown command" in str(e).lower():
                    return False  # old peer: no HASHPAGE verb
                # Mid-walk protocol garbage is a corrupted stream, not a
                # capability miss: keep the verified prefix and abort the
                # cycle (the next one resumes from the cursor).
                self._checkpoint(peer, [], 0, attempts_now(),
                                 cursor=cursor, page_size=shrunk())
                self._degrade(peer, f"hash walk stream corrupted: {e!r}")
                get_metrics().inc("anti_entropy.interrupted_repairs")
                raise
            except (MerkleKVError, OSError) as e:
                self._checkpoint(peer, [], 0, attempts_now(),
                                 cursor=cursor, page_size=shrunk())
                self._degrade(peer, f"hash walk died: {e!r}")
                get_metrics().inc("anti_entropy.interrupted_repairs")
                raise
            if local_hashes is None:
                # One local hash pass per cycle (device-batched when the
                # keyspace is big enough) — per-page hashing would forfeit
                # the batching win.
                local_items = self._engine.snapshot()  # sorted (key, value)
                local_keys = [k for k, _ in local_items]
                local_hashes = _leaf_map(
                    local_items, self._use_device(len(local_items))
                )
                report.local_keys = len(local_items)
            pages += 1

            page: list[tuple[bytes, Optional[bytes], int]] = [
                (
                    k.encode("utf-8", "surrogateescape"),
                    bytes.fromhex(h) if h is not None else None,
                    ts,
                )
                for k, h, ts in rows
            ]
            page_keys = {k for k, _, _ in page}
            # Covered local range: (cursor, last page key], or everything
            # past the cursor once the peer reports the scan exhausted.
            lo = bisect.bisect_right(local_keys, cursor)
            hi = (
                len(local_keys)
                if done
                else bisect.bisect_right(local_keys, page[-1][0])
            )

            # Deletions first — engine-local, nothing to interrupt. A key
            # the page skips is absent on the peer (mirror its absence); a
            # tombstone row carries the peer's deletion ts to adopt.
            to_set: list[tuple[bytes, int]] = []
            for k, digest, ts in page:
                if digest is None:
                    # ts 0 is the server's "state unknown" sentinel (key
                    # vanished between page selection and read, tombstone
                    # evicted): adopting it would delete newer local data.
                    # The key stays in page_keys so the mirror-absence
                    # sweep below skips it too; the next cycle repairs it.
                    if ts != 0 and k in local_hashes:
                        self._repair_delete(k, tomb_ts=ts)
                        report.deleted_keys += 1
                        report.divergent += 1
                    continue
                report.remote_keys += 1
                if local_hashes.get(k) != digest:
                    to_set.append((k, ts))
            for k in local_keys[lo:hi]:
                if k not in page_keys:
                    self._repair_delete(k)
                    report.deleted_keys += 1
                    report.divergent += 1
            report.divergent += len(to_set)

            next_cursor = page[-1][0] if page else cursor
            # Value repairs for this page; a death here checkpoints the
            # page remainder WITH the advanced cursor (deletes are already
            # applied and the pending list captures the unapplied sets).
            try:
                self._repair_sets_resumable(
                    client, peer, to_set, report, deadline, lww=False,
                    cursor=next_cursor,
                )
            except Exception:
                sess = self._sessions.get(peer)
                if sess is not None:
                    sess.page_size = shrunk()
                raise
            if peer in self._sessions:
                # Deadline checkpoint inside the repair loop — not a link
                # fault, so the page size carries over unshrunk.
                self._sessions[peer].page_size = size
                return True
            cursor = next_cursor
            size = min(self._hash_page, size * 2)
            if done:
                return True

    # -- hash-first path ------------------------------------------------------
    def _fetch_remote_hashes(
        self, client: MerkleKVClient, report: SyncReport
    ) -> Optional[dict[bytes, tuple[bytes, int]]]:
        """Peer (leaf digest, last-write ts) map, or None if the peer can't
        serve LEAFHASHES."""
        try:
            # Decode INSIDE the try: a malformed digest line (corrupt peer,
            # or a future wire extension this reader doesn't know) must
            # degrade to the full-transfer fallback, not kill the cycle.
            return _decode_leaf_map(client.leaf_hashes_ts())
        except ProtocolError as e:
            # The peer answered but can't serve the verb (ERROR response):
            # the one case where full transfer is the right degradation.
            report.details.append(f"LEAFHASHES unsupported: {e!r}")
            get_metrics().inc("anti_entropy.leafhash_fallbacks")
            return None
        except (MerkleKVError, OSError):
            # Transport death mid-fetch. Falling back to full transfer
            # would push the ENTIRE keyspace over the same dying link —
            # strictly worse. Abort the cycle; the loop retries next round
            # (and any checkpointed session resumes).
            get_metrics().inc("anti_entropy.leafhash_aborts")
            raise
        except Exception as e:
            # Malformed payload from a live peer: full transfer re-derives
            # the hashes locally and still converges.
            report.details.append(f"LEAFHASHES undecodable: {e!r}")
            get_metrics().inc("anti_entropy.leafhash_fallbacks")
            return None

    def _sync_hash_first(
        self,
        client: MerkleKVClient,
        remote_hashes: dict[bytes, tuple[Optional[bytes], int]],
        report: SyncReport,
        deadline: Optional[Deadline] = None,
    ) -> None:
        local = {k: v for k, v in self._engine.snapshot()}
        # Live digests and tombstones arrive in one LEAFHASHES payload;
        # pairwise semantics stay strict local := remote over the LIVE
        # keyspace, with remote tombstone timestamps adopted so the copied
        # deletion keeps its original LWW position.
        remote_digests = {
            k: d for k, (d, _) in remote_hashes.items() if d is not None
        }
        remote_tombs = {
            k: ts for k, (d, ts) in remote_hashes.items() if d is None
        }
        report.remote_keys = len(remote_digests)
        report.local_keys = len(local)

        use_device = self._use_device(len(set(local) | set(remote_digests)))
        local_hashes = _leaf_map(sorted(local.items()), use_device)
        divergent = self._diff(local_hashes, remote_digests, use_device)
        report.divergent = len(divergent)

        # Local deletions first — no network involved, cannot be
        # interrupted by a peer death.
        to_set: list[tuple[bytes, int]] = []
        for k in divergent:
            if k in remote_digests:
                # Propagate the peer's last-write ts with the value so
                # LWW ordering metadata survives the repair.
                to_set.append((k, remote_hashes[k][1]))
            else:
                self._repair_delete(k, tomb_ts=remote_tombs.get(k))
                report.deleted_keys += 1
        # Value repairs apply BATCH BY BATCH in sorted key order so a peer
        # death mid-stream leaves a converged prefix applied and a
        # checkpointed remainder to resume, instead of losing the cycle.
        self._repair_sets_resumable(
            client, report.peer, to_set, report, deadline, lww=False
        )

    def _repair_sets_resumable(
        self,
        client: MerkleKVClient,
        peer: str,
        pairs: list[tuple[bytes, int]],
        report,  # SyncReport | MultiSyncReport (shared counter fields)
        deadline: Optional[Deadline],
        lww: bool,
        already_repaired: int = 0,
        prior_attempts: int = 0,
        cursor: bytes = b"",
        walk: bool = False,
    ) -> None:
        """Fetch+apply ``pairs`` in mget batches; checkpoint on failure.

        On a transport error (or an expired per-peer deadline) the
        remaining pairs become a SyncSession, the peer is marked degraded,
        and the error propagates (deadline expiry returns silently — the
        loop simply continues next interval). Keys repaired before the
        interruption STAY repaired. An attempt that made ANY progress
        re-earns its retries (attempts reset); only a stalled session is
        eventually abandoned by ``_take_session``.
        """
        repaired = already_repaired

        def attempts_now() -> int:
            return prior_attempts if repaired == already_repaired else 0

        for i in range(0, len(pairs), self._mget_batch):
            if deadline is not None and deadline.expired():
                self._checkpoint(peer, pairs[i:], repaired, attempts_now(),
                                 cursor=cursor, walk=walk)
                self._degrade(peer, "per-peer cycle deadline expired")
                report.details.append(
                    f"{peer}: deadline expired; {len(pairs) - i} repairs "
                    "checkpointed"
                )
                return
            batch = pairs[i : i + self._mget_batch]
            try:
                values = self._fetch_values(client, [k for k, _ in batch])
            except Exception as e:
                self._checkpoint(peer, pairs[i:], repaired, attempts_now(),
                                 cursor=cursor, walk=walk)
                self._degrade(peer, f"repair stream died: {e!r}")
                report.details.append(
                    f"{peer}: interrupted mid-repair ({e!r}); "
                    f"{len(pairs) - i} repairs checkpointed"
                )
                get_metrics().inc("anti_entropy.interrupted_repairs")
                raise
            report.values_fetched += len(values)
            for k, ts in batch:
                if k not in values:
                    # Deleted on the peer between LEAFHASHES and MGET;
                    # the next cycle repairs it.
                    continue
                if lww:
                    if self._repair_set_lww(k, values[k], ts):
                        report.set_keys += 1
                else:
                    self._repair_set(k, values[k], ts)
                    report.set_keys += 1
                repaired += 1

    # -- full path (reference behavior; --full or LEAFHASHES-less peer) -------
    def _sync_full(self, client: MerkleKVClient, report: SyncReport) -> None:
        remote = self._fetch_remote(client)
        local = {k: v for k, v in self._engine.snapshot()}
        report.remote_keys = len(remote)
        report.local_keys = len(local)
        report.values_fetched = len(remote)

        use_device = self._use_device(len(set(local) | set(remote)))
        local_hashes = _leaf_map(sorted(local.items()), use_device)
        remote_hashes = _leaf_map(sorted(remote.items()), use_device)
        divergent = self._diff(local_hashes, remote_hashes, use_device)
        report.divergent = len(divergent)

        for k in divergent:
            if k in remote:
                self._repair_set(k, remote[k])
                report.set_keys += 1
            else:
                self._repair_delete(k)
                report.deleted_keys += 1

    def _clamp_ts(self, ts: Optional[int]) -> Optional[int]:
        """Clock-skew guard for adopted peer timestamps: clamp anything
        beyond now + max_skew_ms BEFORE install/journal, mirroring the
        replicator's inbound clamp — anti-entropy must not re-import the
        poison the replication path already refused. Counted
        (``anti_entropy.skew_clamped``); clamping never changes WHO wins
        an arbitration (comparisons already happened), only how far into
        the future the installed fence reaches."""
        if ts is None or not self._max_skew_ns:
            return ts
        limit = time.time_ns() + self._max_skew_ns
        if ts <= limit:
            return ts
        get_metrics().inc("anti_entropy.skew_clamped")
        return limit

    def _repair_set(self, k: bytes, v: bytes, ts: Optional[int] = None) -> None:
        ts = self._clamp_ts(ts)
        if ts is None:
            self._engine.set(k, v)
        else:
            self._engine.set_with_ts(k, v, ts)
        if self._repair_listener is not None:
            self._repair_listener(k, v, ts)

    def _repair_set_lww(self, k: bytes, v: bytes, ts: int) -> bool:
        """Conditional install for multi-peer repair: a local write or
        deletion racing ahead of the fetched winner must not be clobbered."""
        ts = self._clamp_ts(ts)
        applied = self._engine.set_if_newer(k, v, ts)
        if applied and self._repair_listener is not None:
            self._repair_listener(k, v, ts)
        return applied

    def _repair_delete(self, k: bytes, tomb_ts: Optional[int] = None) -> None:
        """Pairwise repair deletion. With the peer's tombstone ts, adopt it
        (the deletion keeps its LWW position); without one this is a MIRROR
        copy of absence — delete_quiet, because fabricating a tombstone at
        "now" would later kill disjoint writes cluster-wide."""
        tomb_ts = self._clamp_ts(tomb_ts)
        if tomb_ts is None:
            if not hasattr(self._engine, "delete_quiet"):
                self._engine.delete(k)  # engine doubles without quiet mode
            else:
                self._engine.delete_quiet(k)
        elif not hasattr(self._engine, "delete_with_ts"):
            self._engine.delete(k)  # engine doubles without ts-carrying ops
        else:
            self._engine.delete_with_ts(k, tomb_ts)
        if self._repair_listener is not None:
            self._repair_listener(k, None, tomb_ts)

    def _repair_delete_lww(self, k: bytes, ts: int, was_present: bool) -> bool:
        """Conditional deletion for multi-peer repair (peer tombstone won).

        The listener fires on EVERY applied delete, not just when the
        start-of-cycle snapshot saw the key: a replication event may have
        installed it mid-cycle, and the device mirror must drop what the
        engine just dropped (apply_one(k, None) is a no-op for absent
        keys). ``was_present`` only scopes the report count."""
        ts = self._clamp_ts(ts)
        applied = self._engine.delete_if_newer(k, ts)
        if applied and self._repair_listener is not None:
            self._repair_listener(k, None, ts)
        return applied and was_present

    # -- multi-peer cycle -----------------------------------------------------
    def sync_multi(self, peers: list[str]) -> MultiSyncReport:
        """One anti-entropy cycle against ALL peers at once.

        Gathers every peer's (leaf hash, last-write ts) pairs AND tombstones
        (deletion records with timestamps), stacks the live digests with the
        local map into one ``[R, N]`` divergence program (merkle/diff.py),
        then arbitrates each divergent key by **per-key LWW** over the
        deterministic order ``(ts, liveness, digest)``: newest timestamp
        wins; at equal timestamps a live value beats a tombstone; live ties
        break toward the lexicographically larger digest. Only the winning
        values are fetched — grouped per peer so each value travels once —
        and installed conditionally (set_if_newer) WITH the winner's
        timestamp so ordering metadata propagates and racing local writes
        survive. A winning tombstone deletes locally (delete_if_newer), so
        a deletion whose replication event was dropped still converges
        cluster-wide instead of being resurrected by peers holding the old
        value. BARE absence (no value, no tombstone) still never wins: a
        fresh write seen by one node is never destroyed by peers that
        merely haven't received it yet. Every node running this same
        deterministic rule converges the cluster to the LWW-merged union
        keyspace. Timestamps are wall clocks — cross-node skew trades
        accuracy for availability exactly like the reference's replication
        LWW (replication.rs:289-290).

        The reference has no analog: its sync is strictly pairwise and
        full-transfer, and a deletion it hasn't replicated is undone
        forever (/root/reference/src/sync.rs:56-87,74-83).
        """
        traces = {p: PeerTrace(peer=p, mode="multi") for p in peers}
        started, t0 = time.time(), time.perf_counter()
        cid = next_cycle_id()
        tscope, tctx = self._cycle_trace_scope()
        try:
            with tscope, cycle_scope(cid), \
                    span("anti_entropy.sync_multi",
                         peers=",".join(peers)) as rec:
                report = self._sync_multi(peers, traces=traces)
                rec["divergent"] = report.divergent_union
                get_metrics().inc("anti_entropy.multi_syncs")
                get_metrics().inc(
                    "anti_entropy.keys_repaired",
                    report.set_keys + report.deleted_keys,
                )
                return report
        except Exception as e:
            for t in traces.values():
                if t.outcome == "ok" and not t.error:
                    t.outcome = "error"
                    t.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            get_trace_buffer().append(CycleTrace(
                cycle_id=cid, kind="multi", started_unix=started,
                seconds=time.perf_counter() - t0,
                peers=list(traces.values()),
                trace_id=tctx.trace_id if tctx is not None else 0,
            ))

    def _sync_multi(
        self,
        peers: list[str],
        traces: Optional[dict[str, PeerTrace]] = None,
    ) -> MultiSyncReport:
        import numpy as np

        from merklekv_tpu.merkle.diff import (
            align_replicas,
            divergence_masks_engine,
            divergence_masks_np,
        )

        t0 = time.perf_counter()
        report = MultiSyncReport(peers=list(peers))
        deadline = self._retry.deadline()

        # Gather peer leaf-hash+ts maps; a down peer is skipped this cycle.
        clients: list[Optional[MerkleKVClient]] = []
        peer_hashes: list[dict[bytes, tuple[Optional[bytes], int]]] = []

        def drop_peer(
            c: Optional[MerkleKVClient],
            peer: str,
            why: str,
            outcome: str = "skipped",
        ) -> None:
            # Every early-exit path must release the socket: this loop runs
            # every anti-entropy cycle, and an unclosed client per cycle is
            # a steady fd leak.
            if c is not None:
                c.close()
            report.details.append(why)
            clients.append(None)
            peer_hashes.append({})
            if traces is not None:
                traces[peer].outcome = outcome
                traces[peer].error = why
                if c is not None:
                    traces[peer].bytes_sent = c.bytes_sent
                    traces[peer].bytes_received = c.bytes_received

        for peer in peers:
            host, _, port = peer.rpartition(":")
            c: Optional[MerkleKVClient] = None
            try:
                c = self._attach_trace(
                    MerkleKVClient(host, int(port), timeout=self._timeout)
                )
                c.connect()
            except Exception as e:
                drop_peer(c, peer, f"{peer}: unreachable ({e!r})")
                continue
            # An interrupted repair from a previous cycle resumes before
            # this cycle's arbitration, so the local snapshot below already
            # reflects the repaired prefix.
            sess = self._take_session(peer)
            if sess is not None:
                report.resumed_peers.append(peer)
                get_metrics().inc("anti_entropy.sessions_resumed")
                repairs_before = report.set_keys
                try:
                    # cursor threads through so a re-checkpoint on failure
                    # keeps the paged walk's verified prefix — without it a
                    # failed resume would store cursor=b"" and the next
                    # sync_once would restart the walk from scratch.
                    self._repair_sets_resumable(
                        c, peer, sess.pending_sets, report, deadline,
                        lww=True, already_repaired=sess.repaired,
                        prior_attempts=sess.attempts, cursor=sess.cursor,
                        walk=sess.walk,
                    )
                except Exception as e:
                    if traces is not None:
                        traces[peer].repairs += (
                            report.set_keys - repairs_before
                        )
                    drop_peer(c, peer, f"{peer}: resume interrupted ({e!r})",
                              outcome="degraded")
                    report.degraded.append(peer)
                    continue
                if traces is not None:
                    traces[peer].repairs += report.set_keys - repairs_before
                if peer in self._sessions:
                    # Deadline expired mid-resume (silent checkpoint):
                    # arbitration for this peer would run on a spent budget
                    # and its wants-loop re-checkpoint would overwrite the
                    # saved paged-walk cursor with b"".
                    drop_peer(
                        c, peer,
                        f"{peer}: deadline expired mid-resume; checkpointed",
                        outcome="degraded",
                    )
                    report.degraded.append(peer)
                    continue
            if self._partition_id is not None:
                # Partition guard probe BEFORE the gather: the multi-peer
                # path fetches via LEAFHASHES (no pt= address on the
                # wire), so a stale-map peer serving a different
                # partition would contribute its disjoint keyspace to the
                # union and LWW would import it. One zero-width TREELEVEL
                # (pt=-addressed) turns that into a loud per-peer skip.
                try:
                    c.tree_level(0, 0, 0)
                except MovedError as e:
                    get_metrics().inc("anti_entropy.moved_peers")
                    drop_peer(
                        c, peer, f"{peer}: wrong partition ({e})",
                        outcome="error",
                    )
                    continue
                except Exception:
                    pass  # liveness/capability failures handled below
            self._settle_trace_capability(c)
            try:
                decoded = _decode_leaf_map(c.leaf_hashes_ts())
            except Exception:
                # Peer serves data but not LEAFHASHES (the pairwise path's
                # full-transfer fallback, here too): fetch its snapshot and
                # hash locally. Entries carry ts 0 ("unknown age"), so the
                # peer contributes missing keys to the union but loses
                # every LWW race — it can never overwrite fresher state.
                get_metrics().inc("anti_entropy.leafhash_fallbacks")
                try:
                    remote = self._fetch_remote(c)
                    decoded = {
                        k: (d, 0)
                        for k, d in _leaf_map(
                            sorted(remote.items()), False
                        ).items()
                    }
                    report.details.append(
                        f"{peer}: LEAFHASHES unsupported; full snapshot"
                    )
                except Exception as e:
                    drop_peer(c, peer, f"{peer}: unreachable ({e!r})")
                    continue
            clients.append(c)
            peer_hashes.append(decoded)
        live = [i for i, c in enumerate(clients) if c is not None]
        try:
            if not live:
                report.seconds = time.perf_counter() - t0
                return report

            local = {k: v for k, v in self._engine.snapshot()}
            use_device = self._use_device(
                len(set(local).union(*[set(p) for p in peer_hashes]))
            )
            local_hashes = _leaf_map(sorted(local.items()), use_device)

            # Replica 0 = local; only live peers join the arbitration.
            # Each peer's payload splits into live digests (alignment input)
            # and tombstones (deletion candidates for the LWW round).
            peer_maps = [peer_hashes[i] for i in live]
            peer_live = [
                {k: (d, ts) for k, (d, ts) in pm.items() if d is not None}
                for pm in peer_maps
            ]
            peer_tombs = [
                {k: ts for k, (d, ts) in pm.items() if d is None}
                for pm in peer_maps
            ]
            local_tombs = dict(self._engine.tombstones())
            replicas = [local_hashes] + [
                {k: d for k, (d, _) in pl.items()} for pl in peer_live
            ]
            aligned = align_replicas(replicas)
            report.union_keys = aligned.n_keys
            if aligned.n_keys == 0:
                report.seconds = time.perf_counter() - t0
                return report
            if use_device:
                try:
                    from merklekv_tpu.utils.jaxenv import ensure_platform

                    ensure_platform()
                    # Engine boundary: the N-replica comparison shards over
                    # the local device mesh when one exists and the union
                    # keyspace amortizes it (bit-identical masks).
                    masks = np.asarray(
                        divergence_masks_engine(
                            aligned.digests, aligned.present
                        )
                    )
                except Exception as e:
                    jaxenv.note_device_failure(e, "divergence masks")
                    masks = divergence_masks_np(
                        aligned.digests, aligned.present
                    )
            else:
                masks = divergence_masks_np(aligned.digests, aligned.present)
            report.per_peer_divergent = {
                peers[i]: int(masks[slot].sum())
                for slot, i in enumerate(live, start=1)
            }
            if traces is not None:
                for p, d in report.per_peer_divergent.items():
                    traces[p].divergent = d
            divergent = np.nonzero(masks.any(axis=0))[0]
            report.divergent_union = int(len(divergent))

            # Vectorized per-key LWW among replicas holding the key OR a
            # tombstone for it (bare absence never wins — see docstring).
            # Candidate order is (ts, liveness, digest words): liveness 1
            # for a value, 0 for a tombstone, so a value wins timestamp
            # ties — matching the engine's set_if_newer/del_if_newer rule.
            # The former per-key Python loop was O(divergent x replicas)
            # tuple comparisons + one FFI get_ts per key — at the
            # 10M/1%-divergence scale that is ~100K iterations per cycle;
            # here winner selection is 10 elementwise passes over [R, D].
            n_div = len(divergent)
            n_rep = len(replicas)
            keys_div = [aligned.keys[i] for i in divergent]
            sub = np.ascontiguousarray(
                aligned.digests[:, divergent, :]
            ).astype(">u4")
            raw_digests = sub.tobytes()

            def dig(r: int, j: int) -> bytes:
                off = (r * n_div + j) * 32
                return raw_digests[off : off + 32]

            pres = aligned.present[:, divergent]  # [R, D] bool
            # Local last-write timestamps: one bulk export when much of the
            # keyspace diverged, per-key FFI reads when divergence is small
            # relative to the keyspace (a 10M-entry dict per cycle would
            # dwarf a few thousand C calls).
            if n_div * 8 >= len(local):
                local_ts_map = dict(self._engine.key_timestamps())

                def local_ts(k: bytes) -> int:
                    return local_ts_map.get(k, 0)
            else:
                def local_ts(k: bytes) -> int:
                    return self._engine.get_ts(k) or 0

            # Timestamps clamp to int64 max: the matrix is int64 (-1 = no
            # candidate) and a peer with a corrupt clock reporting a uint64
            # ts >= 2^63 must lose gracefully in arbitration, not abort the
            # whole cycle with an OverflowError.
            _I64MAX = (1 << 63) - 1
            ts_m = np.zeros((n_rep, n_div), np.int64)
            ts_m[0] = [
                min(local_ts(k), _I64MAX)
                if p
                else min(local_tombs.get(k, -1), _I64MAX)
                for k, p in zip(keys_div, pres[0])
            ]
            for slot in range(1, n_rep):
                pl, pt = peer_live[slot - 1], peer_tombs[slot - 1]
                ts_m[slot] = [
                    min(pl[k][1], _I64MAX) if p else min(pt.get(k, -1), _I64MAX)
                    for k, p in zip(keys_div, pres[slot])
                ]
            live_m = pres.astype(np.int64)
            valid = ts_m >= 0  # a value or a recorded tombstone

            # Successive narrowing to the (ts, liveness, w0..w7) maximum.
            cand = valid.copy()
            words = sub.astype(np.int64)  # [R, D, 8], big-endian word order
            for crit in (ts_m, live_m, *(words[:, :, w] for w in range(8))):
                masked = np.where(cand, crit, np.int64(-1))
                cand &= masked == masked.max(axis=0)[None, :]
            winner_slot = np.argmax(cand, axis=0)  # first max row; digest
            # ties beyond word 7 mean identical winning state on both rows.
            any_valid = valid.any(axis=0)
            winner_ts_arr = ts_m[winner_slot, np.arange(n_div)]
            winner_live_arr = live_m[winner_slot, np.arange(n_div)] == 1

            # wants[peer_slot] = (key, winner_ts) pairs that peer serves.
            wants: dict[int, list[tuple[bytes, int]]] = {}
            for j in np.nonzero(any_valid)[0]:
                key = keys_div[j]
                ws = int(winner_slot[j])
                winner_ts = int(winner_ts_arr[j])
                local_present = bool(pres[0, j])
                if not winner_live_arr[j]:
                    # A deletion won: apply it locally unless local state is
                    # newer (delete_if_newer re-checks under the shard lock).
                    if self._repair_delete_lww(key, winner_ts, local_present):
                        report.deleted_keys += 1
                    continue
                if ws == 0:
                    continue  # local already holds the winning state
                winner = dig(ws, j)
                if local_present and dig(0, j) == winner:
                    continue  # same digest locally; nothing to fetch
                wants.setdefault(live[ws - 1], []).append((key, winner_ts))

            for r, pairs in wants.items():
                # A peer dying mid-fetch no longer aborts the whole cycle:
                # its remaining repairs are checkpointed (resumed next
                # cycle), it is marked degraded, and the other peers'
                # repairs proceed.
                peer = peers[r]
                repairs_before = report.set_keys
                try:
                    self._repair_sets_resumable(
                        clients[r], peer, pairs, report, deadline, lww=True
                    )
                except Exception:
                    report.degraded.append(peer)
                    continue
                finally:
                    if traces is not None:
                        traces[peer].repairs += (
                            report.set_keys - repairs_before
                        )
                if peer in self._sessions:  # deadline checkpoint, no raise
                    report.degraded.append(peer)
        finally:
            for i, c in enumerate(clients):
                if c is not None:
                    if traces is not None:
                        traces[peers[i]].bytes_sent = c.bytes_sent
                        traces[peers[i]].bytes_received = c.bytes_received
                    c.close()
            if traces is not None:
                for p in report.degraded:
                    traces[p].outcome = "degraded"
            for peer in peers:
                self._session_done(peer)

        report.seconds = time.perf_counter() - t0
        self.last_multi_report = report
        return report

    def _use_device(self, n_union: int) -> bool:
        if jaxenv.device_failed():
            return False  # sticky CPU fallback after a device failure
        return self._device == "tpu" or (
            self._device == "auto" and n_union >= _DEVICE_THRESHOLD
        )

    def _diff(
        self,
        local_hashes: dict[bytes, bytes],
        remote_hashes: dict[bytes, bytes],
        use_device: bool,
    ) -> list[bytes]:
        if use_device:
            try:
                from merklekv_tpu.utils.jaxenv import ensure_platform

                ensure_platform()
                from merklekv_tpu.merkle.diff import diff_keys_pair

                return diff_keys_pair(local_hashes, remote_hashes)
            except Exception as e:
                jaxenv.note_device_failure(e, "pairwise diff")
        keys = set(local_hashes) | set(remote_hashes)
        return sorted(
            k for k in keys if local_hashes.get(k) != remote_hashes.get(k)
        )

    def _fetch_remote(self, c: MerkleKVClient) -> dict[bytes, bytes]:
        """Snapshot over an already-open connection: SCAN, then batched MGET."""
        return self._mget_all(c, c.scan())

    def _fetch_values(
        self, c: MerkleKVClient, keys: list[bytes]
    ) -> dict[bytes, bytes]:
        """Targeted value fetch for the divergent set only."""
        return self._mget_all(
            c, [k.decode("utf-8", "surrogateescape") for k in keys]
        )

    def _mget_all(
        self, c: MerkleKVClient, keys: list[str]
    ) -> dict[bytes, bytes]:
        out: dict[bytes, bytes] = {}
        for i in range(0, len(keys), self._mget_batch):
            batch = keys[i : i + self._mget_batch]
            for k, v in c.mget(batch).items():
                if v is None:
                    # MGET's wire format can't distinguish a missing key
                    # from a literal "NOT_FOUND" value; GET can (the
                    # "VALUE " prefix). The key came from SCAN/LEAFHASHES,
                    # so only a concurrent delete or that literal value
                    # lands here.
                    v = c.get(k)
                    if v is None:
                        continue
                out[k.encode("utf-8", "surrogateescape")] = v.encode(
                    "utf-8", "surrogateescape"
                )
        return out

    # -- periodic loop ---------------------------------------------------------
    def start_loop(
        self,
        peers: list[str],
        interval_seconds: float,
        multi_peer: bool = False,
        peer_up=None,  # Callable[[str], bool] from the health monitor
        pause_when=None,  # Callable[[], bool] from the overload monitor
    ) -> None:
        """Periodic anti-entropy: pairwise per peer, or one fused
        multi-peer arbitration cycle when ``multi_peer`` is set.

        ``peer_up`` (the failure detector's verdict) lets a cycle skip
        confirmed-down peers instead of paying a connect timeout each; the
        monitor keeps probing, so a recovered peer rejoins the next cycle.

        ``pause_when`` (the overload monitor's verdict) defers whole
        cycles while the node is above a resource watermark: a sync cycle
        materializes leaf maps and repair batches, exactly the allocation
        a memory-pressured node must not make, and journals repairs a
        disk-full node cannot. Deferred cycles are counted
        (``anti_entropy.overload_skips``) and never fire the converged
        hook — lag residue stays visible until a real full pass runs
        after recovery.
        """

        def up(peer: str) -> bool:
            if peer_up is None:
                return True
            try:
                return bool(peer_up(peer))
            except Exception:
                return True  # a broken detector must not stall repairs

        def run() -> None:
            while not self._stop.wait(interval_seconds):
                if pause_when is not None:
                    try:
                        paused = bool(pause_when())
                    except Exception:
                        paused = False  # a broken monitor must not stall
                    if paused:
                        get_metrics().inc("anti_entropy.overload_skips")
                        continue
                live_peers = [p for p in peers if up(p)]
                skipped = len(peers) - len(live_peers)
                if skipped:
                    get_metrics().inc("anti_entropy.down_peer_skips", skipped)
                # Full clean pass: EVERY configured peer synced this round
                # with nothing checkpointed/degraded/skipped. Only that
                # proves enough coverage to clear dropped-frame lag
                # residue (see __init__ on the hook).
                full_pass = skipped == 0 and bool(live_peers)
                if multi_peer:
                    if not live_peers:
                        continue
                    try:
                        rep = self.sync_multi(live_peers)
                        full_pass = full_pass and not rep.degraded
                    except Exception:
                        # Retried next round — but never silently: a loop
                        # that throws every cycle looks like a healthy
                        # no-op without this counter.
                        get_metrics().inc("anti_entropy.loop_errors")
                        full_pass = False
                    self._fire_converged(full_pass)
                    continue
                for peer in live_peers:
                    if self._stop.is_set():
                        return
                    host, _, port = peer.rpartition(":")
                    try:
                        self.sync_once(host, int(port))
                    except Exception as e:
                        # Peer down or stream interrupted: any checkpointed
                        # session resumes next round, the health table
                        # carries the degradation, and the loop moves on.
                        # Only degrade here if the cycle didn't already —
                        # a second mark would double-count the metric and
                        # bury the specific reason under this generic one.
                        get_metrics().inc("anti_entropy.loop_errors")
                        if peer not in self._degraded_this_cycle:
                            self._degrade(peer, f"sync cycle failed: {e!r}")
                        full_pass = False
                        continue
                    if (
                        peer in self._sessions
                        or peer in self._degraded_this_cycle
                    ):
                        full_pass = False
                self._fire_converged(full_pass)

        self._stop.clear()
        self._loop_thread = threading.Thread(
            target=run, daemon=True, name="mkv-anti-entropy"
        )
        self._loop_thread.start()

    def _fire_converged(self, full_pass: bool) -> None:
        if full_pass and self._on_cycle_converged is not None:
            try:
                self._on_cycle_converged()
            except Exception:
                pass  # a broken lag hook must never stall the loop

    def stop(self) -> None:
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None

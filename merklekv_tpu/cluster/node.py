"""ClusterNode: wires replication + anti-entropy to a running native server.

Owns the SYNC / REPLICATE cluster-command callback (the native server
delegates those verbs here), the Replicator lifecycle (REPLICATE
enable/disable/status, reference server.rs:686-720), and the periodic
anti-entropy loop.
"""

from __future__ import annotations

import sys
import threading
from typing import Optional

from merklekv_tpu.cluster.replicator import Replicator
from merklekv_tpu.cluster.sync import SyncManager
from merklekv_tpu.cluster.transport import Transport, make_transport
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer

__all__ = ["ClusterNode"]


class ClusterNode:
    def __init__(
        self,
        cfg: Config,
        engine: NativeEngine,
        server: NativeServer,
        transport: Optional[Transport] = None,
        storage=None,  # Optional[DurableStore], already recovered
    ) -> None:
        self._cfg = cfg
        self._engine = engine
        self._server = server
        self._storage = storage
        self._transport = transport
        self._owns_transport = transport is None
        self._replicator: Optional[Replicator] = None
        self._mirror = None  # DeviceTreeMirror, alive while replication is on
        self._health = None  # PeerHealthMonitor, alive with the sync loop
        self._rep_mu = threading.Lock()
        self.sync_manager = SyncManager(
            engine,
            device=cfg.anti_entropy.engine,
            repair_listener=self._on_sync_repair,
            on_peer_degraded=self._on_peer_degraded,
            mode=cfg.anti_entropy.mode,
            bisect_threshold=cfg.anti_entropy.bisect_threshold,
        )

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._server.set_cluster_handler(self._on_cluster_command)
        if self._storage is not None:
            # WAL recording: the store drains the native change-event queue
            # itself until a Replicator takes over the drain (then the
            # store rides its batch listener — one queue, one consumer).
            self._storage.attach_server(self._server)
        if self._cfg.replication.enabled:
            err = self._enable_replication()
            if err is not None:
                print(f"replication not started: {err}", file=sys.stderr,
                      flush=True)
        if self._cfg.anti_entropy.enabled and self._cfg.anti_entropy.peers:
            # Failure detection: probe peers off the sync path so the loop
            # can skip confirmed-down peers instead of burning a connect
            # timeout per cycle (reference has no peer health, SURVEY §5.3).
            from merklekv_tpu.cluster.health import PeerHealthMonitor

            self._health = PeerHealthMonitor(
                self._cfg.anti_entropy.peers,
                interval_seconds=min(
                    self._cfg.anti_entropy.interval_seconds, 2.0
                ),
            )
            self._health.start()
            self.sync_manager.start_loop(
                self._cfg.anti_entropy.peers,
                self._cfg.anti_entropy.interval_seconds,
                multi_peer=self._cfg.anti_entropy.multi_peer,
                peer_up=self._health.is_up,
            )

    def stop(self) -> None:
        self.sync_manager.stop()
        if self._health is not None:
            self._health.stop()
            self._health = None
        self._disable_replication()
        if self._owns_transport and self._transport is not None:
            self._transport.close()
            self._transport = None
        self._server.set_cluster_handler(None)

    @property
    def replicator(self) -> Optional[Replicator]:
        return self._replicator

    # -- replication management ---------------------------------------------
    def _get_transport(self) -> Transport:
        if self._transport is None:
            rep = self._cfg.replication
            self._transport = make_transport(
                rep.mqtt_broker,
                rep.mqtt_port,
                kind=rep.transport,
                client_id=rep.client_id,
                username=rep.username,
                password=rep.password,
            )
        return self._transport

    def _enable_replication(self) -> Optional[str]:
        with self._rep_mu:
            if self._replicator is not None:
                return None  # already enabled
            try:
                transport = self._get_transport()
            except OSError as e:
                return f"broker unreachable: {e}"
            # The mirror is only trustworthy while the event queue feeds it,
            # i.e. while replication is enabled — so its lifecycle is tied
            # to the replicator's. "cpu" pins anti-entropy (and HASH) to the
            # host path; anything else serves HASH from the device tree.
            if self._cfg.anti_entropy.engine != "cpu":
                from merklekv_tpu.cluster.mirror import DeviceTreeMirror

                self._mirror = DeviceTreeMirror(
                    self._engine,
                    sharded=self._cfg.device.sharded_mirror,
                )
            storage = self._storage
            if storage is not None:
                # Hand the event-queue drain to the replicator; local
                # writes reach the WAL through its batch listener, remote
                # applies through the storage hook inside the replicator.
                storage.pause_drain()
            try:
                self._replicator = Replicator(
                    self._engine,
                    self._server,
                    transport,
                    topic_prefix=self._cfg.replication.topic_prefix,
                    node_id=self._cfg.replication.client_id,
                    mirror=self._mirror,
                    batch_listener=(
                        storage.record_events if storage is not None else None
                    ),
                    storage=storage,
                )
                self._replicator.start()
            except Exception as e:
                # Take the drain back: a half-failed enable must not leave
                # WAL recording paused with no batch listener feeding it.
                self._replicator = None
                if storage is not None:
                    self._server.enable_events(True)
                    storage.resume_drain()
                return f"replicator start failed: {e}"
            return None

    def _disable_replication(self) -> None:
        with self._rep_mu:
            if self._replicator is not None:
                self._replicator.stop()
                self._replicator = None
                if self._storage is not None:
                    # Replicator.stop() turned event staging off; the WAL
                    # still needs it — take the drain back.
                    self._server.enable_events(True)
                    self._storage.resume_drain()
            if self._mirror is not None:
                # Before any teardown of the native engine: the mirror's
                # warm thread reads through the engine's raw pointer.
                self._mirror.close()
                self._mirror = None

    def _on_peer_degraded(self, peer: str, reason: str) -> None:
        """A sync stream against ``peer`` died mid-cycle (its remaining
        repairs are checkpointed for resume); reflect it in the health
        table so PEERS shows the degradation while probes keep running."""
        h = self._health
        if h is not None:
            h.mark_degraded(peer, reason)

    def _on_sync_repair(self, key: bytes, value, ts=None) -> None:
        """Anti-entropy repairs bypass the server event queue; feed the
        device mirror directly so HASH stays truthful after a SYNC, and the
        WAL so a repaired key survives a crash without needing re-repair."""
        with self._rep_mu:
            mirror = self._mirror
        if mirror is not None:
            mirror.apply_one(key, value)
        storage = self._storage
        if storage is not None:
            # Journal at the EXACT ts the repair installed (threaded through
            # the listener — an engine read-back here could race a newer
            # concurrent writer and journal the repair value under the
            # winner's timestamp). ts None means the repair carried no
            # ordering metadata (delete_quiet absence copy, legacy full
            # transfer): skip the journal rather than fabricate a ts —
            # anti-entropy re-repairs after a crash.
            if ts is None:
                return
            if value is None:
                storage.record_delete(key, ts)
            else:
                storage.record_set(key, value, ts)

    def _query_ready_mirror(self, fn):
        """Shared gate for device-tree reads (HASH root, TREELEVEL slices):
        returns ``fn(mirror)`` after flushing staged events through the
        replicator (read-your-writes), or None whenever the device path
        can't answer — replication off, device disabled, mirror still
        warming (a warm-up is kicked off), or any device failure — so the
        native fallback serves instead and nothing stalls on the device."""
        with self._rep_mu:
            rep, mirror = self._replicator, self._mirror
        if rep is None or mirror is None:
            return None
        if not mirror.ready():
            mirror.start_warming()  # no-op if already in flight
            return None
        try:
            rep.flush()  # serve root-consistent state: drain staged events
            return fn(mirror)
        except Exception:
            return None  # native fallback answers instead

    def device_tree_level(self, level: int, lo: int, hi: int):
        """TREELEVEL answer from the live device tree: ``(rows, n)`` with
        reference-level ``(idx, digest)`` rows, or None when the mirror
        isn't ready (the native server's host-side cached tree answers
        meanwhile, so peers' walks never stall on a warming mirror)."""
        return self._query_ready_mirror(
            lambda m: m.level_nodes(level, lo, hi)
        )

    def device_root_hex(self) -> Optional[str]:
        """Whole-keyspace Merkle root from the live device tree, or None
        when the mirror isn't ready (replication off / device disabled /
        still warming — the native path answers meanwhile)."""
        return self._query_ready_mirror(lambda m: m.root_hex())

    @property
    def health(self):
        return self._health

    def _metrics_wire(self) -> str:
        """METRICS wire payload: the control plane's counter snapshot —
        transport reconnects/outbox drops, anti-entropy loop counters, span
        counts — as ``name:value`` lines. The complement of STATS, which
        covers the native engine/server scope only."""
        from merklekv_tpu.utils.tracing import get_metrics

        lines = []
        snap = get_metrics().snapshot()
        for name in sorted(snap["counters"]):
            lines.append(f"{name}:{snap['counters'][name]}")
        # Span aggregates (integers only — the parsers treat values as
        # numeric text): count and total milliseconds per span name.
        for name in sorted(snap["spans"]):
            sp = snap["spans"][name]
            lines.append(f"span.{name}.count:{sp['count']}")
            lines.append(f"span.{name}.total_ms:{int(sp['total_s'] * 1e3)}")
        t = self._transport
        if t is not None:
            for attr in ("reconnects", "outbox_dropped", "callback_errors"):
                v = getattr(t, attr, None)
                if v is not None:
                    lines.append(f"transport.{attr}_live:{v}")
        body = "".join(f"{ln}\r\n" for ln in lines)
        return f"METRICS\r\n{body}END\r\n"

    # -- cluster command callback ---------------------------------------------
    def _on_cluster_command(self, line: str) -> Optional[str]:
        parts = line.split()
        if parts[0] == "PEERS":
            if self._health is None:
                return None  # native default: empty table
            return self._health.wire_table()
        if parts[0] == "METRICS":
            return self._metrics_wire()
        if parts[0] == "HASH":
            # Whole-keyspace root served from the device-resident
            # incremental tree; empty answer falls back to the native path.
            root = self.device_root_hex()
            return f"HASH {root}\r\n" if root is not None else None
        if parts[0] == "TREELEVEL":
            # Bisection-walk node fetch served from the device-resident
            # tree (one batched device gather per request); empty answer
            # falls back to the native server's cached host tree.
            out = self.device_tree_level(
                int(parts[1]), int(parts[2]), int(parts[3])
            )
            if out is None:
                return None
            rows, n = out
            body = "".join(f"{i} {d.hex()}\r\n" for i, d in rows)
            return f"NODES {len(rows)} {n}\r\n{body}"
        if parts[0] == "SYNC":
            host, port = parts[1], int(parts[2])
            try:
                self.sync_manager.sync_once(
                    host,
                    port,
                    full="--full" in parts,
                    verify="--verify" in parts,
                )
                return "OK\r\n"
            except Exception as e:
                return f"ERROR {e}\r\n"
        if parts[0] == "REPLICATE":
            action = parts[1]
            if action == "enable":
                err = self._enable_replication()
                return "OK\r\n" if err is None else f"ERROR {err}\r\n"
            if action == "disable":
                self._disable_replication()
                return "OK\r\n"
            if action == "status":
                with self._rep_mu:
                    enabled = self._replicator is not None
                if enabled:
                    n = len(self._cfg.replication.peer_list)
                    return f"REPLICATION enabled {n} nodes\r\n"
                return "REPLICATION disabled\r\n"
        return None

"""ClusterNode: wires replication + anti-entropy to a running native server.

Owns the SYNC / REPLICATE cluster-command callback (the native server
delegates those verbs here), the Replicator lifecycle (REPLICATE
enable/disable/status, reference server.rs:686-720), and the periodic
anti-entropy loop.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

from merklekv_tpu.cluster.overload import (
    DRAINING,
    LEVEL_NAMES,
    REASON_CODES,
    SHEDDING,
    DegradationLadder,
    OverloadMonitor,
)
from merklekv_tpu.cluster.replicator import Replicator
from merklekv_tpu.cluster.sync import SyncManager
from merklekv_tpu.cluster.transport import Transport, make_transport
from merklekv_tpu.config import Config
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.obs import tracewire
from merklekv_tpu.obs.lag import ConvergenceTracker

__all__ = ["ClusterNode"]


class ClusterNode:
    def __init__(
        self,
        cfg: Config,
        engine: NativeEngine,
        server: NativeServer,
        transport: Optional[Transport] = None,
        storage=None,  # Optional[DurableStore], already recovered
    ) -> None:
        self._cfg = cfg
        self._engine = engine
        self._server = server
        self._storage = storage
        self._transport = transport
        self._owns_transport = transport is None
        self._replicator: Optional[Replicator] = None
        self._mirror = None  # DeviceTreeMirror, alive while replication is on
        self._health = None  # PeerHealthMonitor, alive with the sync loop
        self._rep_mu = threading.Lock()
        self._exporter = None  # MetricsExporter, alive while the node runs
        self._gauge_names: list = []  # (name, fn) pairs we registered
        self._bootstrap = None  # BootstrapSession while a (re)join runs
        self._bootstrap_thread: Optional[threading.Thread] = None
        self._stopped = False  # guards late starts from the bootstrap thread
        # Convergence-lag SLO plane: per-peer lag from envelope publish
        # HWMs, residue cleared when an anti-entropy cycle converges, and
        # the /healthz readiness level (live|lagging|diverged).
        self.lag_tracker = ConvergenceTracker(
            lag_ms_threshold=cfg.observability.lag_ms_threshold,
            diverged_after_s=cfg.observability.diverged_after_s,
        )
        # One PROFILE capture at a time; directory returned on start.
        self._profile_mu = threading.Lock()
        self._profiling = False
        # Flight recorder (post-mortem black box): sampler + durable spill
        # started in start() per [observability] flight settings.
        self._flight_sampler = None
        self._flight_spiller = None
        # Overload-protection plane: the node-wide degradation ladder
        # (live -> shedding -> read_only -> draining), fed by the memory /
        # disk watermark monitor and enforced by the native server.
        self.ladder = DegradationLadder()
        self._overload: Optional[OverloadMonitor] = None
        # Partitioned cluster mode: this node owns ONE partition of a
        # P-way keyspace. The map (validated here, served via PARTMAP) is
        # the routing table; the native guard refuses foreign keys with
        # ERROR MOVED; replication rides a partition-local topic; and the
        # anti-entropy peer set defaults to the partition's sibling
        # replicas — so failures, overload, repair, and bootstrap all stay
        # partition-local by construction (the node's whole-keyspace root
        # IS the per-partition Merkle root).
        self._partmap = None
        self._partition_id: Optional[int] = None
        # Live-rebalance plane: the per-node session state machine (donor /
        # joiner / sibling roles), built lazily on the first REBALANCE verb.
        self._rebalance = None
        self._rebalance_mu = threading.Lock()
        if cfg.cluster.partitions > 0:
            from merklekv_tpu.cluster.partmap import parse_map_spec

            if not 0 <= cfg.cluster.partition_id < cfg.cluster.partitions:
                # Config.from_dict validates TOML-loaded configs; a
                # programmatically built Config bypasses it, and the
                # default partition_id of -1 would silently derive peers
                # from replicas[-1] (the LAST partition) while the native
                # guard clamps to 0 — a loud startup error beats a node
                # enforcing one partition while syncing against another.
                raise ValueError(
                    "[cluster] partition_id must be in "
                    f"[0, {cfg.cluster.partitions}), got "
                    f"{cfg.cluster.partition_id}"
                )
            self._partmap = parse_map_spec(
                cfg.cluster.partition_map,
                cfg.cluster.partitions,
                cfg.cluster.map_epoch,
            )
            self._partition_id = cfg.cluster.partition_id
        if storage is not None:
            # Durable map-file overlay: a node that committed a split
            # persists epoch E+1 (and its possibly-new partition id) under
            # its storage directory at the rebalance commit point. Boot
            # config is typically still at E, so the file — strictly newer
            # — wins; this is what makes the epoch flip survive kill -9.
            # It also resurrects a committed JOINER (whose boot config has
            # partitions == 0) straight into its adopted partition.
            from merklekv_tpu.cluster.partmap import load_map_file

            loaded = load_map_file(storage.directory)
            if loaded is not None:
                pmap, pid = loaded
                if self._partmap is None or pmap.epoch > self._partmap.epoch:
                    self._partmap = pmap
                    self._partition_id = pid
                    cfg.anti_entropy.peers = []  # re-derive from the map
        if self._partmap is not None:
            if not cfg.anti_entropy.peers and cfg.port:
                # Sibling derivation: the partition's other replicas are
                # exactly the peers anti-entropy (and bootstrap donors)
                # should talk to — cross-partition walks would compare
                # DISJOINT keyspaces and mirror everything as divergence.
                # An explicit [anti_entropy] peers list still wins; nodes
                # on an ephemeral port (tests) cannot self-identify and
                # keep their explicit list.
                def is_self(a: str) -> bool:
                    # Exact-match plus the wildcard-bind case: a node
                    # bound 0.0.0.0/:: cannot know which map spelling is
                    # its own, so same-port entries are treated as self —
                    # a node must never dial itself as a peer. Exotic
                    # host spellings (localhost vs 127.0.0.1) should set
                    # [anti_entropy] peers explicitly.
                    host, _, port = a.rpartition(":")
                    if port != str(cfg.port):
                        return False
                    return host == cfg.host or cfg.host in (
                        "0.0.0.0", "::", ""
                    )

                cfg.anti_entropy.peers = [
                    a
                    for a in self._partmap.replicas[self._partition_id]
                    if not is_self(a)
                ]
        self.sync_manager = SyncManager(
            engine,
            device=cfg.anti_entropy.engine,
            repair_listener=self._on_sync_repair,
            on_peer_degraded=self._on_peer_degraded,
            mode=cfg.anti_entropy.mode,
            bisect_threshold=cfg.anti_entropy.bisect_threshold,
            on_cycle_converged=self.lag_tracker.on_converged,
            max_skew_ms=cfg.replication.max_skew_ms,
            tree_lag_limit=cfg.device.max_staleness_versions,
            partition_id=self._partition_id,
        )

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._server.set_cluster_handler(self._on_cluster_command)
        # Partition guard BEFORE anything serves: from the first accepted
        # command, a foreign key answers ERROR MOVED instead of landing in
        # (and polluting) this partition's keyspace.
        if self._partmap is not None:
            self._install_partition_guard()
            # Boot foreign-key sweep: a donor (or sibling) killed after
            # the epoch persisted but before its moved-range drop ran
            # restarts owning the NARROWED cell while the engine still
            # holds the moved keys. Quiet-drop them behind the guard —
            # the joiner owns them now, and serving them here would be
            # double-ownership.
            self._boot_foreign_sweep()
        # Overload protection BEFORE anything serves: admission limits go
        # to the native accept path, and the watermark monitor starts
        # pushing the degradation ladder (its first poll runs inline, so
        # a node restarted over a full disk comes up read-only, not live).
        self._server.set_limits(
            self._cfg.server.max_connections, self._cfg.server.max_pipeline
        )
        self._overload = OverloadMonitor(
            self.ladder,
            self._engine,
            self._server,
            self._cfg.server,
            storage=self._storage,
            partition_id=self._partition_id,
        ).start()
        if self._storage is not None:
            self._storage.set_defer_compaction(self._overload.memory_pressure)
        self._register_gauges()
        from merklekv_tpu.obs.trace import get_trace_buffer

        get_trace_buffer().set_capacity(self._cfg.observability.trace_cycles)
        tracewire.set_propagation(self._cfg.observability.trace_propagation)
        tracewire.get_collector().set_capacity(
            self._cfg.observability.trace_spans
        )
        self._start_flight_recorder()
        if self._cfg.observability.http_port != 0:
            # Per-node Prometheus endpoint (/metrics + /healthz): registry
            # counters/histograms/gauges and the native STATS block in one
            # namespace. -1 binds an ephemeral port (tests read
            # metrics_port); failure to bind is reported, never fatal —
            # the data plane must not die for observability.
            from merklekv_tpu.obs.exporter import MetricsExporter

            port = self._cfg.observability.http_port
            try:
                self._exporter = MetricsExporter(
                    max(0, port),
                    host=self._cfg.observability.http_host,
                    stats_fn=self._server.stats_text,
                    health_fn=self._health_payload,
                ).start()
            except OSError as e:
                print(f"metrics exporter not started: {e}", file=sys.stderr,
                      flush=True)
        if self._storage is not None:
            # WAL recording: the store drains the native change-event queue
            # itself until a Replicator takes over the drain (then the
            # store rides its batch listener — one queue, one consumer).
            self._storage.attach_server(self._server)
        if self._cfg.replication.enabled:
            err = self._enable_replication()
            if err is not None:
                print(f"replication not started: {err}", file=sys.stderr,
                      flush=True)
        if self._cfg.anti_entropy.enabled and self._cfg.anti_entropy.peers:
            # Failure detection: probe peers off the sync path so the loop
            # can skip confirmed-down peers instead of burning a connect
            # timeout per cycle (reference has no peer health, SURVEY §5.3).
            from merklekv_tpu.cluster.health import PeerHealthMonitor

            self._health = PeerHealthMonitor(
                self._cfg.anti_entropy.peers,
                interval_seconds=min(
                    self._cfg.anti_entropy.interval_seconds, 2.0
                ),
            )
            self._health.start()
        # Bootstrap BEFORE the periodic sync loop: a node joining empty
        # (or recovering through interior WAL corruption) ships a peer's
        # verified snapshot instead of walking the whole keyspace, serving
        # zero reads until the stamped root verifies. The loop is deferred
        # until the session FINISHES — a transfer outliving the sync
        # interval must not race full walk-from-empty cycles (the exact
        # O(n) wire cost this subsystem exists to avoid); the bootstrap
        # thread starts the loop on its way out.
        bootstrapping = False
        if self._cfg.bootstrap.enabled and self._cfg.anti_entropy.peers:
            reason = self._bootstrap_reason()
            if reason is not None:
                bootstrapping = True
                self._start_bootstrap(reason)
        if not bootstrapping:
            self._start_sync_loop()

    def _flight_dir(self) -> Optional[str]:
        """Where the durable spill lives: explicit [observability]
        flight_dir wins; "" resolves to <node data dir>/flight on durable
        nodes and to None (spill off; ring + FLIGHT verb still live) on
        storage-less ones — an embedded test node must not litter."""
        d = self._cfg.observability.flight_dir
        if d:
            return d
        if self._storage is not None:
            return os.path.join(self._storage.directory, "flight")
        return None

    def _start_flight_recorder(self) -> None:
        """Arm the black box: size the ring, push the native slow-command
        threshold, start the metric sampler, and — when a spill directory
        resolves — the periodic spill writer plus the fatal-dump handlers
        (faulthandler first, then the native crash marker so the marker
        chains INTO faulthandler's traceback dump)."""
        obs = self._cfg.observability
        if not obs.flight_enabled:
            # Disarm explicitly: an embedded server reused from a previous
            # node (or configured by one) may still hold its threshold.
            self._server.set_slow_threshold(0)
            return
        from merklekv_tpu.obs import flightrec

        rec = flightrec.get_recorder()
        rec.set_capacity(obs.flight_events)
        self._server.set_slow_threshold(obs.slow_command_us)
        if self._partition_id is not None:
            # The partition id on node_start is what lets blackbox group
            # several nodes' spills by partition and tell a partition-
            # local incident (one group flips) from a cluster-wide one.
            rec.record(
                "node_start",
                port=self._server.port,
                partition=self._partition_id,
            )
            rec.record(
                "map_change",
                epoch=self._partmap.epoch,
                partitions=self._partmap.count,
                partition=self._partition_id,
            )
        else:
            rec.record("node_start", port=self._server.port)
        self._flight_sampler = flightrec.MetricSampler(
            interval_s=obs.flight_sample_s,
            stats_fn=self._server.stats_text,
        ).start()
        flight_dir = self._flight_dir()
        if flight_dir is not None:
            self._flight_spiller = flightrec.FlightSpiller(
                flight_dir,
                sampler=self._flight_sampler,
                interval_s=obs.flight_spill_s,
                node=f"{self._cfg.host}:{self._server.port}",
            )
            try:
                self._flight_spiller.start()
            except OSError as e:
                # An unwritable flight dir must not kill the data plane;
                # the in-memory ring and FLIGHT verb still serve.
                self._flight_spiller = None
                print(f"flight spill not started: {e}", file=sys.stderr,
                      flush=True)
            if self._flight_spiller is not None:
                flightrec.install_fault_handlers(flight_dir)
                from merklekv_tpu.native_bindings import install_crash_marker

                install_crash_marker(
                    os.path.join(flight_dir, "fatal.txt")
                )

    def _start_sync_loop(self) -> None:
        if (
            self._cfg.anti_entropy.enabled
            and self._cfg.anti_entropy.peers
            and not self._stopped
        ):
            self.sync_manager.start_loop(
                self._cfg.anti_entropy.peers,
                self._cfg.anti_entropy.interval_seconds,
                multi_peer=self._cfg.anti_entropy.multi_peer,
                peer_up=self._health.is_up if self._health else None,
                pause_when=(
                    self._overload.should_pause_background
                    if self._overload is not None
                    else None
                ),
            )

    def stop(self) -> None:
        self._stopped = True
        # Draining is the ladder's top rung: new connections are refused
        # BUSY and writes answer READONLY while the teardown (final WAL
        # drain, shutdown snapshot) runs. The monitor stops first so it
        # cannot race the rung back down.
        if self._overload is not None:
            self._overload.stop()
        self._server.set_degradation(DRAINING, REASON_CODES["draining"])
        if self._exporter is not None:
            self._exporter.close()
            self._exporter = None
        self._unregister_gauges()
        if self._bootstrap is not None:
            self._bootstrap.stop()
        if self._bootstrap_thread is not None:
            self._bootstrap_thread.join(timeout=10)
            self._bootstrap_thread = None
        self.sync_manager.stop()
        if self._health is not None:
            self._health.stop()
            self._health = None
        with self._rebalance_mu:
            rebalance = self._rebalance
        if rebalance is not None:
            rebalance.stop()
        self._disable_replication()
        if self._owns_transport and self._transport is not None:
            self._transport.close()
            self._transport = None
        self._server.set_cluster_handler(None)
        # Back to live: embedded/test shapes reuse the native server after
        # a node stops (the process-level path closes it right after, so
        # the draining window there lasts until server.close()).
        self._server.set_degradation(0, 0)
        if self._partmap is not None:
            # Same successor-node rule as the slow threshold below: an
            # embedded server reused by an unpartitioned node must not
            # keep refusing foreign keys with a dead node's map.
            self._server.set_partition(0, 0, 0)
        # Disarm the slow-command log with the rest of the per-node server
        # state: a successor node attached to the same embedded server
        # must not inherit this node's threshold.
        self._server.set_slow_threshold(0)
        # Flight recorder LAST: node_stop is the clean-shutdown marker —
        # FAULT_MODEL.md's contract is that its PRESENCE in the spill's
        # tail proves the stop completed, so it must be recorded (and the
        # final spill written) after the whole teardown above, not before
        # it. A death mid-teardown then still reads as unclean.
        if self._flight_sampler is not None or self._flight_spiller is not None:
            from merklekv_tpu.obs import flightrec

            flightrec.record("node_stop")
        if self._flight_sampler is not None:
            self._flight_sampler.stop()
        if self._flight_spiller is not None:
            self._flight_spiller.stop(final=True)
            self._flight_spiller = None
        self._flight_sampler = None

    @property
    def replicator(self) -> Optional[Replicator]:
        return self._replicator

    # -- live rebalancing -----------------------------------------------------
    def _rebalance_manager(self):
        with self._rebalance_mu:
            if self._rebalance is None:
                from merklekv_tpu.cluster.rebalance import RebalanceManager

                self._rebalance = RebalanceManager(self)
            return self._rebalance

    def _rebalance_state_code(self) -> int:
        with self._rebalance_mu:
            rebalance = self._rebalance
        return rebalance.state_code() if rebalance is not None else 0

    def _install_partition_guard(self) -> None:
        """Push the current map into the native guard. Unsplit maps take
        the legacy modulo path (byte-identical to pre-rebalance behavior);
        split maps install the full cell table so foreign keys answer
        ``ERROR MOVED <owner> <epoch>`` with split-tree routing."""
        pmap, pid = self._partmap, self._partition_id
        if pmap is None or pid is None:
            return
        if pmap.is_split:
            self._server.set_partition_map(
                pmap.epoch,
                pmap.hash_base,
                pid,
                [pmap.assignment(p) for p in range(pmap.count)],
            )
        else:
            self._server.set_partition(pmap.epoch, pmap.count, pid)

    def adopt_partition_map(self, pmap, pid: Optional[int] = None) -> None:
        """Commit a new partition-map epoch on this node: persist it
        (THE durability point — a kill one instruction later restarts at
        the new epoch), then swap the in-memory map and the native guard.
        ``pid`` defaults to the current identity (donor/sibling); the
        joiner passes its newly-owned partition."""
        from merklekv_tpu.cluster.partmap import save_map_file

        if pid is None:
            pid = self._partition_id
        if self._storage is not None:
            save_map_file(self._storage.directory, pmap, pid)
        self._partmap = pmap
        self._partition_id = pid
        self._install_partition_guard()
        from merklekv_tpu.obs.flightrec import record

        record(
            "map_change",
            epoch=pmap.epoch,
            partitions=pmap.count,
            partition=pid,
        )

    def _boot_foreign_sweep(self) -> None:
        """Quiet-drop every key outside this node's owned cell. Only a
        split map can leave residue (a crash between the epoch persist and
        the moved-range drop); boot-shaped maps skip the scan entirely."""
        pmap, pid = self._partmap, self._partition_id
        if pmap is None or pid is None or not pmap.is_split:
            return
        from merklekv_tpu.cluster.partmap import key_in_range

        base = pmap.hash_base
        root, depth, path = pmap.assignment(pid)
        dropped = 0
        for k, _ in self._engine.snapshot():
            if not key_in_range(k, base, root, depth, path):
                if self._engine.delete_quiet(k):
                    dropped += 1
        if dropped:
            if self._storage is not None:
                self._storage.request_snapshot()
            from merklekv_tpu.obs.flightrec import record
            from merklekv_tpu.utils.tracing import get_metrics

            get_metrics().inc("rebalance.boot_swept_keys", dropped)
            record("rebalance_boot_sweep", keys=dropped, partition=pid)

    # -- replication management ---------------------------------------------
    def _get_transport(self) -> Transport:
        if self._transport is None:
            rep = self._cfg.replication
            self._transport = make_transport(
                rep.mqtt_broker,
                rep.mqtt_port,
                kind=rep.transport,
                client_id=rep.client_id,
                username=rep.username,
                password=rep.password,
            )
        return self._transport

    def _enable_replication(self) -> Optional[str]:
        with self._rep_mu:
            if self._replicator is not None:
                return None  # already enabled
            try:
                transport = self._get_transport()
            except OSError as e:
                return f"broker unreachable: {e}"
            # The mirror is only trustworthy while the event queue feeds it,
            # i.e. while replication is enabled — so its lifecycle is tied
            # to the replicator's. "cpu" pins anti-entropy (and HASH) to the
            # host path; anything else serves HASH from the device tree.
            if self._cfg.anti_entropy.engine != "cpu":
                from merklekv_tpu.cluster.mirror import DeviceTreeMirror

                self._mirror = DeviceTreeMirror(
                    self._engine,
                    sharded=self._cfg.device.sharded_mirror,
                    sharding=self._cfg.device.sharding,
                    max_staleness_ms=self._cfg.device.max_staleness_ms,
                    max_staleness_versions=(
                        self._cfg.device.max_staleness_versions
                    ),
                    dispatch_deadline_ms=(
                        self._cfg.device.dispatch_deadline_ms
                    ),
                    scrub_interval_s=self._cfg.device.scrub_interval_s,
                    scrub_keys=self._cfg.device.scrub_keys,
                    degrade_after=self._cfg.device.degrade_after_failures,
                )
            storage = self._storage
            if storage is not None:
                # Hand the event-queue drain to the replicator; local
                # writes reach the WAL through its batch listener, remote
                # applies through the storage hook inside the replicator.
                storage.pause_drain()
            # Partition-local replication fabric: each partition's replica
            # group publishes/subscribes on its OWN topic, so one
            # partition's write storm (or poisoned stream) can never fan
            # out into a sibling partition's appliers — the frame-level
            # blast radius is one partition. The node id carries a p<pid>
            # prefix so per-peer attribution (replication.lag_events.<src>,
            # skew clamps, blackbox joins) names the partition too.
            topic_prefix = self._cfg.replication.topic_prefix
            node_id = self._cfg.replication.client_id
            if self._partition_id is not None:
                topic_prefix = f"{topic_prefix}/p{self._partition_id}"
                node_id = node_id or (
                    f"p{self._partition_id}-"
                    f"{self._cfg.host}:{self._server.port}"
                )
            try:
                self._replicator = Replicator(
                    self._engine,
                    self._server,
                    transport,
                    topic_prefix=topic_prefix,
                    node_id=node_id,
                    mirror=self._mirror,
                    batch_listener=(
                        storage.record_events if storage is not None else None
                    ),
                    storage=storage,
                    batch_max_events=self._cfg.replication.batch_max_events,
                    batch_max_bytes=self._cfg.replication.batch_max_bytes,
                    lag_tracker=self.lag_tracker,
                    max_skew_ms=self._cfg.replication.max_skew_ms,
                )
                self._replicator.start()
            except Exception as e:
                # Take the drain back: a half-failed enable must not leave
                # WAL recording paused with no batch listener feeding it.
                self._replicator = None
                if storage is not None:
                    self._server.enable_events(True)
                    storage.resume_drain()
                return f"replicator start failed: {e}"
            return None

    def _disable_replication(self) -> None:
        with self._rep_mu:
            if self._replicator is not None:
                self._replicator.stop()
                self._replicator = None
                if self._storage is not None:
                    # Replicator.stop() turned event staging off; the WAL
                    # still needs it — take the drain back.
                    self._server.enable_events(True)
                    self._storage.resume_drain()
            if self._mirror is not None:
                # Before any teardown of the native engine: the mirror's
                # warm thread reads through the engine's raw pointer.
                self._mirror.close()
                self._mirror = None

    # -- bootstrap (joiner side) ----------------------------------------------
    @property
    def bootstrap(self):
        """The BootstrapSession of the current/most recent (re)join, or
        None when this node never bootstrapped (tests, top, healthz)."""
        return self._bootstrap

    def _bootstrap_reason(self) -> Optional[str]:
        """Why this node should bootstrap, or None to start normally.

        An empty keyspace is the classic new/long-dead joiner. A recovery
        that hit interior WAL corruption (or rejected every snapshot)
        restored only a verified PREFIX — the re-anchor snapshot closes
        the durability hole, and bootstrapping from a healthy peer closes
        the data hole without waiting out a worst-case walk."""
        try:
            if self._engine.dbsize() == 0:
                return "empty-keyspace"
        except Exception:
            return None
        st = self._storage
        if st is not None and st.last_recovery is not None:
            rec = st.last_recovery
            if rec.corruption:
                return "wal-corruption"
            if rec.snapshots_rejected and rec.snapshot_path is None:
                return "snapshots-rejected"
        return None

    def _start_bootstrap(self, reason: str) -> None:
        from merklekv_tpu.cluster.bootstrap import BootstrapSession

        # Close the read gate first: no client read — and no peer's
        # anti-entropy walk — sees unverified state from here on.
        self._server.set_serving(False)
        with self._rep_mu:
            rep = self._replicator
        if rep is not None:
            # Live replication frames journal but defer apply until the
            # verified snapshot is installed (no gap in the write stream).
            rep.hold_applies()

        def on_serving() -> None:
            self._server.set_serving(True)
            with self._rep_mu:
                r = self._replicator
            if r is not None:
                r.release_applies()

        self._bootstrap = BootstrapSession(
            self._engine,
            self.sync_manager,
            self._cfg.anti_entropy.peers,
            self._cfg.bootstrap,
            merkle_engine=self._cfg.storage.merkle_engine,
            health=self._health,
            batch_listener=self._on_bootstrap_applied,
            on_serving=on_serving,
        )
        sess = self._bootstrap

        def run() -> None:
            try:
                sess.run(reason)
            finally:
                # The periodic loop was deferred for the transfer's
                # duration; hand over to it now (no-op if disabled or the
                # node stopped meanwhile).
                self._start_sync_loop()

        self._bootstrap_thread = threading.Thread(
            target=run, daemon=True, name="mkv-bootstrap"
        )
        self._bootstrap_thread.start()

    def _on_bootstrap_applied(self, applied) -> None:
        """Verified snapshot slab installed into the engine: feed the
        device mirror and the WAL, exactly like anti-entropy repairs —
        bootstrap applies bypass the server's event queue."""
        with self._rep_mu:
            mirror = self._mirror
        if mirror is not None:
            mirror.apply_batch([(k, v) for k, v, _ in applied])
        if self._storage is not None:
            self._storage.record_applied(applied)

    def _snap_meta_wire(self) -> str:
        storage = self._storage
        if storage is None:
            return "ERROR snapshot shipping requires durable storage\r\n"
        meta = storage.donor_meta()
        if meta == storage.BUILDING:
            # Transient, not a capability miss: the artifact is being
            # written in the background — the joiner polls ("retry" is the
            # signal its discover phase waits on).
            return "ERROR snapshot not ready (building); retry\r\n"
        if meta is None:
            return "ERROR no snapshot available\r\n"
        seq, wal_seq, size, root_hex = meta
        return f"SNAPMETA {seq} {wal_seq} {size} {root_hex}\r\n"

    def _snap_chunk_wire(self, seq: int, offset: int, count: int) -> str:
        import base64
        import zlib

        storage = self._storage
        if storage is None:
            return "ERROR snapshot shipping requires durable storage\r\n"
        try:
            raw = storage.read_snapshot_range(seq, offset, count)
        except OSError:
            # Artifact gone (donor restarted past the pin TTL): the joiner
            # re-discovers rather than assembling a short file.
            return f"ERROR snapshot {seq} gone\r\n"
        if not raw:
            # Past EOF: a bare zero-length frame (the client rejects a
            # zero-length header that still carries payload bytes).
            return f"CHUNK {offset} 0 0\r\n\r\n"
        # CRC over the RAW bytes; payload zlib+base64 so the CRLF text
        # protocol carries arbitrary binary, and key/value-shaped snapshot
        # bodies compress well (measured: ~5-10x on text keyspaces).
        payload = base64.b64encode(zlib.compress(raw, 1)).decode("ascii")
        from merklekv_tpu.utils.tracing import get_metrics

        m = get_metrics()
        m.inc("bootstrap.donor_chunks")
        m.inc("bootstrap.donor_bytes", len(raw))
        return f"CHUNK {offset} {len(raw)} {zlib.crc32(raw)}\r\n{payload}\r\n"

    # -- causal tracing / profiler --------------------------------------------
    def _record_trace_span(self, args: list[str]) -> str:
        """Record one donor-side serve span from a TRACESPAN notification.
        Malformed notifications are dropped (never an error back into the
        native dispatch path)."""
        try:
            verb, token, start_ns, dur_ns = (
                args[0], args[1], int(args[2]), int(args[3])
            )
        except (IndexError, ValueError):
            return "OK\r\n"
        ctx = tracewire.parse_token(token)
        if ctx is None:
            return "OK\r\n"
        tracewire.get_collector().record(
            trace_id=ctx.trace_id,
            span_id=tracewire._new_id(),
            parent_id=ctx.span_id,
            name=f"serve.{verb.lower()}",
            role="donor",
            ts_ns=start_ns,
            dur_ns=dur_ns,
            node=f"{self._cfg.host}:{self._server.port}",
        )
        return "OK\r\n"

    def _profile_wire(self, secs: int) -> str:
        """Start a bounded ``jax.profiler`` capture ("PROFILE <secs>"): the
        device data plane's rebuild/diff/scatter programs land in the
        capture (inspect with TensorBoard/xprof/Perfetto). The response
        carries the capture directory immediately; a background thread
        stops the capture after ``secs``. One capture at a time."""
        secs = max(1, min(secs, 600))
        with self._profile_mu:
            if self._profiling:
                return "ERROR profile capture already running\r\n"
            logdir = self._cfg.observability.profile_dir
            if not logdir:
                # Config contract: "" = <storage_path>/profiles on a
                # durable node (captures survive with the data), system
                # temp on a storage-less one.
                if self._cfg.storage.enabled:
                    logdir = os.path.join(
                        self._cfg.storage_path, "profiles"
                    )
                else:
                    import tempfile

                    logdir = os.path.join(
                        tempfile.gettempdir(), "mkv-profiles"
                    )
            logdir = os.path.join(logdir, time.strftime("%Y%m%d-%H%M%S"))
            try:
                os.makedirs(logdir, exist_ok=True)
                import jax

                jax.profiler.start_trace(logdir)
            except Exception as e:
                return f"ERROR profiler unavailable: {e}\r\n"
            self._profiling = True

        def stop_later() -> None:
            time.sleep(secs)
            with self._profile_mu:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._profiling = False

        threading.Thread(
            target=stop_later, daemon=True, name="mkv-profile-stop"
        ).start()
        from merklekv_tpu.utils.tracing import get_metrics

        get_metrics().inc("profiler.captures")
        return f"PROFILE {logdir}\r\n"

    def _on_peer_degraded(self, peer: str, reason: str) -> None:
        """A sync stream against ``peer`` died mid-cycle (its remaining
        repairs are checkpointed for resume); reflect it in the health
        table so PEERS shows the degradation while probes keep running."""
        h = self._health
        if h is not None:
            h.mark_degraded(peer, reason)

    def _on_sync_repair(self, key: bytes, value, ts=None) -> None:
        """Anti-entropy repairs bypass the server event queue; feed the
        device mirror directly so HASH stays truthful after a SYNC, and the
        WAL so a repaired key survives a crash without needing re-repair."""
        with self._rep_mu:
            mirror = self._mirror
        if mirror is not None:
            mirror.apply_one(key, value)
        storage = self._storage
        if storage is not None:
            # Journal at the EXACT ts the repair installed (threaded through
            # the listener — an engine read-back here could race a newer
            # concurrent writer and journal the repair value under the
            # winner's timestamp). ts None means the repair carried no
            # ordering metadata (delete_quiet absence copy, legacy full
            # transfer): skip the journal rather than fabricate a ts —
            # anti-entropy re-repairs after a crash.
            if ts is None:
                return
            if value is None:
                storage.record_delete(key, ts)
            else:
                storage.record_set(key, value, ts)

    def _query_ready_mirror(self, fn, force: bool = False):
        """Shared gate for device-tree reads (HASH root, TREELEVEL slices):
        returns ``fn(mirror)``, or None whenever the device path can't
        answer — replication off, device disabled, mirror still warming (a
        warm-up is kicked off), or any device failure — so the native
        fallback serves instead and nothing stalls on the device.

        The freshness contract: the DEFAULT path serves the pump's
        last-published snapshot and performs NO synchronous replicator
        flush — a root-serving query never serializes behind the write
        stream; the tree trails live by at most the [device] max_staleness
        window. ``force=True`` is the explicit exactness escape hatch
        (snapshot stamping, the wire's vs=03 forced refresh): drain staged
        events through the replicator, pump them to the device, THEN
        serve."""
        with self._rep_mu:
            rep, mirror = self._replicator, self._mirror
        if rep is None or mirror is None:
            return None
        if not mirror.ready():
            mirror.start_warming()  # no-op if already in flight
            return None
        try:
            if force:
                rep.flush()  # native queue -> mirror staging
                mirror.publish_now()  # staging -> served snapshot
            return fn(mirror)
        except Exception:
            return None  # native fallback answers instead

    def device_tree_level(
        self, level: int, lo: int, hi: int, force: bool = False
    ):
        """TREELEVEL answer from the last-published device tree:
        ``(rows, n)`` with reference-level ``(idx, digest)`` rows, or None
        when the mirror isn't ready (the native server's host-side cached
        tree answers meanwhile, so peers' walks never stall on a warming
        mirror)."""
        return self._query_ready_mirror(
            lambda m: m.level_nodes(level, lo, hi), force=force
        )

    def device_root_hex(self, force: bool = False) -> Optional[str]:
        """Whole-keyspace Merkle root from the last-published device tree,
        or None when the mirror isn't ready (replication off / device
        disabled / still warming — the native path answers meanwhile).
        ``force=True`` drains the write stream to the device first and
        serves an exact root (read-your-writes for snapshot verification
        and tests; the default bounded-staleness path never waits)."""
        return self._query_ready_mirror(
            lambda m: m.published_root_hex(), force=force
        )

    @property
    def health(self):
        return self._health

    @property
    def metrics_port(self) -> Optional[int]:
        """Bound port of the /metrics exporter, or None when disabled."""
        return self._exporter.port if self._exporter is not None else None

    def _health_payload(self) -> dict:
        """/healthz extra fields: engine reachability, peer summary, and
        the convergence-lag readiness level (live|lagging|diverged)."""
        if not self._engine._h:
            return {"keys": -1, "readiness": "diverged"}
        payload = {"keys": self._engine.dbsize(), "port": self._server.port}
        payload["readiness"] = self.lag_tracker.readiness()
        # Overload plane: the degradation rung, and a degraded status the
        # moment the node sheds anything — a load balancer must see a
        # shedding/read-only node as unhealthy-for-writes immediately.
        level = self.ladder.level()
        payload["degradation"] = LEVEL_NAMES.get(level, "live")
        if level >= SHEDDING:
            payload["status"] = "degraded"
        if self._partition_id is not None:
            # Per-partition readiness: this node IS one replica of one
            # partition, so its rung is that partition's health here —
            # an LB/router reading every replica's /healthz gets the
            # per-partition availability matrix.
            payload["partition"] = self._partition_id
            payload["partition_epoch"] = self._partmap.epoch
            payload["partition_state"] = LEVEL_NAMES.get(level, "live")
        with self._rebalance_mu:
            rebalance = self._rebalance
        if rebalance is not None and rebalance.state != "idle":
            # Surfaced only while a session is (or recently was) live —
            # the steady-state payload stays byte-compatible.
            payload["rebalance"] = rebalance.state
        lag = self.lag_tracker.lag_events()
        if lag:
            payload["lag_events"] = sum(lag.values())
        h = self._health
        if h is not None:
            rows = h.snapshot()
            payload["peers_up"] = sum(1 for r in rows if r.status == "up")
            payload["peers_total"] = len(rows)
        return payload

    # -- gauges ---------------------------------------------------------------
    def _register_gauges(self) -> None:
        """Callback gauges over this node's live state. Registration
        replaces same-named gauges (last node wins in multi-node-per-
        process tests); each is read at scrape time, and a callback that
        throws drops only its own sample."""
        from merklekv_tpu.utils.tracing import get_metrics

        m = get_metrics()
        engine = self._engine

        def live_keys() -> int:
            # Guard the raw handle: a gauge outliving the engine (node not
            # stopped before engine.close()) must drop its sample, not
            # drive the FFI through a dead pointer.
            return engine.dbsize() if engine._h else -1

        def tombstones() -> int:
            return len(engine.tombstones()) if engine._h else -1

        def mirror_leaves() -> int:
            with self._rep_mu:
                mirror = self._mirror
            return mirror.leaf_count() if mirror is not None else -1

        def mirror_staleness() -> int:
            with self._rep_mu:
                mirror = self._mirror
            return mirror.staleness() if mirror is not None else -1

        def pump_lag_ms() -> int:
            with self._rep_mu:
                mirror = self._mirror
            return (
                int(round(mirror.pump_lag_ms())) if mirror is not None else -1
            )

        def mirror_shards() -> int:
            with self._rep_mu:
                mirror = self._mirror
            return mirror.shard_count() if mirror is not None else -1

        def backend_level() -> int:
            with self._rep_mu:
                mirror = self._mirror
            return mirror.backend_level() if mirror is not None else -1

        def shard_rebuild_us() -> int:
            with self._rep_mu:
                mirror = self._mirror
            return mirror.shard_rebuild_us() if mirror is not None else -1

        def outbox_depth() -> int:
            t = self._transport
            return getattr(t, "outbox_depth", 0) if t is not None else 0

        def peer_states() -> dict:
            h = self._health
            if h is None:
                return {}
            code = {"up": 2, "degraded": 1, "down": 0, "unknown": -1}
            return {
                r.peer: code.get(r.status, -1) for r in h.snapshot()
            }

        def bootstrap_state() -> int:
            b = self._bootstrap
            return b.state_code() if b is not None else 0

        tracker = self.lag_tracker

        gauges = [
            ("keyspace.keys", live_keys,
             "Live keys in the native engine.", ""),
            ("keyspace.tombstones", tombstones,
             "Deletion records retained for cluster LWW.", ""),
            ("device.tree_leaves", mirror_leaves,
             "Leaf count of the device-resident Merkle tree "
             "(-1: no mirror).", ""),
            ("device.mirror_staleness", mirror_staleness,
             "Engine mutation versions the PUBLISHED device tree trails "
             "the live keyspace by — exact against mkv_engine_version via "
             "the pump's applied-version watermark (-1: no mirror).", ""),
            ("device.pump_lag_versions", mirror_staleness,
             "Pump-plane alias of device.mirror_staleness: versions the "
             "device-update pump has staged but not yet published (-1: no "
             "mirror).", ""),
            ("device.pump_lag_ms", pump_lag_ms,
             "Milliseconds the oldest staged-but-unpublished device-tree "
             "change has waited on the pump (0: caught up; -1: no "
             "mirror).", ""),
            ("device.shards", mirror_shards,
             "Device shards serving the Merkle tree's leaf level "
             "([device] sharding; 1: single-device tree; -1: no mirror or "
             "warming).", ""),
            ("device.shard_rebuild_us", shard_rebuild_us,
             "Dispatch cost of the last sharded subtree rebuild in "
             "microseconds (async enqueue; -1: single-device backend or "
             "no rebuild yet).", ""),
            ("device.backend_level", backend_level,
             "Degradation-ladder rung serving the Merkle tree (N>=2: "
             "sharded width; 1: single-device; 0: CPU golden tree; -1: "
             "native fallback / warming / no mirror).", ""),
            ("replication.outbox_depth", outbox_depth,
             "Events queued in the transport outbox awaiting a broker "
             "heal.", ""),
            ("peer.state", peer_states,
             "Peer health (2=up 1=degraded 0=down -1=unknown).", "peer"),
            ("bootstrap.state", bootstrap_state,
             "Bootstrap state machine (0=idle 1=discover 2=fetch 3=verify "
             "4=delta 5=live -1=failed).", ""),
            ("replication.lag_events", tracker.lag_events,
             "Events a peer has published (envelope HWM) that this node "
             "has not yet applied; anti-entropy convergence clears drop "
             "residue.", "src"),
            ("replication.lag_ms", tracker.lag_ms,
             "Publish-to-apply wall delay of the newest applied frame per "
             "peer (ms; cross-host clock skew applies).", "src"),
            ("node.readiness", tracker.readiness_code,
             "Convergence readiness (2=live 1=lagging 0=diverged).", ""),
            ("node.degradation", self.ladder.level,
             "Overload degradation ladder (0=live 1=shedding 2=read_only "
             "3=draining).", ""),
            ("rebalance.state", self._rebalance_state_code,
             "Live-rebalance session phase (0=idle, donor 1-7 "
             "conscribe..done, joiner 10-13, negative=failed/aborted).",
             ""),
        ]
        if self._partition_id is not None:
            pid = str(self._partition_id)
            ladder = self.ladder

            def partition_state() -> dict:
                # Labeled by partition so a fleet-wide scrape aggregates
                # into the per-partition availability matrix directly
                # (max by partition = worst replica's rung).
                return {pid: ladder.level()}

            gauges.append(
                ("partition.state", partition_state,
                 "Degradation rung of this replica's partition (0=live "
                 "1=shedding 2=read_only 3=draining), labeled with the "
                 "partition id it serves.", "partition")
            )
        if self._storage is not None:
            storage = self._storage
            gauges += [
                ("storage.wal_bytes", storage.wal_size_bytes,
                 "Total bytes across live WAL segments.", ""),
                ("storage.wal_segments", storage.wal_segment_count,
                 "Live WAL segment files.", ""),
            ]
        for name, fn, help_, label in gauges:
            m.register_gauge(name, fn, help=help_, label=label)
        self._gauge_names = [(g[0], g[1]) for g in gauges]

    def _unregister_gauges(self) -> None:
        from merklekv_tpu.utils.tracing import get_metrics

        m = get_metrics()
        for name, fn in self._gauge_names:
            # Identity-checked: if a later node replaced this name (the
            # documented last-wins rule), its registration survives our
            # stop instead of being stripped with ours.
            m.unregister_gauge(name, fn)
        self._gauge_names = []

    def _metrics_wire(self) -> str:
        """METRICS wire payload: the control plane's counter snapshot —
        transport reconnects/outbox drops, anti-entropy loop counters, span
        counts — as ``name:value`` lines. The complement of STATS, which
        covers the native engine/server scope only."""
        from merklekv_tpu.utils.tracing import get_metrics

        metrics = get_metrics()
        lines = []
        snap = metrics.snapshot()
        for name in sorted(snap["counters"]):
            lines.append(f"{name}:{snap['counters'][name]}")
        # Span aggregates (integers only — the parsers treat values as
        # numeric text): count, total, and bucket-derived percentiles per
        # span name. total_us is the canonical total; the deprecated
        # total_ms field (sub-millisecond spans truncated to 0) finished
        # its one-release window and is gone — docs/PROTOCOL.md "METRICS".
        for name in sorted(snap["spans"]):
            sp = snap["spans"][name]
            lines.append(f"span.{name}.count:{sp['count']}")
            lines.append(f"span.{name}.total_us:{int(sp['total_s'] * 1e6)}")
            hist = snap["histograms"].get(f"span.{name}")
            if hist and hist["count"]:
                h = metrics.histogram(f"span.{name}")
                for q, tag in ((0.5, "p50_us"), (0.99, "p99_us")):
                    v = h.quantile(q)
                    if v is not None:
                        lines.append(f"span.{name}.{tag}:{int(v * 1e6)}")
        t = self._transport
        if t is not None:
            for attr in ("reconnects", "outbox_dropped", "callback_errors"):
                v = getattr(t, attr, None)
                if v is not None:
                    lines.append(f"transport.{attr}_live:{v}")
        # Convergence-lag plane: per-peer lag gauges + the readiness level,
        # so wire-only consumers (top) see them without scraping /metrics.
        # The METRICS contract is integer-text values across the board
        # (parsers depend on it), so lag_ms rounds and readiness rides as
        # its numeric code (2=live 1=lagging 0=diverged).
        for src, v in sorted(self.lag_tracker.lag_events().items()):
            lines.append(f"replication.lag_events.{src}:{v}")
        for src, v in sorted(self.lag_tracker.lag_ms().items()):
            lines.append(f"replication.lag_ms.{src}:{int(round(v))}")
        lines.append(f"readiness_code:{self.lag_tracker.readiness_code()}")
        # Device freshness plane: pump lag (versions + ms) and the
        # engine-vs-served tree versions, so wire-only consumers (top's
        # STALE and VER columns) see the staleness contract without
        # scraping /metrics. Integer-text contract like every METRICS line.
        with self._rep_mu:
            mirror = self._mirror
        if mirror is not None:
            # Deliberately OUTSIDE the ready() gate below: the backend
            # level is most interesting exactly when the mirror is NOT
            # ready (-1 = serving off the native fallback — top's BKND
            # column must show the degradation, not hide it).
            lines.append(f"device.backend_level:{mirror.backend_level()}")
        if mirror is not None and mirror.ready():
            # Gated on ready(): a warming mirror has no published tree, and
            # tree_version 0 would read as "202 versions stale" in top's
            # VER column instead of "no device serving yet" ("-").
            try:
                lines.append(
                    f"device.pump_lag_versions:{mirror.staleness()}"
                )
                lines.append(
                    "device.pump_lag_ms:"
                    f"{int(round(mirror.pump_lag_ms()))}"
                )
                lines.append(
                    f"device.tree_version:{mirror.published_version()}"
                )
                lines.append(f"device.shards:{mirror.shard_count()}")
                if self._engine._h:
                    lines.append(
                        f"node.engine_version:{self._engine.version()}"
                    )
            except Exception:
                pass  # a dying mirror drops its lines, not METRICS
        # Partition plane: identity + state lines so wire-only consumers
        # (top's PART column, the chaos suite) see which partition this
        # node serves and how it is doing, without scraping /metrics.
        # Integer-text contract like every METRICS line.
        if self._partition_id is not None:
            lines.append(f"partition.id:{self._partition_id}")
            lines.append(f"partition.epoch:{self._partmap.epoch}")
            lines.append(f"partition.count:{self._partmap.count}")
            lines.append(f"partition.state:{self.ladder.level()}")
        lines.append(f"rebalance.state:{self._rebalance_state_code()}")
        # Overload plane: the ladder rung plus the native shed counters
        # (one stats_text read), so wire-only consumers (top's STATE and
        # SHED/s columns) see overload state without scraping /metrics.
        lines.append(f"node.degradation:{self.ladder.level()}")
        try:
            stats: dict[str, str] = {}
            for ln in self._server.stats_text().splitlines():
                name, _, value = ln.strip().partition(":")
                stats[name] = value
            shed = sum(
                int(stats.get(k, 0) or 0)
                for k in (
                    "shed_commands",
                    "busy_rejected_connections",
                    "pipeline_rejected",
                )
            )
            lines.append(f"node.shed_total:{shed}")
            lines.append(
                "node.readonly_rejected:"
                f"{int(stats.get('readonly_commands', 0) or 0)}"
            )
        except Exception:
            pass  # a dead server handle drops the shed lines, not METRICS
        body = "".join(f"{ln}\r\n" for ln in lines)
        return f"METRICS\r\n{body}END\r\n"

    @staticmethod
    def _take_version_flags(args: list[str]) -> tuple[bool, bool]:
        """(want_version, force_refresh) from a trailing vs=XX token the
        native parser relayed on HASH/TREELEVEL callback lines."""
        for p in args:
            if len(p) == 5 and p.startswith("vs="):
                try:
                    flags = int(p[3:], 16)
                except ValueError:
                    continue
                return bool(flags & 1), bool(flags & 2)
        return False, False

    def _version_lag(self, served_version: int) -> int:
        """Mutations the live engine has moved past the served tree — the
        lag half of a stamped answer. A dead engine handle reads as 0
        rather than driving the FFI through a closed pointer."""
        try:
            if not self._engine._h:
                return 0
            return max(0, self._engine.version() - served_version)
        except Exception:
            return 0

    # -- cluster command callback ---------------------------------------------
    def _on_cluster_command(self, line: str) -> Optional[str]:
        parts = line.split()
        if parts[0] == "PEERS":
            if self._health is None:
                return None  # native default: empty table
            return self._health.wire_table()
        if parts[0] == "REBALANCE":
            # Live-rebalance control plane. Relayed by the native server
            # OUTSIDE the degradation/serving gates: a fenced sibling or a
            # non-serving joiner must still take COMMIT/ABORT, or a
            # wobbling node could wedge the whole session.
            return self._rebalance_manager().handle(parts[1:])
        if parts[0] == "PARTMAP":
            # Versioned partition map: any member serves the full routing
            # table (smart clients/routers bootstrap from whichever node
            # they can reach). None on an unpartitioned node -> the native
            # fallback answers ERROR (capability signal).
            if self._partmap is None:
                return None
            return self._partmap.wire()
        if parts[0] == "METRICS":
            return self._metrics_wire()
        if parts[0] == "TRACE":
            # Correlated anti-entropy traces: newest n cycles, one k=v row
            # per (cycle, peer) from the process-wide ring buffer.
            from merklekv_tpu.obs.trace import get_trace_buffer

            n = int(parts[1]) if len(parts) > 1 else 8
            return get_trace_buffer().wire_dump(n)
        if parts[0] == "TRACEDUMP":
            # Raw causal-trace spans (cross-node stitching input).
            n = int(parts[1]) if len(parts) > 1 else 0
            return tracewire.get_collector().wire_dump(n)
        if parts[0] == "TRACESPAN":
            # Native server notification: a traced cluster verb was served
            # on this node. Record the donor-side span under the
            # initiator's trace id, parented to the span id the token
            # carried. "TRACESPAN <VERB> <tc=token> <start_ns> <dur_ns>".
            return self._record_trace_span(parts[1:])
        if parts[0] == "FLIGHT":
            # Flight-recorder stream: the full python event ring (which
            # includes native slow commands relayed via SLOWCMD below).
            from merklekv_tpu.obs.flightrec import get_recorder

            n = int(parts[1]) if len(parts) > 1 else 64
            return get_recorder().wire_dump(n)
        if parts[0] == "SLOWCMD":
            # Native notification: a dispatch crossed the slow-command
            # threshold. "SLOWCMD <VERB> <dur_us> <addr> [tc=token]" —
            # a traced serve carries the initiator's token, and stamping
            # its trace id here is what lets blackbox link this node's
            # slow serve to the initiator's cycle across spills.
            # Malformed notifications drop (never an error into native
            # dispatch).
            from merklekv_tpu.obs.flightrec import record

            try:
                fields = {
                    "verb": parts[1],
                    "dur_us": int(parts[2]),
                    "conn": parts[3],
                }
                if len(parts) > 4:
                    ctx = tracewire.parse_token(parts[4])
                    if ctx is not None:
                        fields["trace"] = f"{ctx.trace_id:016x}"
                record("slow_command", **fields)
            except (IndexError, ValueError):
                pass
            return "OK\r\n"
        if parts[0] == "PROFILE":
            return self._profile_wire(int(parts[1]))
        if parts[0] == "HASH":
            # Whole-keyspace root served from the device pump's
            # last-published snapshot; empty answer falls back to the
            # native path. A trailing vs= token (relayed verbatim by the
            # native parser) asks for the version stamp / forced refresh.
            want, force = self._take_version_flags(parts[1:])
            if not want:
                root = self.device_root_hex(force=force)
                return f"HASH {root}\r\n" if root is not None else None
            out = self._query_ready_mirror(
                lambda m: m.published_root_stamped(), force=force
            )
            if out is None:
                return None
            root, ver = out
            return f"HASH {root} {ver} {self._version_lag(ver)}\r\n"
        if parts[0] == "TREELEVEL":
            # Bisection-walk node fetch served from the pump's
            # last-published tree (one batched device gather per request);
            # empty answer falls back to the native server's cached host
            # tree. Stamped when the request carried a vs= token.
            args = [p for p in parts[1:] if not p.startswith("vs=")]
            want, force = self._take_version_flags(parts[1:])
            if not want:
                out = self.device_tree_level(
                    int(args[0]), int(args[1]), int(args[2]), force=force
                )
                if out is None:
                    return None
                rows, n = out
                body = "".join(f"{i} {d.hex()}\r\n" for i, d in rows)
                return f"NODES {len(rows)} {n}\r\n{body}"
            out = self._query_ready_mirror(
                lambda m: m.level_nodes_stamped(
                    int(args[0]), int(args[1]), int(args[2])
                ),
                force=force,
            )
            if out is None:
                return None
            rows, n, ver = out
            body = "".join(f"{i} {d.hex()}\r\n" for i, d in rows)
            return (
                f"NODES {len(rows)} {n} {ver} {self._version_lag(ver)}\r\n"
                f"{body}"
            )
        if parts[0] == "SNAPMETA":
            return self._snap_meta_wire()
        if parts[0] == "SNAPCHUNK":
            return self._snap_chunk_wire(
                int(parts[1]), int(parts[2]), int(parts[3])
            )
        if parts[0] == "SYNC":
            host, port = parts[1], int(parts[2])
            try:
                self.sync_manager.sync_once(
                    host,
                    port,
                    full="--full" in parts,
                    verify="--verify" in parts,
                )
                return "OK\r\n"
            except Exception as e:
                return f"ERROR {e}\r\n"
        if parts[0] == "REPLICATE":
            action = parts[1]
            if action == "enable":
                err = self._enable_replication()
                return "OK\r\n" if err is None else f"ERROR {err}\r\n"
            if action == "disable":
                self._disable_replication()
                return "OK\r\n"
            if action == "status":
                with self._rep_mu:
                    enabled = self._replicator is not None
                if enabled:
                    n = len(self._cfg.replication.peer_list)
                    return f"REPLICATION enabled {n} nodes\r\n"
                return "REPLICATION disabled\r\n"
        return None

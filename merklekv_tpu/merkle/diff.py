"""Vectorized multi-replica Merkle diff.

The reference diffs two trees by walking a flat leaf map pairwise on the host
(/root/reference/src/store/merkle.rs:171-196) and reconciles one peer at a
time over per-key TCP GETs (/root/reference/src/sync.rs:56-214). Here the
whole comparison is one XLA program over stacked replica tensors:

  - N replicas' leaf digests are aligned host-side onto the union keyspace
    (sorted keys; absent keys get a presence-mask 0);
  - the device computes per-key divergence masks for all replicas against a
    reference replica simultaneously — [R, N] in one fused elementwise pass;
  - winners for reconciliation (LWW at a higher layer) come back as index
    lists, not values — values never travel through the diff.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "AlignedReplicas",
    "align_replicas",
    "divergence_vs_ref",
    "divergence_masks",
    "divergence_masks_engine",
    "diff_keys_multi",
    "diff_keys_pair",
]

# Route the [R, N] comparison through the keyspace-sharded SPMD program
# only past this union-keyspace size: below it the collective setup costs
# more than the elementwise pass it parallelizes.
SHARDED_DIFF_MIN_KEYS = 1 << 15


class AlignedReplicas:
    """Union-keyspace alignment of R replicas' (key -> leaf digest) maps.

    Attributes:
      keys:    union keyspace, sorted bytes, length N.
      digests: [R, N, 8] uint32 — leaf digest per replica/key (0 if absent).
      present: [R, N] bool — key present in replica r.
    """

    __slots__ = ("keys", "digests", "present")

    def __init__(self, keys: list[bytes], digests: np.ndarray, present: np.ndarray):
        self.keys = keys
        self.digests = digests
        self.present = present

    @property
    def n_replicas(self) -> int:
        return self.digests.shape[0]

    @property
    def n_keys(self) -> int:
        return self.digests.shape[1]


def align_replicas(replicas: Sequence[dict[bytes, bytes]]) -> AlignedReplicas:
    """Align R (key -> 32-byte leaf hash) maps onto the sorted union keyspace."""
    union: set[bytes] = set()
    for r in replicas:
        union.update(r.keys())
    keys = sorted(union)
    n = len(keys)
    r_count = len(replicas)
    idx = {k: i for i, k in enumerate(keys)}
    digests = np.zeros((r_count, n, 8), np.uint32)
    present = np.zeros((r_count, n), bool)
    for ri, rep in enumerate(replicas):
        for k, h in rep.items():
            i = idx[k]
            digests[ri, i] = np.frombuffer(h, ">u4").astype(np.uint32)
            present[ri, i] = True
    return AlignedReplicas(keys, digests, present)


def divergence_vs_ref(digests, present, ref_d, ref_p):
    """THE divergence predicate, in one place: a key diverges iff presence
    differs or both present with different digests. Polymorphic over numpy
    and jax arrays (method-call formulation, no jnp/np entry points) so the
    device programs and the host twin cannot drift apart. Deliberately NOT
    jitted: divergence_masks_np must stay pure-host (spawned server
    processes may not initialize an accelerator backend), and the device
    callers already jit at their own program boundaries."""
    same_digest = (digests == ref_d).all(axis=-1)
    both_present = present & ref_p
    return (present != ref_p) | (both_present & ~same_digest)


def divergence_masks(digests: jax.Array, present: jax.Array) -> jax.Array:
    """[R, N] bool: key i diverges between replica r and replica 0.

    Row 0 is all-False by construction.
    """
    return divergence_vs_ref(digests, present, digests[0:1], present[0:1])


def divergence_masks_np(digests: np.ndarray, present: np.ndarray) -> np.ndarray:
    """Host-side twin of :func:`divergence_masks` for small keyspaces where
    initializing an accelerator backend is not worth it (and, in spawned
    server processes, must be avoided unless explicitly configured)."""
    return divergence_vs_ref(digests, present, digests[0:1], present[0:1])


def _local_diff_mesh():
    """One-axis ``key`` mesh over the largest power-of-two local-device
    subset, or None on a single-device host. Deferred import: parallel/
    imports this module, so the dependency must stay call-time."""
    from merklekv_tpu.parallel.mesh import make_mesh
    from merklekv_tpu.parallel.sharded_state import resolve_shard_count

    devs = jax.local_devices()
    n = resolve_shard_count("auto", len(devs))  # 0 on a 1-device host
    if n < 2:
        return None
    return make_mesh({"key": n}, devices=devs[:n])


def divergence_masks_engine(
    digests, present, min_keys: Optional[int] = None
) -> jax.Array:
    """The N-replica diff behind the engine boundary.

    Routes the ``[R, N]`` comparison through the keyspace-sharded SPMD
    program (``parallel.sharded_merkle.sharded_divergence``) when the host
    has a multi-device mesh and the union keyspace amortizes the
    collectives; single-device :func:`divergence_masks` otherwise. Masks
    are bit-identical either way. The key axis is padded up to the mesh
    axis with all-absent columns (absent everywhere == absent on the
    reference -> never divergent) and sliced back off.

    ``min_keys`` overrides :data:`SHARDED_DIFF_MIN_KEYS` (0 forces the
    sharded path whenever a mesh exists — tests and the bench sweep).
    """
    n = int(digests.shape[1])
    lim = SHARDED_DIFF_MIN_KEYS if min_keys is None else min_keys
    mesh = None
    if n > 0 and n >= lim:
        try:
            mesh = _local_diff_mesh()
        except Exception:
            mesh = None
    if mesh is None:
        return divergence_masks(digests, present)
    from merklekv_tpu.device.guard import DeviceDispatchError, get_guard
    from merklekv_tpu.parallel.sharded_merkle import sharded_divergence

    d = int(mesh.shape["key"])
    pad = (-n) % d
    if pad:
        dig = np.concatenate(
            [np.asarray(digests),
             np.zeros((digests.shape[0], pad, 8), np.uint32)], axis=1
        )
        pres = np.concatenate(
            [np.asarray(present),
             np.zeros((present.shape[0], pad), bool)], axis=1
        )
    else:
        dig, pres = digests, present
    try:
        # Deadline-guarded like every serving-path device program: a sick
        # mesh fails the dispatch at the guard instead of wedging the
        # anti-entropy walk. Label follows the documented shard{N}_*
        # scheme so chaos globs targeting the sharded rungs (shard*,
        # shard8_*) reach this seam too.
        masks, _counts = get_guard().run(
            f"shard{d}_diff", lambda: sharded_divergence(mesh, dig, pres)
        )
    except DeviceDispatchError:
        # The sharded program is an optimization, never the contract: the
        # single-device comparison is bit-identical, so a faulted mesh
        # sheds parallelism here, not the sync plane.
        return divergence_masks(digests, present)
    return masks[:, :n] if pad else masks


@jax.jit
def _any_divergent(digests: jax.Array, present: jax.Array) -> jax.Array:
    """[N] bool: key diverges between ANY pair of replicas (union view)."""
    masks = divergence_masks(digests, present)
    return jnp.any(masks, axis=0)


def diff_keys_multi(aligned: AlignedReplicas) -> dict[int, list[bytes]]:
    """Per-replica divergent key lists vs replica 0, computed in one program."""
    if aligned.n_keys == 0:
        return {r: [] for r in range(1, aligned.n_replicas)}
    masks = np.asarray(divergence_masks(aligned.digests, aligned.present))
    out: dict[int, list[bytes]] = {}
    for r in range(1, aligned.n_replicas):
        (ii,) = np.nonzero(masks[r])
        out[r] = [aligned.keys[i] for i in ii]
    return out


def diff_keys_pair(
    local: dict[bytes, bytes], remote: dict[bytes, bytes]
) -> list[bytes]:
    """Sorted keys differing between two leaf-hash maps (reference
    merkle.rs:171-196 semantics), via the batched device path."""
    aligned = align_replicas([local, remote])
    return diff_keys_multi(aligned).get(1, [])

"""CpuMerkleState: the degradation ladder's terminal rung.

A drop-in for :class:`merklekv_tpu.merkle.incremental.DeviceMerkleState`
built ENTIRELY from the golden CPU tree (merkle/cpu.py) — no jax import,
no device dispatch, nothing a sick accelerator plane can wedge. The
degradation ladder (merklekv_tpu.device.ladder) falls back to it when
every device rung has failed, so a node with a dead backend still serves
HASH/TREELEVEL bit-identically (the levels ARE the reference tree — no
promotion-chain correction needed) at host-hashing speed.

Surface parity with DeviceMerkleState (the subset the mirror's pump,
staging, and query paths drive): ``from_items`` / ``apply`` /
``pending_count`` / ``flush_pending`` / ``root_hex(flush=)`` /
``root_hash`` / ``level_nodes(level, lo, hi, flush=)`` / ``leaf_count`` /
``leaf_digest``. ``_n_shards`` is 0 — the ``device.backend_level`` gauge's
"CPU golden" code.

Cost model: mutations update the leaf-hash map (O(batch) leaf hashing);
interior levels rebuild lazily per publish generation (O(n) 64-byte node
compressions, no leaf rehashing). That is the last-resort trade the ladder
makes deliberately: correctness and liveness over the device plane's
throughput.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

from merklekv_tpu.merkle.cpu import build_levels, ref_level_sizes
from merklekv_tpu.merkle.encoding import leaf_hash

__all__ = ["CpuMerkleState"]


class CpuMerkleState:
    # Same staging ceiling as the device state: the mirror's PENDING_LIMIT
    # auto-publish contract must hold on every rung.
    PENDING_LIMIT = 65536

    _n_shards = 0  # backend-level code: CPU golden rung

    def __init__(self) -> None:
        self._leaves: dict[bytes, bytes] = {}  # key -> 32-byte leaf hash
        self._sorted: list[bytes] = []
        self._levels: list[list[bytes]] = []
        self._dirty = False
        self._pending: dict[bytes, Optional[bytes]] = {}
        # Attribution parity with the device state (tests/gauges read them).
        self.full_rebuilds = 0
        self.incremental_batches = 0
        self.structural_batches = 0

    # ------------------------------------------------------------ loading
    @classmethod
    def from_items(
        cls, items: Iterable[tuple[bytes, bytes]]
    ) -> "CpuMerkleState":
        st = cls()
        dedup = dict(items)
        if dedup:
            st._leaves = {k: leaf_hash(k, v) for k, v in dedup.items()}
            st._dirty = True
            st.full_rebuilds += 1
        return st

    def __len__(self) -> int:
        self._flush()
        return len(self._leaves)

    def leaf_count(self) -> int:
        # The leaf map only moves at flush, so this is the as-published
        # count; staged pending changes don't count until their flush.
        return len(self._leaves)

    # ------------------------------------------------------------ updates
    def apply(self, changes: Sequence[tuple[bytes, Optional[bytes]]]) -> None:
        for k, v in changes:
            self._pending[k] = v
        if len(self._pending) >= self.PENDING_LIMIT:
            self._flush()

    def pending_count(self) -> int:
        return len(self._pending)

    def flush_pending(self) -> None:
        self._flush()

    def _flush(self) -> None:
        if not self._pending:
            return
        pending, self._pending = self._pending, {}
        structural = False
        for k, v in pending.items():
            if v is None:
                structural |= self._leaves.pop(k, None) is not None
            else:
                structural |= k not in self._leaves
                self._leaves[k] = leaf_hash(k, v)
        self._dirty = True
        if structural:
            self.structural_batches += 1
        else:
            self.incremental_batches += 1

    def _rebuild(self) -> None:
        if not self._dirty:
            return
        self._sorted = sorted(self._leaves)
        self._levels = build_levels([self._leaves[k] for k in self._sorted])
        self._dirty = False

    # ------------------------------------------------------------ queries
    def root_hash(self, flush: bool = True) -> Optional[bytes]:
        if flush:
            self._flush()
        self._rebuild()
        return self._levels[-1][0] if self._levels else None

    def root_hex(self, flush: bool = True) -> str:
        r = self.root_hash(flush=flush)
        return r.hex() if r is not None else "0" * 64

    def leaf_digest(self, key: bytes) -> Optional[bytes]:
        self._flush()
        return self._leaves.get(key)

    def level_nodes(
        self, level: int, lo: int, hi: int, flush: bool = True
    ) -> tuple[list[tuple[int, bytes]], int]:
        """Reference-tree digests at ``level`` for ``[lo, hi)`` plus the
        live leaf count — bit-identical to the device answer by
        construction (these ARE the reference levels)."""
        if flush:
            self._flush()
        self._rebuild()
        n = len(self._sorted)
        if n == 0:
            return [], 0
        sizes = ref_level_sizes(n)
        if level >= len(sizes):
            return [], n
        m = sizes[level]
        lo = max(0, min(lo, m))
        hi = max(lo, min(hi, m))
        return [(i, self._levels[level][i]) for i in range(lo, hi)], n

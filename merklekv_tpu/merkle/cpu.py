"""Golden CPU Merkle tree.

Semantics-equal to the reference tree (/root/reference/src/store/merkle.rs):
leaves sorted lexicographically by key (byte order), pairwise bottom-up
combination, odd trailing node promoted unchanged, flat leaf-map diff.

Two deliberate departures from the reference *implementation* (roots are
still bit-identical):

- **Lazy rebuild.** The reference rebuilds the whole tree on every
  insert/remove (merkle.rs:52-62), making an n-key snapshot O(n^2 log n)
  hashing. Here mutations only touch the leaf map; levels are rebuilt once,
  on demand.
- **Flat level arrays.** The tree is a list of levels of 32-byte hashes,
  not linked nodes — the same layout the TPU engine uses, so parity tests
  can compare any level, not just the root. Structural views
  (preorder_hashes / node_count) are derived from the level layout.
"""

from __future__ import annotations

from typing import Iterable, Optional

from merklekv_tpu.merkle.encoding import EMPTY_ROOT_HEX, leaf_hash, node_hash


def _sort_key(k: str) -> bytes:
    # Rust `String::cmp` is byte-wise over UTF-8; UTF-8 byte order equals
    # code-point order, but sorting on the encoded bytes makes that explicit.
    return k.encode("utf-8")


def ref_level_sizes(n: int) -> list[int]:
    """Reference (odd-promotion) tree level sizes for ``n`` leaves:
    ``[n, (n+1)//2, ...]`` down to 1; empty for ``n <= 0``. The single
    source of the size law — the device tree's level serving and the sync
    walk's index math both import it, so a future tree-shape change cannot
    desync them."""
    if n <= 0:
        return []
    sizes = [n]
    while sizes[-1] > 1:
        sizes.append((sizes[-1] + 1) // 2)
    return sizes


def build_levels(leaf_hashes: list[bytes]) -> list[list[bytes]]:
    """Bottom-up levels from sorted leaf hashes. levels[0] is the leaves;
    levels[-1] is [root]. Odd trailing nodes are promoted (copied up)."""
    if not leaf_hashes:
        return []
    levels = [list(leaf_hashes)]
    while len(levels[-1]) > 1:
        cur = levels[-1]
        nxt = [node_hash(cur[i], cur[i + 1]) for i in range(0, len(cur) - 1, 2)]
        if len(cur) % 2:
            nxt.append(cur[-1])
        levels.append(nxt)
    return levels


def root_from_leaf_hashes(leaf_hashes: list[bytes]) -> Optional[bytes]:
    levels = build_levels(leaf_hashes)
    return levels[-1][0] if levels else None


class MerkleTree:
    """In-memory Merkle tree over a (key -> leaf hash) map."""

    def __init__(self) -> None:
        self._leaf_map: dict[str, bytes] = {}
        self._levels: list[list[bytes]] = []
        self._dirty = False

    # ------------------------------------------------------------ mutation

    def insert(self, key: str, value: str | bytes) -> None:
        self._leaf_map[key] = leaf_hash(key, value)
        self._dirty = True

    def insert_hash(self, key: str, hash32: bytes) -> None:
        """Insert a precomputed leaf hash (used when only hashes travel)."""
        if len(hash32) != 32:
            raise ValueError("leaf hash must be 32 bytes")
        self._leaf_map[key] = hash32
        self._dirty = True

    def remove(self, key: str) -> None:
        if self._leaf_map.pop(key, None) is not None:
            self._dirty = True

    def clear(self) -> None:
        if self._leaf_map:
            self._leaf_map.clear()
            self._dirty = True

    @classmethod
    def from_items(cls, items: Iterable[tuple[str, str | bytes]]) -> "MerkleTree":
        t = cls()
        for k, v in items:
            t.insert(k, v)
        return t

    # ------------------------------------------------------------ queries

    def __len__(self) -> int:
        return len(self._leaf_map)

    def __contains__(self, key: str) -> bool:
        return key in self._leaf_map

    def leaf_hash_of(self, key: str) -> Optional[bytes]:
        return self._leaf_map.get(key)

    def _rebuild(self) -> None:
        if not self._dirty:
            return
        ordered = sorted(self._leaf_map.items(), key=lambda kv: _sort_key(kv[0]))
        self._levels = build_levels([h for _, h in ordered])
        self._dirty = False

    @property
    def levels(self) -> list[list[bytes]]:
        self._rebuild()
        return self._levels

    def root_hash(self) -> Optional[bytes]:
        self._rebuild()
        return self._levels[-1][0] if self._levels else None

    def root_hex(self) -> str:
        r = self.root_hash()
        return r.hex() if r is not None else EMPTY_ROOT_HEX

    # ------------------------------------------------------------ views

    def inorder_keys(self) -> list[str]:
        return sorted(self._leaf_map.keys(), key=_sort_key)

    def leaves(self) -> list[tuple[str, bytes]]:
        return sorted(self._leaf_map.items(), key=lambda kv: _sort_key(kv[0]))

    def node_count(self) -> int:
        """Nodes in the materialized tree (promoted nodes counted once),
        matching the reference's linked-node count (merkle.rs:155-163)."""
        self._rebuild()
        if not self._levels:
            return 0
        count = len(self._levels[0])
        for lo in self._levels[:-1]:
            # Each full pair at this level yields one new parent node;
            # a promoted odd tail is the same node, not a new one.
            count += len(lo) // 2
        return count

    def preorder_hashes(self) -> list[bytes]:
        """Root -> left subtree -> right subtree over the implicit structure.

        A promoted node at level l+1 shares identity with its level-l
        origin, so traversal descends through promotions without re-emitting
        them (parity with the reference's cloned-node traversal).
        """
        self._rebuild()
        if not self._levels:
            return []
        out: list[bytes] = []

        def go(level: int, idx: int) -> None:
            out.append(self._levels[level][idx])
            if level == 0:
                return
            lo = self._levels[level - 1]
            li, ri = 2 * idx, 2 * idx + 1
            if ri < len(lo):
                go(level - 1, li)
                go(level - 1, ri)
            else:
                # Promotion: same node one level down; skip the duplicate
                # emission and descend directly to its children.
                drop = out.pop()
                assert drop == lo[li]
                go(level - 1, li)

        go(len(self._levels) - 1, 0)
        return out

    # ------------------------------------------------------------ diff

    def diff_keys(self, other: "MerkleTree") -> list[str]:
        """Exact set of differing keys, sorted: present in only one tree, or
        present in both with different leaf hashes
        (reference: merkle.rs:171-196)."""
        diffs: list[str] = []
        for k in sorted(self._leaf_map.keys() | other._leaf_map.keys(), key=_sort_key):
            if self._leaf_map.get(k) != other._leaf_map.get(k):
                diffs.append(k)
        return diffs

    def diff_first_key(self, other: "MerkleTree") -> Optional[str]:
        d = self.diff_keys(other)
        return d[0] if d else None

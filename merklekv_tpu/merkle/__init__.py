"""Merkle hash-tree core.

`encoding` pins the byte-level hash spec (shared by CPU and TPU engines);
`cpu` is the golden host implementation. The pluggable MerkleEngine seam the
anti-entropy subsystem programs against (analog of the reference's
storage-engine plugin boundary, /root/reference/src/store/mod.rs) lives in
`merklekv_tpu.merkle.engine` once the TPU engine lands.
"""

from merklekv_tpu.merkle.encoding import (
    EMPTY_ROOT_HEX,
    encode_leaf,
    leaf_hash,
    node_hash,
)
from merklekv_tpu.merkle.cpu import MerkleTree, build_levels, root_from_leaf_hashes

__all__ = [
    "EMPTY_ROOT_HEX",
    "encode_leaf",
    "leaf_hash",
    "node_hash",
    "MerkleTree",
    "build_levels",
    "root_from_leaf_hashes",
]

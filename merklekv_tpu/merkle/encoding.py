"""Byte-level Merkle hash specification.

This module is the single source of truth for how (key, value) pairs become
leaf hashes and how sibling hashes combine into parent hashes. Both the CPU
golden implementation and the TPU (JAX/Pallas) engines derive from this spec,
so their roots are bit-identical.

Spec (matches the reference semantics, /root/reference/src/store/merkle.rs:7-16,45-49,96-103):

  leaf_bytes(k, v) = u32_be(len(k)) || k || u32_be(len(v)) || v
  leaf_hash(k, v)  = SHA256(leaf_bytes(k, v))
  node_hash(l, r)  = SHA256(l || r)            # l, r are 32-byte child hashes

Length-prefixing makes the encoding injective for arbitrary bytes (NUL,
unicode, empty strings), so distinct (k, v) pairs can never collide by
concatenation ambiguity.

The empty tree has no root; the protocol's `HASH` command renders it as 64
ASCII zeros (reference: src/server.rs:671-675).
"""

from __future__ import annotations

import hashlib
import struct

EMPTY_ROOT_HEX = "0" * 64

_U32_BE = struct.Struct(">I")


def _as_bytes(s: str | bytes) -> bytes:
    return s.encode("utf-8") if isinstance(s, str) else s


def encode_leaf(key: str | bytes, value: str | bytes) -> bytes:
    """Injective length-prefixed encoding of a (key, value) pair."""
    kb = _as_bytes(key)
    vb = _as_bytes(value)
    return b"".join((_U32_BE.pack(len(kb)), kb, _U32_BE.pack(len(vb)), vb))


def leaf_hash(key: str | bytes, value: str | bytes) -> bytes:
    """32-byte SHA-256 leaf hash of a (key, value) pair."""
    return hashlib.sha256(encode_leaf(key, value)).digest()


def node_hash(left: bytes, right: bytes) -> bytes:
    """32-byte SHA-256 parent hash of two 32-byte child hashes."""
    return hashlib.sha256(left + right).digest()

"""Device-resident Merkle state with incremental updates for every op kind.

The reference rebuilds its whole tree on every mutation
(/root/reference/src/store/merkle.rs:52-56) and never updates the tree from
replication events (TODO at replication.rs:312-316). Here the tree LIVES in
device HBM and change-event batches are applied as XLA programs:

- **value updates** (keyspace shape unchanged): hash the k changed leaves,
  scatter them into the capacity-padded leaf level, re-reduce only the
  touched parent paths — O(k log C) device work.
- **inserts / deletes** (shape changes): the sorted layout shifts, so the
  interior of the tree right of the first edit must re-reduce — but the
  surviving leaves' digests are already on device. The batch becomes: host
  computes the permutation (numpy index arithmetic, no hashing), device
  gathers surviving digests into their new slots, scatters the k fresh
  digests, and re-reduces all levels. Host hashing cost is O(k changed
  leaves), never O(n); the O(n) interior re-reduction is pure 64-byte
  SHA-256 compressions in one fused program.

Representation: a FULL binary tree at capacity C = 2^d (slots >= n hold a
zero sentinel). The reference tree pairs only live nodes and promotes odd
tails, so its levels differ from the padded tree's — but only on the right
spine: by induction, reference level l equals padded level l at every
position except the last. ``_ref_root`` therefore recovers the bit-exact
reference root in one O(log C) walk that carries the corrected last node
("promotion chain") and reads one padded node per level.

Host memory: only the sorted key array is kept (values are never stored —
fresh digests are computed from the (key, value) pairs each batch carries),
so a 10M-key tree costs the host one object array, not a value map.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Iterable, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from merklekv_tpu.device.guard import get_guard
from merklekv_tpu.merkle.jax_engine import leaf_digests
from merklekv_tpu.obs.metrics import get_metrics
from merklekv_tpu.ops.dispatch import (
    hash_node_level,
    hash_node_pairs,
    use_pallas,
)
from merklekv_tpu.ops.sha256 import (
    digest_to_bytes,
    digests_to_bytes,
    sha256_node_pairs,
)

__all__ = ["DeviceMerkleState"]


def _next_pow2(n: int) -> int:
    return 1 << max(1, (n - 1).bit_length())


def _bucket(k: int) -> int:
    """Round a batch size up so one compiled program serves many sizes."""
    return _next_pow2(max(k, 16))


def _reduce_levels(leaves: jax.Array) -> tuple:
    """All padded-tree levels bottom-up; trace-time loop, static shapes.
    Node hashing is backend-dispatched (Pallas on TPU, scan elsewhere)."""
    levels = [leaves]
    cur = leaves
    while cur.shape[0] > 1:
        # Adjacent-pair level hash: capacity is a power of two, so every
        # level is even and the contiguous level kernel applies throughout
        # (no odd-promotion tail in the padded tree).
        cur = hash_node_level(cur)
        levels.append(cur)
    return tuple(levels)


# The compiled-program caches below key on use_pallas() so a backend flip
# between traces (tests forcing MKV_SHA256_BACKEND) can't replay a program
# compiled for the other formulation.

@lru_cache(maxsize=None)
def _build_fn(capacity: int, pallas: bool):
    """Compiled initial build over capacity-padded leaves: one compile per
    capacity bucket, shared by every live count within it (the caller pads
    the digest array to C on the host)."""
    del pallas  # cache key only; _reduce_levels re-reads the dispatch

    @jax.jit
    def go(leaves: jax.Array):
        return _reduce_levels(leaves)

    return go


def _scatter_levels(levels: tuple, idx: jax.Array, new_leaves: jax.Array):
    """Scatter new leaf digests + re-reduce only the touched parent paths.

    idx [kb] int32 (padded entries duplicate a real entry with the
    identical leaf value, so duplicate scatters are benign);
    new_leaves [kb, 8] uint32."""
    out = [levels[0].at[idx].set(new_leaves)]
    cur_idx = idx
    for lvl in range(1, len(levels)):
        cur_idx = cur_idx // 2
        left = out[-1][2 * cur_idx]
        right = out[-1][2 * cur_idx + 1]
        parents = hash_node_pairs(left, right)
        out.append(levels[lvl].at[cur_idx].set(parents))
    return tuple(out)


@lru_cache(maxsize=None)
def _scatter_hash_fn(capacity: int, kb: int, nblk: int, pallas: bool):
    """Fused leaf hashing + scatter + path re-reduction: ONE device program
    per update batch. Separate hash-then-scatter calls each pay a host->
    device dispatch round trip — through a tunneled backend that latency,
    not the hashing, dominates sustained update throughput (BASELINE
    config 4)."""
    del pallas

    @jax.jit
    def go(levels: tuple, idx: jax.Array, blocks: jax.Array,
           nblocks: jax.Array):
        from merklekv_tpu.ops.dispatch import hash_blocks

        return _scatter_levels(levels, idx, hash_blocks(blocks, nblocks))

    return go


@lru_cache(maxsize=None)
def _restructure_fn(c_old: int, c_new: int, kb: int, pallas: bool):
    """Compiled gather + scatter + full reduction for shape changes.

    gather_idx [c_new] int32: source slot in the OLD leaf level for each new
    slot, or -1 for slots that receive a fresh digest / stay zero.
    fresh_pos [kb] int32 + fresh [kb, 8]: the k changed/inserted digests
    (padded entries duplicate entry 0 — same value, benign).
    """
    del pallas

    @jax.jit
    def go(old_leaves, gather_idx, fresh_pos, fresh):
        safe = jnp.clip(gather_idx, 0, max(c_old - 1, 0))
        base = jnp.where((gather_idx >= 0)[:, None], old_leaves[safe], 0)
        if kb:
            base = base.at[fresh_pos].set(fresh)
        return _reduce_levels(base)

    return go


@lru_cache(maxsize=None)
def _ref_root_fn(capacity: int):
    """Compiled promotion-chain walk: padded levels + live count n -> the
    reference odd-promotion root over the first n leaves."""

    @jax.jit
    def go(levels: tuple, n: jax.Array):
        m = jnp.asarray(n, jnp.int32)
        last = jax.lax.dynamic_index_in_dim(
            levels[0], jnp.maximum(m - 1, 0), axis=0, keepdims=False
        )
        for lvl in range(1, len(levels)):
            odd = (m % 2) == 1
            # Even m: reference's next last = H(level[m-2], last). Position
            # m-2 of the reference level equals the padded level (only the
            # last position can differ).
            prev = jax.lax.dynamic_index_in_dim(
                levels[lvl - 1], jnp.maximum(m - 2, 0), axis=0, keepdims=False
            )
            combined = sha256_node_pairs(prev[None], last[None])[0]
            # Odd m: the tail is promoted unchanged. m == 1: stay at root.
            new_last = jnp.where(odd, last, combined)
            last = jnp.where(m <= 1, last, new_last)
            m = jnp.where(m <= 1, m, (m + 1) // 2)
        return last

    return go


class DeviceMerkleState:
    """Sorted keyspace + device-resident padded tree levels.

    Host side owns only the sorted key array (the authoritative KV store is
    the native engine). Device side owns ``levels``: levels[0] is [C, 8]
    leaf digests, levels[d] is [1, 8].

    ``sharding`` (a ``NamedSharding`` whose spec shards dim 0, e.g.
    ``P("key", None)``) places the leaf level across a device mesh; the
    jitted build/scatter/restructure programs then run SPMD with XLA
    inserting the collectives (GSPMD) — the serving-path integration of
    SURVEY §2.4's keyspace sharding. Capacity is kept a multiple of the
    mesh axis so the leaf dimension always divides evenly.
    """

    # Auto-flush ceiling: bounds the host memory pending values can hold.
    PENDING_LIMIT = 65536

    # Dispatch-guard label prefix: every device program call routes through
    # the process guard (merklekv_tpu.device.guard) under a label naming
    # the seam — the chaos injector matches on it and the degradation
    # ladder reads it out of the typed error. The sharded subclass prefixes
    # its shard width so faults can target one rung.
    _guard_prefix = ""

    def _label(self, op: str) -> str:
        return self._guard_prefix + op

    def __init__(self, sharding=None) -> None:
        self._keys = np.empty(0, dtype=object)  # sorted key bytes
        # key -> sorted position. np.searchsorted on an OBJECT array does a
        # Python-level comparison per probe (~tens of ms per 32K-key batch
        # against a 1M tree) and was the sustained-update bottleneck; dict
        # lookups are O(1) C-level. Rebuilt on structural changes only.
        self._index: dict[bytes, int] = {}
        self._levels: Optional[tuple[jax.Array, ...]] = None
        self._capacity = 0
        self._sharding = sharding
        if sharding is not None:
            axis = sharding.spec[0]
            if not isinstance(axis, str):
                raise ValueError(
                    "sharding must shard dim 0 on a named mesh axis"
                )
            self._n_shards = int(sharding.mesh.shape[axis])
            if self._n_shards & (self._n_shards - 1):
                # Capacity is a power of two (the padded-tree math depends
                # on it), so only power-of-two shard counts divide the leaf
                # dimension evenly. Callers with odd device counts should
                # mesh a power-of-two subset (DeviceTreeMirror does).
                raise ValueError(
                    f"sharded tree needs a power-of-two shard count, "
                    f"got {self._n_shards}"
                )
            from jax.sharding import NamedSharding, PartitionSpec

            # Matching 1-D placement for per-slot index vectors.
            self._sharding_1d = NamedSharding(
                sharding.mesh, PartitionSpec(axis)
            )
        else:
            self._n_shards = 1
            self._sharding_1d = None
        # Writes accumulate here and flush as ONE device batch at the next
        # query (or at PENDING_LIMIT): a stream of N single-key applies
        # costs one restructure, not N — the amortization a per-write
        # caller (the mirror's remote-apply path) depends on.
        self._pending: dict[bytes, Optional[bytes]] = {}
        self.full_rebuilds = 0
        self.incremental_batches = 0
        self.structural_batches = 0

    # ------------------------------------------------------------ loading
    @classmethod
    def from_items(
        cls, items: Iterable[tuple[bytes, bytes]], sharding=None
    ) -> "DeviceMerkleState":
        st = cls(sharding=sharding)
        dedup = dict(items)
        if dedup:
            ordered = sorted(dedup.items())
            st._initial_build(
                np.array([k for k, _ in ordered], dtype=object),
                [v for _, v in ordered],
            )
        return st

    def __len__(self) -> int:
        self._flush()
        return len(self._keys)

    def leaf_count(self) -> int:
        """Built leaf count WITHOUT flushing staged changes — the gauge
        path must never trigger device work."""
        return len(self._keys)

    # ------------------------------------------------------------ lookups
    def _find(self, key: bytes) -> int:
        """Position of key in the sorted array, or -1."""
        return self._index.get(key, -1)

    def _positions(self, keys: Sequence[bytes]) -> np.ndarray:
        """Sorted-array positions for keys known to be present."""
        idx = self._index
        return np.fromiter(
            (idx[k] for k in keys), dtype=np.int32, count=len(keys)
        )

    def _set_keys(self, keys_arr: np.ndarray) -> None:
        self._keys = keys_arr
        self._index = {k: i for i, k in enumerate(keys_arr)}

    # ------------------------------------------------------------ updates
    def apply(self, changes: Sequence[tuple[bytes, Optional[bytes]]]) -> None:
        """Stage (key, value|None-for-delete) changes; last write per key
        wins. Device work is deferred to the next flush (the mirror's pump
        cycle, or the next exact query) so bursts of single-key applies
        amortize into one batch."""
        for k, v in changes:
            self._pending[k] = v
        if len(self._pending) >= self.PENDING_LIMIT:
            self._flush()

    def pending_count(self) -> int:
        """Staged-but-undispatched changes (no device work to read it)."""
        return len(self._pending)

    def flush_pending(self) -> None:
        """Dispatch every staged change to the device now — the pump's
        drain step. Idempotent when nothing is staged."""
        self._flush()

    def _flush(self) -> None:
        if not self._pending:
            return
        pending, self._pending = self._pending, {}
        try:
            self._flush_batch(pending)
        except BaseException:
            # A failed dispatch must not silently drop the batch: the tree
            # is unchanged (the dispatch seams assign levels atomically on
            # success), so restoring the staged changes keeps the state
            # consistent for a retry — or for the degradation ladder's
            # rebuild at a lower rung. Entries staged by a racing caller
            # between the swap and here win over the restored batch.
            merged = dict(pending)
            merged.update(self._pending)
            self._pending = merged
            raise

    def _flush_batch(self, pending: dict[bytes, Optional[bytes]]) -> None:
        # One membership pass (O(1) dict probes) classifies the whole batch.
        keys = sorted(pending)
        idx = self._index
        deletes = [k for k in keys if k in idx and pending[k] is None]
        inserts = [k for k in keys if k not in idx and pending[k] is not None]
        upserts = {k: v for k, v in pending.items() if v is not None}

        if not deletes and not inserts:
            updates = sorted(upserts.items())
            if updates and self._levels is not None:
                self._update_in_place(updates)
            return
        self._restructure(deletes, upserts, inserts)

    def _update_in_place(self, items: list[tuple[bytes, bytes]]) -> None:
        from merklekv_tpu.merkle.packing import pack_leaves

        k = len(items)
        kb = _bucket(k)
        idx = np.empty(kb, np.int32)
        idx[:k] = self._positions([key for key, _ in items])
        idx[k:] = idx[0]  # pad with a duplicate of a real entry
        packed = pack_leaves([key for key, _ in items], [v for _, v in items])
        # Pad rows by duplicating row 0 (same digest as idx[0] — duplicate
        # scatters write identical values). The block axis stays EXACT (one
        # compile per distinct max_blocks — bounded by value sizes in
        # practice): rounding it up doubles the host->device transfer,
        # which is the sustained-update bottleneck on a tunneled backend.
        nblk = packed.max_blocks
        blocks = np.zeros((kb, nblk, 16), np.uint32)
        blocks[:k, : packed.max_blocks] = packed.blocks
        nblocks = np.empty(kb, np.int32)
        nblocks[:k] = packed.nblocks
        if kb > k:
            blocks[k:] = blocks[0]
            nblocks[k:] = nblocks[0]
        # Device-plane attribution (batch size + host->device transfer
        # bytes): counters + a DISPATCH-latency histogram, no per-batch log
        # line — a sustained drain flushes many times per second and span()
        # would turn the log into the hot path. JAX dispatch is async, so
        # the histogram measures trace+enqueue cost (queue-pressure
        # signal), NOT on-device execution — forcing completion per batch
        # (a host fetch) would serialize the very pipelining the drain
        # depends on; end-to-end device time shows up in the spans that
        # already force a root read (mirror warm, storage snapshot stamp).
        import time as _time

        t0 = _time.perf_counter()
        fn = _scatter_hash_fn(self._capacity, kb, nblk, use_pallas())
        self._levels = get_guard().run(
            self._label("scatter"),
            lambda: fn(
                self._levels, jnp.asarray(idx), jnp.asarray(blocks),
                jnp.asarray(nblocks),
            ),
        )
        self.incremental_batches += 1
        m = get_metrics()
        m.inc("device.scatter_keys", k)
        m.inc("device.scatter_bytes",
              int(blocks.nbytes + idx.nbytes + nblocks.nbytes))
        m.observe("device.scatter_dispatch", _time.perf_counter() - t0)

    # ------------------------------------------------------------ structure
    def _capacity_for(self, n: int) -> int:
        # Sharded trees keep C a multiple of the mesh axis so the leaf
        # dimension always divides evenly across devices.
        return max(_next_pow2(n), self._n_shards)

    def _put(self, arr: np.ndarray, one_d: bool = False) -> jax.Array:
        """Host array -> device, honoring the keyspace sharding if set."""
        if self._sharding is None:
            return jnp.asarray(arr)
        return jax.device_put(
            arr, self._sharding_1d if one_d else self._sharding
        )

    def _initial_build(self, keys_arr: np.ndarray, values: list) -> None:
        from merklekv_tpu.utils.tracing import span

        n = len(keys_arr)
        c = self._capacity_for(n)
        # Full rebuilds are rare (warm-up, empty->non-empty restructure) and
        # expensive — a span records batch size and transfer bytes per the
        # device-plane attribution the MTU throughput analysis needs.
        with span("device.rebuild", keys=n, capacity=c) as rec:
            # leaf_digests is itself a device dispatch (jitted leaf
            # hashing) — guard it like every other program call, or a
            # wedged backend hangs the warm thread with no deadline.
            digests = get_guard().run(
                self._label("build"),
                lambda: np.asarray(leaf_digests(list(keys_arr), values)),
            )
            padded = np.zeros((c, 8), np.uint32)
            padded[:n] = digests
            rec["bytes"] = int(padded.nbytes)
            self._levels = self._dispatch_build(padded)
        self._set_keys(keys_arr)
        self._capacity = c
        self.full_rebuilds += 1

    def _restructure(
        self,
        deletes: list[bytes],
        upserts: dict[bytes, Optional[bytes]],
        inserts: list[bytes],
    ) -> None:
        old = self._keys
        n_old = len(old)

        # Host plan: pure index arithmetic, no hashing of survivors.
        del_pos = self._positions(deletes)
        survivors = np.delete(old, del_pos) if len(del_pos) else old
        surv_src = (
            np.delete(np.arange(n_old, dtype=np.int32), del_pos)
            if len(del_pos)
            else np.arange(n_old, dtype=np.int32)
        )
        ins_keys = np.array(sorted(inserts), dtype=object)
        if len(ins_keys):
            ins_at = np.searchsorted(survivors, ins_keys).astype(np.int64)
            new_keys = np.insert(survivors, ins_at, ins_keys)
            gather = np.insert(surv_src, ins_at, np.int32(-1))
        else:
            new_keys = survivors
            gather = surv_src
        n_new = len(new_keys)
        if n_new == 0:
            self._set_keys(np.empty(0, dtype=object))
            self._levels = None
            self._capacity = 0
            return
        if self._levels is None:
            # Empty -> non-empty: everything is fresh; all values are in
            # this batch by construction.
            self._initial_build(
                new_keys, [upserts[k] for k in new_keys]
            )
            return

        c_new = self._capacity_for(n_new)
        gather_padded = np.full(c_new, -1, np.int32)
        gather_padded[:n_new] = gather

        # Fresh digests: every upsert (update of a survivor or insert).
        fresh_items = sorted(upserts.items())
        k = len(fresh_items)
        kb = _bucket(k) if k else 0
        if k:
            fresh_keys = np.array([key for key, _ in fresh_items],
                                  dtype=object)
            fresh_pos = np.empty(kb, np.int32)
            fresh_pos[:k] = np.searchsorted(new_keys, fresh_keys)
            fresh_pos[k:] = fresh_pos[0]

            # Guarded like the build path: the fresh-digest leaf hashing
            # is a device dispatch and must not be able to wedge the
            # pump thread outside the deadline.
            def hash_fresh():
                digests = leaf_digests([key for key, _ in fresh_items],
                                       [v for _, v in fresh_items])
                return jnp.concatenate(
                    [digests, jnp.broadcast_to(digests[0], (kb - k, 8))],
                    axis=0,
                ) if kb > k else digests

            fresh = get_guard().run(self._label("restructure"), hash_fresh)
        else:
            fresh_pos = np.zeros(0, np.int32)
            fresh = jnp.zeros((0, 8), jnp.uint32)

        import time as _time

        t0 = _time.perf_counter()
        self._levels = self._dispatch_restructure(
            gather_padded, fresh_pos, fresh, kb, c_new
        )
        self._set_keys(new_keys)
        self._capacity = c_new
        self.structural_batches += 1
        m = get_metrics()
        m.inc("device.restructure_keys", k)
        m.inc("device.restructure_bytes",
              int(gather_padded.nbytes + fresh_pos.nbytes + k * 32))
        # Dispatch latency, same async-enqueue semantics as scatter above.
        m.observe("device.restructure_dispatch", _time.perf_counter() - t0)

    # ------------------------------------------------- device dispatch seam
    # The host planning above (classification, permutation index arithmetic,
    # packing) is backend-agnostic; only these two hooks touch a compiled
    # device program. ShardedDeviceMerkleState (parallel/sharded_state.py)
    # overrides them with explicit shard_map SPMD programs.
    def _dispatch_build(self, padded: np.ndarray) -> tuple:
        """Capacity-padded [C, 8] leaf digests -> every padded level."""
        fn = _build_fn(len(padded), use_pallas())
        return get_guard().run(
            self._label("build"), lambda: fn(self._put(padded))
        )

    def _dispatch_restructure(
        self,
        gather_padded: np.ndarray,
        fresh_pos: np.ndarray,
        fresh: jax.Array,
        kb: int,
        c_new: int,
    ) -> tuple:
        """Gather survivors into shifted slots + scatter fresh digests +
        full re-reduction (``self._capacity`` still holds the OLD C)."""
        fn = _restructure_fn(self._capacity, c_new, kb, use_pallas())
        return get_guard().run(
            self._label("restructure"),
            lambda: fn(
                self._levels[0], self._put(gather_padded, one_d=True),
                jnp.asarray(fresh_pos), fresh,
            ),
        )

    # ------------------------------------------------------------ queries
    def root_hash(self, flush: bool = True) -> Optional[bytes]:
        """Reference-tree root. ``flush=False`` serves the tree AS BUILT —
        staged changes stay staged — so a bounded-staleness reader (the
        mirror's published snapshot) never triggers device work beyond the
        root walk itself."""
        if flush:
            self._flush()
        if not len(self._keys) or self._levels is None:
            return None
        fn = _ref_root_fn(self._capacity)
        return get_guard().run(
            self._label("root"),
            lambda: digest_to_bytes(
                np.asarray(fn(self._levels, jnp.int32(len(self._keys))))
            ),
        )

    def root_hex(self, flush: bool = True) -> str:
        r = self.root_hash(flush=flush)
        return r.hex() if r is not None else "0" * 64

    def leaf_digest(self, key: bytes) -> Optional[bytes]:
        self._flush()
        i = self._find(key)
        if i < 0 or self._levels is None:
            return None
        return get_guard().run(
            self._label("levels"),
            lambda: digest_to_bytes(np.asarray(self._levels[0][i])),
        )

    # ------------------------------------------- reference-level serving
    @staticmethod
    def ref_level_sizes(n: int) -> list[int]:
        """Reference (odd-promotion) tree level sizes for ``n`` leaves
        (shared size law — see merkle/cpu.py)."""
        from merklekv_tpu.merkle.cpu import ref_level_sizes

        return ref_level_sizes(n)

    def _promoted_last(self, level: int) -> bytes:
        """The reference tree's LAST node at ``level``, recovered from the
        padded levels by the promotion-chain walk (same recurrence as
        ``_ref_root_fn``, stopped at ``level``): the padded tree hashes
        zero sentinels into its right spine, so only this one position per
        level can differ from the reference tree."""
        n = len(self._keys)
        last = digest_to_bytes(np.asarray(self._levels[0][n - 1]))
        m = n
        for lvl in range(1, level + 1):
            if m <= 1:
                break
            if m % 2 == 0:
                # Even level size: the reference's next last node combines
                # position m-2 (identical in the padded tree — only the
                # LAST position per level can differ) with the carried
                # correction. Odd sizes promote the tail unchanged.
                from merklekv_tpu.merkle.encoding import node_hash

                prev = digest_to_bytes(
                    np.asarray(self._levels[lvl - 1][m - 2])
                )
                last = node_hash(prev, last)
            m = (m + 1) // 2
        return last

    def level_nodes(
        self, level: int, lo: int, hi: int, flush: bool = True
    ) -> tuple[list[tuple[int, bytes]], int]:
        """Reference-tree digests at ``level`` for indices ``[lo, hi)``
        (clamped to the level's size), plus the live leaf count — the
        device-side answer to the TREELEVEL wire verb. One batched device
        gather serves the whole slice; the only host hashing is the O(level)
        promotion-chain correction when the slice touches the level's last
        node. Digests are bit-identical to the reference tree (and hence to
        the native server's host fallback). ``flush=False`` serves the tree
        as built (the published-snapshot read path)."""
        if flush:
            self._flush()
        n = len(self._keys)
        if n == 0 or self._levels is None:
            return [], 0
        sizes = self.ref_level_sizes(n)
        if level >= len(sizes):
            return [], n
        m = sizes[level]
        lo = max(0, min(lo, m))
        hi = max(lo, min(hi, m))
        if lo == hi:
            return [], n

        # One device gather for the whole slice (the padded level's prefix
        # matches the reference level everywhere but the last position);
        # guarded so a TREELEVEL serve against a wedged device fails at the
        # dispatch deadline (and the native fallback answers) instead of
        # parking the query thread forever.
        def read() -> list[tuple[int, bytes]]:
            block = np.asarray(self._levels[level][lo:hi])
            digs = digests_to_bytes(block)
            rows = [(lo + i, d) for i, d in enumerate(digs)]
            if hi == m and level > 0:
                rows[-1] = (m - 1, self._promoted_last(level))
            return rows

        return get_guard().run(self._label("levels"), read), n

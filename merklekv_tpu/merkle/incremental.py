"""Device-resident Merkle state with incremental O(k log C) updates.

The reference rebuilds its whole tree on every mutation
(/root/reference/src/store/merkle.rs:52-56) and never updates the tree from
replication events (TODO at replication.rs:312-316). Here the tree LIVES in
device HBM and change-event batches are applied as one XLA program:

  1. hash the k changed leaves (batched SHA-256),
  2. scatter them into the capacity-padded leaf level,
  3. re-reduce only the touched parent paths — k node hashes per level,
     log2(C) levels.

Representation: a FULL binary tree at capacity C = 2^d (slots >= n hold a
zero sentinel). The reference tree pairs only live nodes and promotes odd
tails, so its levels differ from the padded tree's — but only on the right
spine: by induction, reference level l equals padded level l at every
position except the last. ``_ref_root`` therefore recovers the bit-exact
reference root in one O(log C) walk that carries the corrected last node
("promotion chain") and reads one padded node per level.

Sorted-order maintenance is host-side: value updates keep positions stable
(O(k log C) device work); key inserts/deletes shift the dense sorted layout,
so they mark the state dirty and the next root triggers a full batched
rebuild — which the Pallas path does at ~10^7+ leaves/s, so the rebuild
amortizes across any realistic insert rate.
"""

from __future__ import annotations

from bisect import bisect_left, insort
from functools import lru_cache, partial
from typing import Iterable, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from merklekv_tpu.merkle.jax_engine import leaf_digests
from merklekv_tpu.merkle.packing import pack_leaves
from merklekv_tpu.ops.sha256 import digest_to_bytes, sha256_node_pairs

__all__ = ["DeviceMerkleState"]


def _next_pow2(n: int) -> int:
    return 1 << max(1, (n - 1).bit_length())


def _bucket(k: int) -> int:
    """Round a batch size up so one compiled program serves many sizes."""
    return _next_pow2(max(k, 16))


@lru_cache(maxsize=None)
def _scatter_update_fn(capacity: int, kb: int):
    """Compiled scatter + path re-reduction for (capacity, batch bucket)."""

    @jax.jit
    def go(levels: tuple, idx: jax.Array, new_leaves: jax.Array):
        # idx [kb] int32 (padded entries duplicate a real entry with the
        # identical leaf value, so duplicate scatters are benign);
        # new_leaves [kb, 8] uint32.
        out = [levels[0].at[idx].set(new_leaves)]
        cur_idx = idx
        for lvl in range(1, len(levels)):
            cur_idx = cur_idx // 2
            left = out[-1][2 * cur_idx]
            right = out[-1][2 * cur_idx + 1]
            parents = sha256_node_pairs(left, right)
            out.append(levels[lvl].at[cur_idx].set(parents))
        return tuple(out)

    return go


@lru_cache(maxsize=None)
def _ref_root_fn(capacity: int):
    """Compiled promotion-chain walk: padded levels + live count n -> the
    reference odd-promotion root over the first n leaves."""

    @jax.jit
    def go(levels: tuple, n: jax.Array):
        m = jnp.asarray(n, jnp.int32)
        last = jax.lax.dynamic_index_in_dim(
            levels[0], jnp.maximum(m - 1, 0), axis=0, keepdims=False
        )
        for lvl in range(1, len(levels)):
            odd = (m % 2) == 1
            # Even m: reference's next last = H(level[m-2], last). Position
            # m-2 of the reference level equals the padded level (only the
            # last position can differ).
            prev = jax.lax.dynamic_index_in_dim(
                levels[lvl - 1], jnp.maximum(m - 2, 0), axis=0, keepdims=False
            )
            combined = sha256_node_pairs(prev[None], last[None])[0]
            # Odd m: the tail is promoted unchanged. m == 1: stay at root.
            new_last = jnp.where(odd, last, combined)
            last = jnp.where(m <= 1, last, new_last)
            m = jnp.where(m <= 1, m, (m + 1) // 2)
        return last

    return go


class DeviceMerkleState:
    """Sorted keyspace + device-resident padded tree levels.

    Host side owns the sorted key list and (key -> value bytes) map (the
    authoritative store is the native engine; this mirrors only what the
    tree needs). Device side owns ``levels``: levels[0] is [C, 8] leaf
    digests, levels[d] is [1, 8].
    """

    def __init__(self) -> None:
        self._keys: list[bytes] = []
        self._pos: dict[bytes, int] = {}
        self._values: dict[bytes, bytes] = {}
        self._levels: Optional[tuple[jax.Array, ...]] = None
        self._capacity = 0
        self._dirty = True  # structure changed; next root does a full build
        self.full_rebuilds = 0
        self.incremental_batches = 0

    # ------------------------------------------------------------ loading
    @classmethod
    def from_items(cls, items: Iterable[tuple[bytes, bytes]]) -> "DeviceMerkleState":
        st = cls()
        for k, v in items:
            st._values[k] = v
        st._keys = sorted(st._values)
        st._pos = {k: i for i, k in enumerate(st._keys)}
        st._dirty = True
        return st

    def __len__(self) -> int:
        return len(self._keys)

    # ------------------------------------------------------------ updates
    def apply(self, changes: Sequence[tuple[bytes, Optional[bytes]]]) -> None:
        """Apply (key, value|None-for-delete) changes.

        Value updates of existing keys go through the incremental device
        path; inserts and deletes change the sorted layout and mark the
        state for a full rebuild at the next root query.
        """
        in_place: dict[bytes, bytes] = {}
        for k, v in changes:
            if v is None:
                if k in self._values:
                    del self._values[k]
                    self._dirty = True
                    in_place.pop(k, None)
            elif k in self._values:
                self._values[k] = v
                in_place[k] = v
            else:
                self._values[k] = v
                self._dirty = True
        if self._dirty:
            # Layout shifted; incremental positions are meaningless.
            return
        if in_place and self._levels is not None:
            self._incremental_update(sorted(in_place.items()))

    def _incremental_update(self, items: list[tuple[bytes, bytes]]) -> None:
        k = len(items)
        kb = _bucket(k)
        idx = np.empty(kb, np.int32)
        for i, (key, _) in enumerate(items):
            idx[i] = self._pos[key]
        idx[k:] = idx[0]  # pad with a duplicate of a real entry
        digests = leaf_digests([key for key, _ in items],
                               [v for _, v in items])
        new_leaves = jnp.concatenate(
            [digests, jnp.broadcast_to(digests[0], (kb - k, 8))], axis=0
        ) if kb > k else digests
        fn = _scatter_update_fn(self._capacity, kb)
        self._levels = fn(self._levels, jnp.asarray(idx), new_leaves)
        self.incremental_batches += 1

    # ------------------------------------------------------------ rebuild
    def _full_rebuild(self) -> None:
        self._keys = sorted(self._values)
        self._pos = {k: i for i, k in enumerate(self._keys)}
        n = len(self._keys)
        if n == 0:
            self._levels = None
            self._capacity = 0
            self._dirty = False
            return
        c = _next_pow2(n)
        digests = leaf_digests(self._keys, [self._values[k] for k in self._keys])
        leaves = jnp.zeros((c, 8), jnp.uint32).at[:n].set(digests)
        levels = [leaves]
        cur = leaves
        while cur.shape[0] > 1:
            cur = sha256_node_pairs(cur[0::2], cur[1::2])
            levels.append(cur)
        self._levels = tuple(levels)
        self._capacity = c
        self._dirty = False
        self.full_rebuilds += 1

    # ------------------------------------------------------------ queries
    def root_hash(self) -> Optional[bytes]:
        if self._dirty:
            self._full_rebuild()
        if not self._keys:
            return None
        root = _ref_root_fn(self._capacity)(
            self._levels, jnp.int32(len(self._keys))
        )
        return digest_to_bytes(np.asarray(root))

    def root_hex(self) -> str:
        r = self.root_hash()
        return r.hex() if r is not None else "0" * 64

    def leaf_digest(self, key: bytes) -> Optional[bytes]:
        if self._dirty:
            self._full_rebuild()
        i = self._pos.get(key)
        if i is None or self._levels is None:
            return None
        return digest_to_bytes(np.asarray(self._levels[0][i]))

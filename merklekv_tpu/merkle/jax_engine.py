"""TPU Merkle engine: whole-keyspace hashing and tree build as XLA programs.

Replaces the reference's per-insert full rebuild
(/root/reference/src/store/merkle.rs:52-56,73-121 — O(n^2 log n) hashing per
snapshot) with:

  1. one batched SHA-256 program over every leaf (``sha256_blocks``), and
  2. a log-depth bottom-up reduction (``build_levels_device``) whose per-level
     shapes are static under ``jit``, with the reference's odd-node promotion
     rule reproduced exactly so roots are bit-identical to the CPU core.

Two build paths:
- **static** (`tree_root`, `build_levels_device`): shapes specialized on the
  exact leaf count N. Best throughput; used by the bench and by snapshot-style
  rebuilds. One compile per distinct N.
- **capacity** (`tree_root_capacity`): one compiled program per capacity C
  (power-of-two bucket) valid for any live count n <= C, for serving paths
  where n changes per batch and recompiles are unacceptable. The dynamic level
  sizes are carried as traced scalars; promotion is a dynamic scatter.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from merklekv_tpu.merkle.packing import pack_leaves
from merklekv_tpu.ops.sha256 import (
    digest_to_bytes,
    digests_to_bytes,
    sha256_blocks,
    sha256_node_pairs,
)

__all__ = [
    "leaf_digests",
    "build_levels_device",
    "tree_root",
    "tree_root_capacity",
    "anti_entropy_forward",
    "JaxMerkleTree",
]


def anti_entropy_forward(blocks, nblocks, digests, present):
    """The canonical single-chip data-plane step: hash every leaf, reduce to
    the tree root, and compute R-replica divergence — one jittable program.

    Shared by ``bench.py``, ``__graft_entry__.entry()``, and the sync
    manager so they all measure/compile the same forward program.

    blocks [N, B, 16] u32, nblocks [N] i32, digests [R, N, 8] u32,
    present [R, N] bool -> (root [8] u32, masks [R, N] bool, counts [R] i32).
    """
    from merklekv_tpu.merkle.diff import divergence_masks

    leaves = sha256_blocks(blocks, nblocks)
    root = build_levels_device(leaves)[-1][0]
    masks = divergence_masks(digests, present)
    counts = jnp.sum(masks, axis=1, dtype=jnp.int32)
    return root, masks, counts


def anti_entropy_forward_pallas(blocks, nblocks, digests, present):
    """Same program as :func:`anti_entropy_forward` with the SHA-256 work in
    Pallas kernels (rounds in VMEM). TPU-only; bit-identical outputs."""
    from merklekv_tpu.merkle.diff import divergence_masks
    from merklekv_tpu.ops.sha256_pallas import (
        leaf_digests_pallas,
        tree_root_pallas,
    )

    leaves = leaf_digests_pallas(blocks, nblocks)
    root = tree_root_pallas(leaves)
    masks = divergence_masks(digests, present)
    counts = jnp.sum(masks, axis=1, dtype=jnp.int32)
    return root, masks, counts


# ------------------------------------------------------------ leaf hashing

@jax.jit
def _leaf_digests_jit(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    return sha256_blocks(blocks, nblocks)


def leaf_digests(keys: Sequence[bytes], values: Sequence[bytes]) -> jax.Array:
    """[N, 8] uint32 leaf digests for N (key, value) pairs, hashed on device.

    Backend-dispatched: Pallas kernels on TPU (ops/dispatch.py), the scan
    formulation elsewhere — so every caller (mirror warm build, incremental
    tree, sync leaf maps) gets the tuned production path on the chip."""
    from merklekv_tpu.ops.dispatch import hash_blocks, use_pallas

    packed = pack_leaves(list(keys), list(values))
    if packed.n == 0:
        return jnp.zeros((0, 8), jnp.uint32)
    if use_pallas():
        return hash_blocks(packed.blocks, packed.nblocks)
    return _leaf_digests_jit(packed.blocks, packed.nblocks)


# ------------------------------------------------------------ static build

def build_levels_device(leaves: jax.Array) -> list[jax.Array]:
    """All tree levels, bottom-up, as device arrays. leaves: [N, 8] uint32.

    Trace-time Python loop — level sizes are static for a given N, so the
    whole tree is one straight-line XLA program of ~log2(N) batched hash
    calls. Odd trailing nodes are promoted (copied up) exactly like the
    reference (merkle.rs:111-114).
    """
    levels = [leaves]
    cur = leaves
    while cur.shape[0] > 1:
        m = cur.shape[0]
        pairs = m // 2
        nxt = sha256_node_pairs(cur[0 : 2 * pairs : 2], cur[1 : 2 * pairs : 2])
        if m % 2:
            nxt = jnp.concatenate([nxt, cur[-1:]], axis=0)
        levels.append(nxt)
        cur = nxt
    return levels


@jax.jit
def tree_root(leaves: jax.Array) -> jax.Array:
    """[8] uint32 root digest from [N, 8] leaf digests (N >= 1, static)."""
    return build_levels_device(leaves)[-1][0]


# jit-of-list-of-levels: one compile per leaf count N, then fast replays.
build_levels_jit = jax.jit(build_levels_device)


# ---------------------------------------------------------- capacity build

@jax.jit
def tree_root_capacity(leaves: jax.Array, n: jax.Array) -> jax.Array:
    """Root over the first ``n`` of C leaf slots; one compile per capacity C.

    leaves: [C, 8] uint32 with C a power of two (slots >= n are ignored);
    n: scalar int32, 1 <= n <= C. Produces the root of the odd-promotion tree
    of exactly n leaves — bit-identical to ``tree_root(leaves[:n])`` — so a
    serving path can reuse one compiled program for any live count within a
    capacity bucket.

    With C a power of two, every dynamic level size m <= C_level keeps the
    promotion slot m//2 strictly inside the next level's C_level/2 slots, so
    the dynamic scatter below never aliases a live pair slot.
    """
    c = leaves.shape[0]
    if c & (c - 1):
        raise ValueError(f"capacity must be a power of two, got {c}")
    cur = leaves
    m = jnp.asarray(n, jnp.int32)
    while cur.shape[0] > 1:
        half = cur.shape[0] // 2
        hashed = sha256_node_pairs(cur[0 : 2 * half : 2], cur[1 : 2 * half : 2])
        # Promote a dynamic odd tail: slot m//2 of the next level gets cur[m-1].
        odd = (m % 2) == 1
        last = jax.lax.dynamic_index_in_dim(
            cur, jnp.maximum(m - 1, 0), axis=0, keepdims=False
        )
        is_tgt = (jnp.arange(half, dtype=jnp.int32) == m // 2)[:, None] & odd
        promoted = jnp.where(is_tgt, last[None, :], hashed)
        # Levels past the top (m == 1) pass the root through unchanged.
        done = m <= 1
        cur = jnp.where(done, cur[:half], promoted)
        m = jnp.where(done, m, (m + 1) // 2)
    return cur[0]


# ------------------------------------------------------------ engine class

class JaxMerkleTree:
    """Same surface as the CPU ``MerkleTree`` with device-batched hashing.

    Mutations only touch a host-side (key -> (key_bytes, value_bytes)) map;
    ``root_hash``/``levels`` trigger one batched device rebuild. Used by the
    golden parity suite and as the serving engine's snapshot path.
    """

    def __init__(self) -> None:
        self._items: dict[bytes, bytes] = {}
        self._levels_np: Optional[list[np.ndarray]] = None

    # -- mutation ----------------------------------------------------------
    def insert(self, key: str | bytes, value: str | bytes) -> None:
        self._items[_b(key)] = _b(value)
        self._levels_np = None

    def remove(self, key: str | bytes) -> None:
        if self._items.pop(_b(key), None) is not None:
            self._levels_np = None

    def clear(self) -> None:
        if self._items:
            self._items.clear()
            self._levels_np = None

    def __len__(self) -> int:
        return len(self._items)

    # -- build -------------------------------------------------------------
    def _rebuild(self) -> None:
        if self._levels_np is not None:
            return
        if not self._items:
            self._levels_np = []
            return
        ordered = sorted(self._items.items())
        keys = [k for k, _ in ordered]
        values = [v for _, v in ordered]
        leaves = leaf_digests(keys, values)
        levels = build_levels_jit(leaves)
        self._levels_np = [np.asarray(lv) for lv in levels]

    @property
    def levels(self) -> list[np.ndarray]:
        self._rebuild()
        assert self._levels_np is not None
        return self._levels_np

    def root_hash(self) -> Optional[bytes]:
        self._rebuild()
        if not self._levels_np:
            return None
        return digest_to_bytes(self._levels_np[-1][0])

    def root_hex(self) -> str:
        r = self.root_hash()
        return r.hex() if r is not None else "0" * 64

    def inorder_keys(self) -> list[str]:
        return [k.decode("utf-8", "surrogateescape") for k in sorted(self._items)]

    def leaves(self) -> list[tuple[str, bytes]]:
        self._rebuild()
        assert self._levels_np is not None
        if not self._levels_np:
            return []
        hashes = digests_to_bytes(self._levels_np[0])
        return [
            (k.decode("utf-8", "surrogateescape"), h)
            for k, h in zip(sorted(self._items), hashes)
        ]


def _b(s: str | bytes) -> bytes:
    return s.encode("utf-8") if isinstance(s, str) else s

"""Vectorized host-side packing of (key, value) leaves into SHA-256 blocks.

Variable-length keys/values must become fixed-shape tensors before the device
sees them. This module performs the length-prefixed leaf encoding
(``merklekv_tpu/merkle/encoding.py``; reference
/root/reference/src/store/merkle.rs:7-16) *and* the FIPS 180-4 padding in
fully vectorized numpy — no per-key Python loop — producing the
``[N, B, 16] uint32`` block tensor consumed by
:func:`merklekv_tpu.ops.sha256.sha256_blocks`.

Packing 10M small leaves costs a few hundred ms on one host core; the
scatters are all flat-index writes on one contiguous buffer.
"""

from __future__ import annotations

import numpy as np

__all__ = ["pack_leaves", "PackedLeaves"]


class PackedLeaves:
    """Fixed-shape SHA-256 input tensors for a batch of leaves.

    Attributes:
      blocks:  [N, B, 16] uint32 — padded message blocks, big-endian words.
      nblocks: [N] int32 — valid block count per leaf (>= 1).
    """

    __slots__ = ("blocks", "nblocks")

    def __init__(self, blocks: np.ndarray, nblocks: np.ndarray) -> None:
        self.blocks = blocks
        self.nblocks = nblocks

    @property
    def n(self) -> int:
        return self.blocks.shape[0]

    @property
    def max_blocks(self) -> int:
        return self.blocks.shape[1]


def _lengths(items: list[bytes]) -> np.ndarray:
    return np.fromiter((len(b) for b in items), dtype=np.int64, count=len(items))


def pack_leaves(
    keys: list[bytes],
    values: list[bytes],
    min_blocks: int = 1,
) -> PackedLeaves:
    """Pack N (key, value) leaves into padded SHA-256 block tensors.

    Message layout per leaf (then standard SHA-256 padding):
      u32_be(len(key)) || key || u32_be(len(value)) || value

    ``min_blocks`` lets callers force a common block-axis size across batches
    (e.g. to reuse one compiled program).
    """
    n = len(keys)
    if n != len(values):
        raise ValueError("keys and values must have equal length")
    if n == 0:
        return PackedLeaves(
            np.zeros((0, max(min_blocks, 1), 16), np.uint32),
            np.zeros((0,), np.int32),
        )

    klens = _lengths(keys)
    vlens = _lengths(values)
    mlens = 8 + klens + vlens
    nblocks = (mlens + 9 + 63) // 64  # 0x80 marker + 8-byte bit length
    max_b = int(max(nblocks.max(), min_blocks))
    row = max_b * 64

    out = np.zeros(n * row, dtype=np.uint8)
    row_starts = np.arange(n, dtype=np.int64) * row

    # Key length prefix (offset 0..4 of each row).
    kl_be = klens.astype(">u4").view(np.uint8).reshape(n, 4)
    for c in range(4):
        out[row_starts + c] = kl_be[:, c]

    # Key bytes at offset 4.
    total_k = int(klens.sum())
    if total_k:
        kall = np.frombuffer(b"".join(keys), dtype=np.uint8)
        kstarts = np.concatenate(([0], np.cumsum(klens)[:-1]))
        tgt = np.repeat(row_starts + 4, klens) + (
            np.arange(total_k, dtype=np.int64) - np.repeat(kstarts, klens)
        )
        out[tgt] = kall

    # Value length prefix at offset 4 + klen.
    vl_be = vlens.astype(">u4").view(np.uint8).reshape(n, 4)
    for c in range(4):
        out[row_starts + 4 + klens + c] = vl_be[:, c]

    # Value bytes at offset 8 + klen.
    total_v = int(vlens.sum())
    if total_v:
        vall = np.frombuffer(b"".join(values), dtype=np.uint8)
        vstarts = np.concatenate(([0], np.cumsum(vlens)[:-1]))
        tgt = np.repeat(row_starts + 8 + klens, vlens) + (
            np.arange(total_v, dtype=np.int64) - np.repeat(vstarts, vlens)
        )
        out[tgt] = vall

    # 0x80 end-of-message marker.
    out[row_starts + mlens] = 0x80

    # 64-bit big-endian bit length in the last 8 bytes of the final block.
    bl_be = (mlens * 8).astype(">u8").view(np.uint8).reshape(n, 8)
    tail = row_starts + nblocks * 64 - 8
    for c in range(8):
        out[tail + c] = bl_be[:, c]

    words = (
        out.reshape(n, row).view(">u4").astype(np.uint32).reshape(n, max_b, 16)
    )
    return PackedLeaves(words, nblocks.astype(np.int32))

"""Event-driven cache invalidation: the router rides the replication bus.

The router subscribes (read-only) to the same per-partition replication
topics the replica groups already publish on —
``<prefix>/p<pid>/events`` — and applies each envelope's key events to
the read cache the moment they arrive. No new wire surface: the envelope
(change_event.encode_batch_cbor) already carries everything needed:

- ``events[].key`` — the exact entries to drop;
- ``hseq`` — the publisher's cumulative event HWM INCLUDING frames it
  dropped, so a jump bigger than this frame's batch proves we MISSED
  invalidations → flush the whole partition's entries (we cannot know
  which keys went stale);
- ``hts`` — publish wall-clock ns, giving the router a live
  invalidation-lag measurement (clamped at 0 for clock skew).

The undetectable residue — frames lost with no later frame from that
publisher to expose the gap (QoS-0, broker death, router link down) — is
bounded by the cache's hard ``max_age_ms``; docs/PROTOCOL.md "Router
semantics" states the resulting client-visible staleness bound.
"""

from __future__ import annotations

import re
import threading
import time

from merklekv_tpu.cluster.change_event import OpKind, decode_events_meta
from merklekv_tpu.obs.flightrec import get_recorder
from merklekv_tpu.utils.tracing import get_metrics

__all__ = ["InvalidationFeed"]

_TOPIC_RE = re.compile(r"/p(\d+)/events$")


class InvalidationFeed:
    """Subscribes a Transport to the cluster's replication topics and
    drives a LeaseCache's event-driven invalidation."""

    def __init__(self, cache, transport, topic_prefix: str) -> None:
        self._cache = cache
        self._transport = transport
        self._prefix = topic_prefix.rstrip("/")
        self._mu = threading.Lock()
        # (topic, src) -> last seen cumulative hseq; reset on epoch flips.
        self._hwm: dict[tuple[str, str], int] = {}
        self.last_lag_ms = 0.0
        self.frames = 0
        # Pin the bound method: Transport.unsubscribe matches by identity.
        self._cb = self._on_message
        transport.subscribe(self._prefix + "/", self._cb)

    def close(self) -> None:
        try:
            self._transport.unsubscribe(self._cb)
        except Exception:
            pass

    def reset(self) -> None:
        """Forget per-publisher HWMs (map epoch flip: partition ids and
        topics renumber; stale HWMs would read as giant gaps)."""
        with self._mu:
            self._hwm.clear()

    # -- feed ---------------------------------------------------------------
    def _on_message(self, topic: str, payload: bytes) -> None:
        mt = _TOPIC_RE.search(topic)
        if mt is None:
            return  # rebalance forward topics etc. — not an event stream
        pid = int(mt.group(1))
        m = get_metrics()
        try:
            events, meta = decode_events_meta(payload)
        except Exception:
            m.inc("router.inval_decode_errors")
            return
        self.frames += 1
        m.inc("router.inval_frames")
        src = str(meta.get("src", ""))
        hseq = meta.get("hseq")
        hts = meta.get("hts")
        if isinstance(hts, int) and hts > 0:
            self.last_lag_ms = max(0.0, (time.time_ns() - hts) / 1e6)
            m.observe("router.inval_lag", self.last_lag_ms / 1e3)
        gap = False
        if isinstance(hseq, int):
            hw_key = (topic, src)
            with self._mu:
                last = self._hwm.get(hw_key)
                self._hwm[hw_key] = max(hseq, last or 0)
            # First frame from a publisher sets the baseline — the cache
            # was filled only after we subscribed, so nothing before it
            # can be stale. After that, hseq - len(events) > last means
            # frames vanished between this one and the last we saw.
            gap = last is not None and (hseq - len(events)) > last
        if gap:
            flushed = self._cache.flush_partition(pid)
            m.inc("router.inval_gap_flushes")
            get_recorder().record(
                "router_inval_gap", partition=pid, flushed=flushed,
                src=src, hseq=hseq or 0,
            )
            return
        for ev in events:
            if ev.op == OpKind.TRUNCATE or not ev.key:
                # Keyspace-wide mutation (or a malformed event): drop the
                # partition's entries — precision is not recoverable.
                self._cache.flush_partition(pid)
                return
            self._cache.invalidate(ev.key)

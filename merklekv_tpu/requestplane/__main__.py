"""`python -m merklekv_tpu.requestplane` — run the pooled router."""

import sys

from merklekv_tpu.requestplane.router import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Router-side read cache with memcached-style fill leases.

The request plane's hot-key shield: a GET miss hands out ONE lease per
key, so under a thundering herd exactly one fill crosses to the owning
partition and every concurrent reader waits on the in-flight answer
instead of stampeding the backend (the memcached "lease" design the
ISSUE names). Entries are dropped three ways, in strictness order:

- **event-driven** — the invalidation feed (invalidation.py) applies the
  replication envelope's key events the moment the owning partition
  publishes a write;
- **gap flush** — a detected ``hseq`` gap (missed frames) flushes the
  whole partition's entries, because we no longer know WHICH keys went
  stale;
- **hard max-age** — every entry expires ``max_age_ms`` after its fill
  regardless, which is the documented worst-case staleness bound for the
  undetectable window (frames lost with no successor frame to expose the
  gap; QoS-0 anti-entropy residue).

Thread-safety: one lock around the table; waiter callbacks returned by
``finish_fill``/stolen leases are invoked by the CALLER outside the lock
(the router wraps each waiter in a cross-worker ``post``), so a slow
client can never hold the cache hostage.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from merklekv_tpu.utils.tracing import get_metrics

__all__ = ["LeaseCache", "MISS", "WAIT", "LEAD"]

# begin_get outcomes (identity sentinels, never equal to a cached value).
MISS = object()  # caller must fill (no cache / uncacheable)
WAIT = object()  # another fill is in flight; the waiter was enqueued
LEAD = object()  # caller holds the fill lease


class _Entry:
    __slots__ = ("value", "pid", "filled_mono", "nbytes")

    def __init__(self, value: str, pid: int, nbytes: int) -> None:
        self.value = value
        self.pid = pid
        self.filled_mono = time.monotonic()
        self.nbytes = nbytes


class _Lease:
    __slots__ = ("pid", "started_mono", "waiters")

    def __init__(self, pid: int) -> None:
        self.pid = pid
        self.started_mono = time.monotonic()
        self.waiters: list[Callable] = []


class LeaseCache:
    """LRU byte-budgeted read cache + per-key fill leases.

    A waiter is any callable ``waiter(value, age_ms, error)`` — the router
    passes closures that post the completion back to the waiting
    connection's owning worker. ``value is None`` with ``error is None``
    means a clean NOT_FOUND (valid answer, not cached).
    """

    def __init__(
        self,
        max_bytes: int,
        max_age_ms: float = 2000.0,
        lease_timeout_ms: float = 5000.0,
    ) -> None:
        if max_bytes <= 0:
            raise ValueError("LeaseCache needs a positive byte budget")
        self.max_bytes = max_bytes
        self.max_age_ms = max_age_ms
        self.lease_timeout_ms = lease_timeout_ms
        self._mu = threading.Lock()
        self._entries: OrderedDict[str, _Entry] = OrderedDict()
        self._leases: dict[str, _Lease] = {}
        self._bytes = 0

    # -- stats (gauge callbacks) --------------------------------------------
    @property
    def bytes_used(self) -> int:
        with self._mu:
            return self._bytes

    @property
    def keys(self) -> int:
        with self._mu:
            return len(self._entries)

    @property
    def leases_inflight(self) -> int:
        with self._mu:
            return len(self._leases)

    # -- read path -----------------------------------------------------------
    def begin_get(self, key: str, pid: int, waiter: Callable):
        """One atomic step of the lease protocol. Returns either
        ``(value, age_ms)`` on a hit, or one of the sentinels:

        - ``LEAD``: the caller now owns the fill lease — it MUST later
          call :meth:`finish_fill` (success, NOT_FOUND, or error), or the
          lease is only reclaimed by timeout steal.
        - ``WAIT``: a fill is already in flight; ``waiter`` was enqueued
          and will be invoked by the filler.
        """
        m = get_metrics()
        now = time.monotonic()
        with self._mu:
            e = self._entries.get(key)
            if e is not None:
                age_ms = (now - e.filled_mono) * 1000.0
                if age_ms <= self.max_age_ms:
                    self._entries.move_to_end(key)
                    m.inc("router.cache_hits")
                    return (e.value, age_ms)
                # Hard bound lapsed: the entry may be arbitrarily stale
                # (lost invalidation window) — treat as a miss.
                self._drop_locked(key, e)
                m.inc("router.cache_expired")
            lease = self._leases.get(key)
            if lease is not None:
                if (now - lease.started_mono) * 1000.0 > self.lease_timeout_ms:
                    # The old filler is presumed dead (hung upstream, lost
                    # continuation): steal the lease, keep its waiters —
                    # OUR fill will answer them.
                    lease.started_mono = now
                    lease.pid = pid
                    m.inc("router.lease_timeouts")
                    return LEAD
                lease.waiters.append(waiter)
                m.inc("router.lease_waits")
                return WAIT
            self._leases[key] = _Lease(pid)
            m.inc("router.cache_misses")
            m.inc("router.lease_grants")
            return LEAD

    def finish_fill(
        self,
        key: str,
        value: Optional[str],
        pid: int,
        error: Optional[str] = None,
    ) -> list[Callable]:
        """Complete a fill: cache the value (when clean and found), release
        the lease, and return the waiter callbacks for the CALLER to
        invoke (outside the lock) as ``waiter(value, 0.0, error)``."""
        m = get_metrics()
        with self._mu:
            lease = self._leases.pop(key, None)
            waiters = lease.waiters if lease is not None else []
            if error is None and value is not None:
                old = self._entries.pop(key, None)
                if old is not None:
                    self._bytes -= old.nbytes
                nbytes = len(key) + len(value) + 96  # entry overhead guess
                self._entries[key] = _Entry(value, pid, nbytes)
                self._bytes += nbytes
                m.inc("router.cache_fills")
                while self._bytes > self.max_bytes and self._entries:
                    k, e = self._entries.popitem(last=False)
                    self._bytes -= e.nbytes
                    m.inc("router.cache_evictions")
        if error is not None:
            get_metrics().inc("router.lease_failures")
        return waiters

    # -- invalidation --------------------------------------------------------
    def invalidate(self, key: str) -> bool:
        with self._mu:
            e = self._entries.pop(key, None)
            if e is None:
                return False
            self._bytes -= e.nbytes
        get_metrics().inc("router.cache_invalidations")
        return True

    def flush_partition(self, pid: int) -> int:
        with self._mu:
            doomed = [k for k, e in self._entries.items() if e.pid == pid]
            for k in doomed:
                self._bytes -= self._entries.pop(k).nbytes
        if doomed:
            get_metrics().inc("router.cache_invalidations", len(doomed))
        return len(doomed)

    def clear(self) -> int:
        """Drop every entry (map epoch flip: partition ids renumber, so
        per-entry pids are meaningless). Leases survive — their fills
        complete against the new map."""
        with self._mu:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
        if n:
            get_metrics().inc("router.cache_invalidations", n)
        return n

    def _drop_locked(self, key: str, e: _Entry) -> None:
        del self._entries[key]
        self._bytes -= e.nbytes

"""Production request plane: pipelined epoll router + hot-key read leases.

One address in front of a partitioned cluster, built with the same I/O
discipline as the native serving plane (fixed io-worker pool, full
client pipelining, writev-coalesced bursts), per-partition pipelined
upstream pools with concurrent fan-out, bounded MOVED/BUSY healing, and
an optional lease-guarded read cache invalidated straight off the
replication topics. See router.py for the architecture tour and
docs/PROTOCOL.md "Router semantics" for the wire contract.
"""

from merklekv_tpu.requestplane.cache import LEAD, MISS, WAIT, LeaseCache
from merklekv_tpu.requestplane.invalidation import InvalidationFeed
from merklekv_tpu.requestplane.router import RequestPlaneRouter, main

__all__ = [
    "LeaseCache",
    "InvalidationFeed",
    "RequestPlaneRouter",
    "main",
    "MISS",
    "WAIT",
    "LEAD",
]

"""Pipelined request plane: epoll worker-pool router with read leases.

This replaces the thread-per-connection thin router (cluster/router.py,
kept as the measured A/B baseline) with the same I/O discipline PR 9
gave the native server, applied to the routing hop:

- a **fixed pool of io workers**, each owning a private selector
  (epoll on Linux). A client connection is adopted by one worker for
  life — no cross-worker locking on the request path.
- **full client-side pipelining**: each readable pass drains the socket,
  parses EVERY complete frame, dispatches them in order, and answers
  with ONE writev (``sendmsg``) per burst — responses for a burst
  coalesce instead of paying a syscall each. Out-of-order upstream
  completions park in per-connection ordered slots; only the completed
  prefix ever flushes, so responses are byte-ordered exactly like the
  requests.
- **per-partition upstream pools with pipelined fan-out**: each worker
  keeps one pipelined connection per partition it talks to. Multi-key
  verbs (MGET/MSET/EXISTS, SCAN/DBSIZE) split by partition, dispatch to
  every group concurrently in the same pass, and merge when the last
  sub-answer lands — in-flight requests on one upstream are matched
  back strictly FIFO, which TCP ordering guarantees.
- **bounded MOVED/BUSY healing folded into the pooled path**: a MOVED
  answer (stale map mid-rebalance) schedules a map refresh on the
  keeper thread and a re-route on a worker timer; BUSY waits the same
  PARTITION_MOVED budget out. No worker thread ever sleeps.
- **hot-key read leases** (cache.py + invalidation.py): a GET miss
  grants one fill lease; concurrent readers wait on the in-flight
  answer. Entries invalidate event-driven off the replication topics
  and expire at the hard ``max_age`` bound; ``GET <key> vs=01`` answers
  carry a ``vs=<age_ms>:<bound_ms>`` stamp so a client can SEE the
  staleness it may be eating (docs/PROTOCOL.md "Router semantics").

Backpressure mirrors the native plane: an out-backlog past the high
watermark pauses reading that connection until the drain crosses the low
watermark; EAGAIN parks the remainder behind EPOLLOUT.

Run: ``python -m merklekv_tpu router --port 7400 --seeds host:7001 \\
    --workers 4 --cache-mb 64 --broker host --broker-port 7500 \\
    --topic-prefix mkv --metrics-port 9110``
"""

from __future__ import annotations

import heapq
import os
import selectors
import socket
import sys
import threading
import time
from collections import deque
from typing import Callable, Optional

from merklekv_tpu.client import (
    ConnectionError as ClientConnectionError,
    MerkleKVClient,
    MerkleKVError,
)
from merklekv_tpu.cluster.partmap import PartitionMap
from merklekv_tpu.cluster.retry import PARTITION_MOVED
from merklekv_tpu.obs.flightrec import get_recorder
from merklekv_tpu.requestplane.cache import LEAD, WAIT, LeaseCache
from merklekv_tpu.requestplane.invalidation import InvalidationFeed
from merklekv_tpu.utils.tracing import get_metrics

__all__ = ["RequestPlaneRouter", "main"]

MAX_LINE = 1 << 20          # request-line byte cap ([server] parity)
MAX_IOV = 64                # iovecs per writev (native plane parity)
OUT_HIGH = 8 << 20          # pause reading past this backlog
OUT_LOW = 1 << 20           # resume below this
_READ_CHUNK = 1 << 18

_R = selectors.EVENT_READ
_W = selectors.EVENT_WRITE

# Single-key verbs forwarded verbatim (verb -> takes "<key> <value>").
_SINGLE_KEY = {
    "GET": False,
    "DELETE": False,
    "DEL": False,
    "SET": True,
    "APPEND": True,
    "PREPEND": True,
}

# Bytes fast lane (the hot path): already-uppercase single-key commands
# are routed and forwarded without ever leaving bytes — no decode, no
# closure per request, raw response passthrough. Anything irregular
# (lowercase verb, vs= token, validation failure, ERROR answer, cached
# GET) drops to the str machinery below, which stays authoritative.
# verb -> shape: 0 = GET (key only), 1 = key + value, 2 = key only write.
_FAST_VERBS = {
    b"GET": 0,
    b"SET": 1,
    b"APPEND": 1,
    b"PREPEND": 1,
    b"DELETE": 2,
    b"DEL": 2,
}

# The typed retryable refusal for an upstream that died (or went
# unreachable) mid-command: BUSY is the protocol's "back off and retry"
# answer (client.ServerBusyError), which is exactly the contract — the
# replica group heals (sibling takeover, restart, new map) on the same
# timescale as an overload shed. Never a silent desync, never a generic
# error the SDKs would treat as fatal.
_BUSY_UPSTREAM_LOST = "ERROR BUSY router: upstream connection lost (retry)"


class _Moved(Exception):
    def __init__(self, pid: int, epoch: int) -> None:
        super().__init__(f"MOVED {pid} {epoch}")
        self.pid, self.epoch = pid, epoch


class _Unreachable(Exception):
    pass


def _send_vec(sock: socket.socket, out: deque) -> int:
    """Flush a deque of memoryviews with writev-coalesced sendmsg calls.
    Returns bytes sent; leaves the unsent tail in ``out``. Raises OSError
    on a dead peer; EAGAIN just stops the flush."""
    total = 0
    while out:
        iov = list(out) if len(out) <= MAX_IOV else [
            out[i] for i in range(MAX_IOV)
        ]
        want = sum(len(mv) for mv in iov)
        try:
            sent = sock.sendmsg(iov)
        except (BlockingIOError, InterruptedError):
            break
        total += sent
        rem = sent
        while rem and out:
            mv = out[0]
            if rem >= len(mv):
                rem -= len(mv)
                out.popleft()
            else:
                out[0] = mv[rem:]
                rem = 0
        if sent < want:
            break  # kernel buffer full — park behind EPOLLOUT
    return total


class _Slot:
    """One request's ordered response slot. ``parts``/``outstanding``
    carry fan-out state; ``attempt`` the MOVED/BUSY healing budget."""

    __slots__ = ("data", "done", "parts", "outstanding", "attempt")

    def __init__(self) -> None:
        self.data = b""
        self.done = False
        self.parts: Optional[dict] = None
        self.outstanding = 0
        self.attempt = 0


class _ClientConn:
    __slots__ = (
        "sock", "fd", "worker", "router", "inbuf", "slots", "out",
        "out_bytes", "want_write", "paused", "closed", "close_after_flush",
    )

    def __init__(self, worker: "_Worker", sock: socket.socket) -> None:
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.sock = sock
        self.fd = sock.fileno()
        self.worker = worker
        self.router = worker.router
        self.inbuf = bytearray()
        self.slots: deque[_Slot] = deque()
        self.out: deque = deque()
        self.out_bytes = 0
        self.want_write = False
        self.paused = False
        self.closed = False
        self.close_after_flush = False

    # -- reading -------------------------------------------------------------
    def on_readable(self) -> None:
        got = 0
        while got < (1 << 20):  # fairness cap per pass
            try:
                chunk = self.sock.recv(_READ_CHUNK)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self.close()
                return
            if not chunk:
                self.close()
                return
            self.inbuf += chunk
            got += len(chunk)
            if len(chunk) < _READ_CHUNK:
                break
        self._parse()

    def _parse(self) -> None:
        buf = self.inbuf
        start = 0
        n_lines = 0
        while not self.closed and not self.close_after_flush:
            i = buf.find(b"\n", start)
            if i < 0:
                break
            line = bytes(buf[start:i])
            start = i + 1
            if line.endswith(b"\r"):
                line = line[:-1]
            if len(line) > MAX_LINE:
                self._refuse_long_line()
                break
            n_lines += 1
            self.router._handle_line(self, line)
        if n_lines:
            get_metrics().inc("router.commands", n_lines)
        if start:
            del buf[:start]
        if len(buf) > MAX_LINE and not self.close_after_flush:
            # A newline-less line past the cap: refuse once, close — the
            # rest of the oversized line is garbage (native parity).
            self._refuse_long_line()
        self.worker.dirty_conns.add(self)

    def _refuse_long_line(self) -> None:
        slot = _Slot()
        self.slots.append(slot)
        self.complete(slot, b"ERROR line too long\r\n")
        self.close_after_flush = True

    # -- writing -------------------------------------------------------------
    def complete(self, slot: _Slot, data: bytes) -> None:
        if slot.done:
            return
        slot.data = data
        slot.done = True
        self.worker.dirty_conns.add(self)

    def flush(self) -> None:
        if self.closed:
            return
        while self.slots and self.slots[0].done:
            data = self.slots.popleft().data
            if data:
                self.out.append(memoryview(data))
                self.out_bytes += len(data)
        if self.out:
            try:
                self.out_bytes -= _send_vec(self.sock, self.out)
            except OSError:
                self.close()
                return
        self._update_interest()
        if not self.out and not self.slots and self.close_after_flush:
            self.close()

    def _update_interest(self) -> None:
        want_write = bool(self.out)
        pause = self.out_bytes > OUT_HIGH or (
            self.paused and self.out_bytes > OUT_LOW
        )
        mask = (0 if pause else _R) | (_W if want_write else 0)
        if want_write != self.want_write or pause != self.paused:
            self.want_write = want_write
            self.paused = pause
            try:
                self.worker.sel.modify(self.fd, mask or _R, ("conn", self))
            except (KeyError, ValueError, OSError):
                pass

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self.worker.sel.unregister(self.fd)
        except (KeyError, ValueError, OSError):
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.worker.conns.discard(self)
        self.worker.dirty_conns.discard(self)


class _Upstream:
    """One pipelined backend connection (worker, partition). In-flight
    requests match responses strictly FIFO; multi-line answers (VALUES/
    KEYS blocks) consume their declared row count before the next match.
    """

    __slots__ = (
        "worker", "pid", "addr", "sock", "fd", "inbuf", "pending", "out",
        "cur", "need", "closed", "last_progress",
    )

    def __init__(
        self, worker: "_Worker", pid: int, addr: str, sock: socket.socket
    ) -> None:
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.worker = worker
        self.pid = pid
        self.addr = addr
        self.sock = sock
        self.fd = sock.fileno()
        self.inbuf = bytearray()
        # (kind, n, cont): kind "line" | "mget" (n = row count) | "keys".
        self.pending: deque[tuple[str, int, Callable]] = deque()
        self.out: deque = deque()
        self.cur: Optional[list[str]] = None
        self.need = 0
        self.closed = False
        self.last_progress = time.monotonic()

    def send(self, req: bytes, kind: str, n: int, cont: Callable) -> None:
        if not self.pending:
            self.last_progress = time.monotonic()
        self.pending.append((kind, n, cont))
        self.out.append(memoryview(req))
        self.worker.dirty_up.add(self)

    def flush(self) -> None:
        if self.closed or not self.out:
            return
        try:
            _send_vec(self.sock, self.out)
        except OSError as e:
            self.worker.reset_upstream(self, f"send: {e}")
            return
        if self.out:
            try:
                self.worker.sel.modify(self.fd, _R | _W, ("up", self))
            except (KeyError, ValueError, OSError):
                pass

    def on_readable(self) -> None:
        while True:
            try:
                chunk = self.sock.recv(_READ_CHUNK)
            except (BlockingIOError, InterruptedError):
                break
            except OSError as e:
                self.worker.reset_upstream(self, f"recv: {e}")
                return
            if not chunk:
                self.worker.reset_upstream(self, "connection closed")
                return
            self.inbuf += chunk
            if len(chunk) < _READ_CHUNK:
                break
        self.last_progress = time.monotonic()
        buf = self.inbuf
        start = 0
        pending = self.pending
        dirty_conns = self.worker.dirty_conns
        while not self.closed:
            i = buf.find(b"\n", start)
            if i < 0:
                break
            raw = bytes(buf[start:i + 1])
            start = i + 1
            # Fast lane: a pipelined single-key forward whose answer is
            # not an error passes through as the raw byte slice — no
            # decode, no strip, no per-response closure.
            if (
                self.cur is None
                and pending
                and pending[0][0] == "fwd"
            ):
                _, _, (conn, slot, req) = pending.popleft()
                if raw[:5] == b"ERROR":
                    self.worker.router._fwd_error(conn, slot, req, raw)
                elif not slot.done:
                    slot.data = raw
                    slot.done = True
                    dirty_conns.add(conn)
                continue
            line_b = raw[:-2] if raw[-2:] == b"\r\n" else raw[:-1]
            self._feed_line(line_b.decode("utf-8", "surrogateescape"))
        if start:
            del buf[:start]
        if len(buf) > MAX_LINE + (1 << 16):
            self.worker.reset_upstream(self, "oversized response line")

    def _feed_line(self, line: str) -> None:
        if self.cur is not None:
            self.cur.append(line)
            if len(self.cur) - 1 >= self.need:
                res, self.cur = self.cur, None
                self._complete(res)
            return
        if not self.pending:
            # A response with nothing in flight: protocol desync —
            # nothing downstream can be trusted; reset.
            self.worker.reset_upstream(self, "unsolicited response")
            return
        kind, n, _ = self.pending[0]
        need = 0
        if kind == "mget" and line.startswith("VALUES "):
            need = n
        elif kind == "keys" and line.startswith("KEYS "):
            try:
                need = max(0, int(line[5:]))
            except ValueError:
                need = 0
        if need:
            self.cur = [line]
            self.need = need
        else:
            self._complete([line])

    def _complete(self, res: list[str]) -> None:
        _, _, cont = self.pending.popleft()
        self.last_progress = time.monotonic()
        try:
            cont(res)
        except Exception:
            get_metrics().inc("router.backend_errors")

    def fail_all(self) -> None:
        router = self.worker.router
        while self.pending:
            kind, _, cont = self.pending.popleft()
            try:
                if kind == "fwd":
                    conn, slot, req = cont
                    router._fwd_error(conn, slot, req, None)
                else:
                    cont(None)
            except Exception:
                get_metrics().inc("router.backend_errors")

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self.worker.sel.unregister(self.fd)
        except (KeyError, ValueError, OSError):
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.worker.dirty_up.discard(self)


class _Worker(threading.Thread):
    """One io worker: private selector, private upstream pool, a wake
    pipe for cross-thread posts, and a timer heap for healing backoffs.
    Everything a worker owns is touched only on its own thread."""

    def __init__(self, router: "RequestPlaneRouter", idx: int) -> None:
        super().__init__(daemon=True, name=f"mkv-rplane-io{idx}")
        self.router = router
        self.idx = idx
        self.sel = selectors.DefaultSelector()
        self._rfd, self._wfd = os.pipe()
        os.set_blocking(self._rfd, False)
        os.set_blocking(self._wfd, False)
        self.sel.register(self._rfd, _R, ("wake", None))
        self._inbox: deque[Callable] = deque()
        self._inbox_mu = threading.Lock()
        self._timers: list = []
        self._timer_seq = 0
        self.conns: set[_ClientConn] = set()
        self.upstreams: dict[int, _Upstream] = {}
        self.up_rr: dict[int, int] = {}
        self.dirty_conns: set[_ClientConn] = set()
        self.dirty_up: set[_Upstream] = set()
        self.commands = 0
        self._stopped = False

    # -- cross-thread --------------------------------------------------------
    def post(self, fn: Callable) -> None:
        with self._inbox_mu:
            self._inbox.append(fn)
        try:
            os.write(self._wfd, b"x")
        except (BlockingIOError, OSError):
            pass  # pipe full: a wake is already pending

    def stop(self) -> None:
        self._stopped = True
        self.post(lambda: None)

    # -- worker-thread only --------------------------------------------------
    def add_timer(self, delay_s: float, fn: Callable) -> None:
        self._timer_seq += 1
        heapq.heappush(
            self._timers, (time.monotonic() + delay_s, self._timer_seq, fn)
        )

    def adopt(self, sock: socket.socket) -> None:
        conn = _ClientConn(self, sock)
        try:
            self.sel.register(conn.fd, _R, ("conn", conn))
        except (ValueError, OSError):
            sock.close()
            return
        self.conns.add(conn)

    def reset_upstream(self, up: _Upstream, why: str) -> None:
        if up.closed:
            return
        get_metrics().inc("router.upstream_resets")
        get_recorder().record(
            "router_upstream_reset", partition=up.pid, addr=up.addr,
            why=why, pending=len(up.pending),
        )
        if self.upstreams.get(up.pid) is up:
            del self.upstreams[up.pid]
            # Rotate the dial order so the redial tries the next replica
            # first instead of hammering the one that just died.
            self.up_rr[up.pid] = self.up_rr.get(up.pid, 0) + 1
        up.close()
        up.fail_all()

    def run(self) -> None:
        while not self._stopped:
            timeout = 0.5
            if self._timers:
                timeout = min(
                    timeout, max(0.0, self._timers[0][0] - time.monotonic())
                )
            try:
                events = self.sel.select(timeout)
            except OSError:
                break
            for key, mask in events:
                kind, obj = key.data
                if kind == "wake":
                    try:
                        while os.read(self._rfd, 4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                elif kind == "conn":
                    if mask & _R and not obj.closed:
                        obj.on_readable()
                elif kind == "up":
                    if mask & _W and not obj.closed:
                        obj.flush()
                    if mask & _R and not obj.closed:
                        obj.on_readable()
            while True:
                with self._inbox_mu:
                    if not self._inbox:
                        break
                    fn = self._inbox.popleft()
                try:
                    fn()
                except Exception:
                    get_metrics().inc("router.backend_errors")
            now = time.monotonic()
            while self._timers and self._timers[0][0] <= now:
                _, _, fn = heapq.heappop(self._timers)
                try:
                    fn()
                except Exception:
                    get_metrics().inc("router.backend_errors")
            # Hung-upstream guard: a backend that stops answering (but
            # keeps the socket open) would otherwise wedge its FIFO — and
            # every slot queued behind it — forever.
            if self.upstreams:
                for up in list(self.upstreams.values()):
                    if up.pending and (
                        now - up.last_progress > self.router.timeout
                    ):
                        self.reset_upstream(up, "response timeout")
            # Burst discipline: ONE flush per upstream, then one writev
            # per client connection, per pass.
            if self.dirty_up:
                for up in list(self.dirty_up):
                    up.flush()
                self.dirty_up.clear()
            if self.dirty_conns:
                dirty, self.dirty_conns = self.dirty_conns, set()
                for conn in dirty:
                    conn.flush()
        # teardown on the worker thread: nobody else touches these
        for conn in list(self.conns):
            conn.close()
        for up in list(self.upstreams.values()):
            up.close()
        try:
            self.sel.close()
        except OSError:
            pass
        os.close(self._rfd)
        os.close(self._wfd)


class RequestPlaneRouter:
    """The production request plane: one address for a partitioned
    cluster, pooled + pipelined + (optionally) lease-cached."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        seeds: Optional[list[str]] = None,
        timeout: float = 5.0,
        workers: int = 0,
        cache_bytes: int = 0,
        cache_max_age_ms: float = 2000.0,
        invalidation_transport=None,
        broker: Optional[str] = None,
        broker_port: int = 0,
        transport_kind: str = "framed",
        topic_prefix: str = "",
        metrics_port: Optional[int] = None,
        metrics_host: str = "127.0.0.1",
    ) -> None:
        if not seeds:
            raise ValueError("router needs at least one seed node")
        self.host = host
        self._port = port
        self.seeds = list(seeds)
        self.timeout = timeout
        n = workers or min(8, max(2, os.cpu_count() or 2))
        self._nworkers = n
        self._pmap: Optional[PartitionMap] = None
        self._map_mu = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._workers: list[_Worker] = []
        self._rr = 0
        self.cache: Optional[LeaseCache] = None
        if cache_bytes > 0:
            self.cache = LeaseCache(
                cache_bytes,
                max_age_ms=cache_max_age_ms,
                lease_timeout_ms=max(1000.0, timeout * 1000.0),
            )
        self._transport = invalidation_transport
        self._own_transport = False
        if self._transport is None and broker:
            from merklekv_tpu.cluster.transport import make_transport

            self._transport = make_transport(
                broker, broker_port, transport_kind,
                client_id=f"mkv-router-{os.getpid()}",
            )
            self._own_transport = True
        self._topic_prefix = topic_prefix
        self.feed: Optional[InvalidationFeed] = None
        self._metrics_port_arg = metrics_port
        self._metrics_host = metrics_host
        self._exporter = None
        self._keeper: Optional[threading.Thread] = None
        self._keeper_cond = threading.Condition()
        self._keeper_reqs: list[tuple[int, Callable]] = []
        self._last_refresh = 0.0
        self._gauges: list[tuple[str, Callable]] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self, map_wait_s: float = 10.0) -> "RequestPlaneRouter":
        deadline = time.monotonic() + map_wait_s
        while True:
            try:
                self._refresh_map_blocking(0)
                break
            except ClientConnectionError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        if self.cache is not None and self._transport is not None:
            self.feed = InvalidationFeed(
                self.cache, self._transport, self._topic_prefix
            )
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self._port))
        self._sock.listen(512)
        self._port = self._sock.getsockname()[1]
        for i in range(self._nworkers):
            w = _Worker(self, i)
            w.start()
            self._workers.append(w)
        self._keeper = threading.Thread(
            target=self._keeper_loop, daemon=True, name="mkv-rplane-map"
        )
        self._keeper.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="mkv-rplane-accept"
        )
        self._accept_thread.start()
        self._register_gauges()
        if self._metrics_port_arg is not None:
            from merklekv_tpu.obs.exporter import MetricsExporter

            self._exporter = MetricsExporter(
                self._metrics_port_arg,
                host=self._metrics_host,
                health_fn=self._health_fields,
            )
            self._exporter.start()
        return self

    @property
    def port(self) -> int:
        return self._port

    @property
    def metrics_port(self) -> Optional[int]:
        return self._exporter.port if self._exporter is not None else None

    @property
    def map(self) -> Optional[PartitionMap]:
        return self._pmap

    def stop(self) -> None:
        self._stopped.set()
        with self._keeper_cond:
            self._keeper_cond.notify_all()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        for w in self._workers:
            w.stop()
        for w in self._workers:
            w.join(timeout=5)
        self._workers = []
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        if self.feed is not None:
            self.feed.close()
            self.feed = None
        if self._own_transport and self._transport is not None:
            try:
                self._transport.close()
            except Exception:
                pass
        if self._exporter is not None:
            self._exporter.close()
            self._exporter = None
        m = get_metrics()
        for name, fn in self._gauges:
            m.unregister_gauge(name, fn)
        self._gauges = []

    # -- observability -------------------------------------------------------
    def _register_gauges(self) -> None:
        m = get_metrics()
        pairs: list[tuple[str, Callable]] = [
            ("router.conns",
             lambda: sum(len(w.conns) for w in self._workers)),
            ("router.workers", lambda: len(self._workers)),
            ("router.inval_lag_ms",
             lambda: self.feed.last_lag_ms if self.feed else -1.0),
        ]
        if self.cache is not None:
            pairs += [
                ("router.cache_bytes", lambda: self.cache.bytes_used),
                ("router.cache_keys", lambda: self.cache.keys),
                ("router.leases_inflight",
                 lambda: self.cache.leases_inflight),
            ]
        for name, fn in pairs:
            m.register_gauge(name, fn, help=f"request plane: {name}")
            self._gauges.append((name, fn))

    def _health_fields(self) -> dict:
        pmap = self._pmap
        return {
            "role": "router",
            "partitions": pmap.count if pmap else 0,
            "epoch": pmap.epoch if pmap else 0,
            "workers": len(self._workers),
            "conns": sum(len(w.conns) for w in self._workers),
            "cache_keys": self.cache.keys if self.cache else 0,
            "cache_bytes": self.cache.bytes_used if self.cache else 0,
            "inval_lag_ms": round(
                self.feed.last_lag_ms if self.feed else -1.0, 3
            ),
        }

    def _stats_block(self) -> str:
        lines = [
            "STATS",
            f"total_commands:{sum(w.commands for w in self._workers)}",
            "active_connections:"
            f"{sum(len(w.conns) for w in self._workers)}",
            f"io_threads:{len(self._workers)}",
        ]
        for w in self._workers:
            lines.append(f"io_worker_{w.idx}_commands:{w.commands}")
        lines.append("END")
        return "\r\n".join(lines) + "\r\n"

    def _info_block(self) -> str:
        pmap = self._pmap
        lines = [
            "INFO",
            "role:router",
            f"partitions:{pmap.count if pmap else 0}",
            f"epoch:{pmap.epoch if pmap else 0}",
            f"workers:{len(self._workers)}",
            "END",
        ]
        return "\r\n".join(lines) + "\r\n"

    def _metrics_block(self) -> str:
        snap = get_metrics().snapshot()["counters"]
        lines = ["METRICS"]
        for name in sorted(snap):
            if name.startswith(("router.", "transport.")):
                lines.append(f"{name}:{snap[name]}")
        pmap = self._pmap
        live = {
            "router.partitions": pmap.count if pmap else 0,
            "router.epoch": pmap.epoch if pmap else 0,
            "router.workers": len(self._workers),
            "router.conns": sum(len(w.conns) for w in self._workers),
            "router.cache_keys": self.cache.keys if self.cache else 0,
            "router.cache_bytes": (
                self.cache.bytes_used if self.cache else 0
            ),
            "router.leases_inflight": (
                self.cache.leases_inflight if self.cache else 0
            ),
            "router.inval_lag_ms": round(
                self.feed.last_lag_ms if self.feed else -1.0, 3
            ),
        }
        for name in sorted(live):
            lines.append(f"{name}:{live[name]}")
        lines.append("END")
        return "\r\n".join(lines) + "\r\n"

    # -- partition map -------------------------------------------------------
    def _refresh_map_blocking(self, min_epoch: int) -> None:
        """Newest reachable map (seeds, then known replicas). Runs on the
        keeper thread (or start()); workers never block on this."""
        candidates = list(self.seeds)
        cur = self._pmap
        if cur is not None:
            for reps in cur.replicas:
                for a in reps:
                    if a not in candidates:
                        candidates.append(a)
        fresh = None
        errors: list[str] = []
        for addr in candidates:
            host, _, port = addr.rpartition(":")
            try:
                with MerkleKVClient(
                    host, int(port), timeout=self.timeout
                ) as c:
                    m = c.partition_map()
            except (MerkleKVError, OSError, ValueError) as e:
                errors.append(f"{addr}: {e}")
                continue
            if fresh is None or m.epoch > fresh.epoch:
                fresh = m
            if fresh.epoch >= min_epoch > 0:
                break
        if fresh is None:
            raise ClientConnectionError(
                "router: no reachable node served a partition map: "
                + "; ".join(errors[:4])
            )
        with self._map_mu:
            cur = self._pmap
            if cur is None or fresh.epoch >= cur.epoch:
                epoch_flip = cur is not None and fresh.epoch > cur.epoch
                self._pmap = fresh
                get_metrics().inc("router.map_refreshes")
                if epoch_flip:
                    # Partition ids renumber across an epoch: cached
                    # entries' pids and the feed's per-topic HWMs are
                    # meaningless now. Drop both; refills stamp fresh.
                    if self.cache is not None:
                        self.cache.clear()
                    if self.feed is not None:
                        self.feed.reset()
                    get_recorder().record(
                        "router_map_epoch", epoch=fresh.epoch,
                        partitions=fresh.count,
                    )
        self._last_refresh = time.monotonic()

    def request_refresh(self, min_epoch: int, cb: Callable) -> None:
        """Queue a map refresh on the keeper thread; ``cb(ok)`` fires when
        it settles (posted by the keeper — the caller passes a closure
        that re-posts to its worker)."""
        with self._keeper_cond:
            self._keeper_reqs.append((min_epoch, cb))
            self._keeper_cond.notify()

    def _keeper_loop(self) -> None:
        while not self._stopped.is_set():
            with self._keeper_cond:
                while not self._keeper_reqs and not self._stopped.is_set():
                    self._keeper_cond.wait(timeout=0.5)
                if self._stopped.is_set():
                    return
                batch, self._keeper_reqs = self._keeper_reqs, []
            min_epoch = max(e for e, _ in batch)
            cur = self._pmap
            ok = True
            if cur is not None and cur.epoch >= min_epoch and (
                time.monotonic() - self._last_refresh < 0.05
            ):
                pass  # a refresh just landed past the requested epoch
            else:
                try:
                    self._refresh_map_blocking(min_epoch)
                except ClientConnectionError:
                    ok = False
            for _, cb in batch:
                try:
                    cb(ok)
                except Exception:
                    pass

    # -- serving -------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            w = self._workers[self._rr % len(self._workers)]
            self._rr += 1
            w.post(lambda s=conn, w=w: w.adopt(s))

    def _handle_line(self, conn: _ClientConn, line_b: bytes) -> None:
        worker = conn.worker
        worker.commands += 1
        if self._fast_route(conn, line_b):
            return
        line = line_b.decode("utf-8", "surrogateescape")
        verb, _, rest = line.partition(" ")
        verb = verb.upper()
        slot = _Slot()
        conn.slots.append(slot)
        if verb == "PING":
            conn.complete(
                slot, self._enc(f"PONG {rest}\r\n" if rest else "PONG \r\n")
            )
            return
        if verb == "PARTMAP":
            conn.complete(slot, self._enc(self._pmap.wire()))
            return
        if verb == "METRICS":
            conn.complete(slot, self._enc(self._metrics_block()))
            return
        if verb == "STATS":
            conn.complete(slot, self._enc(self._stats_block()))
            return
        if verb == "INFO":
            conn.complete(slot, self._enc(self._info_block()))
            return
        if verb == "PEERS":
            conn.complete(slot, b"PEERS 0\r\nEND\r\n")
            return
        self._route(conn, slot, verb, rest)

    @staticmethod
    def _enc(s: str) -> bytes:
        return s.encode("utf-8", "surrogateescape")

    # -- bytes fast lane -----------------------------------------------------
    def _fast_route(self, conn: _ClientConn, line_b: bytes) -> bool:
        """The zero-decode forward: an uppercase single-key command whose
        shape validates and whose upstream is already dialable is queued
        as a ("fwd", conn, slot, line) pending entry — the response comes
        back as a raw byte slice. Returns False (having changed NOTHING)
        whenever the str machinery must take over: irregular shape, a
        cached GET, or an upstream that needs the healing ladder."""
        sp = line_b.find(b" ")
        if sp <= 0:
            return False
        shape = _FAST_VERBS.get(line_b[:sp])
        if shape is None:
            return False
        rest = line_b[sp + 1:]
        ksp = rest.find(b" ")
        cache = self.cache
        if shape == 0:  # GET <key>
            if ksp >= 0 or not rest or cache is not None:
                return False  # vs= token / malformed / cache path
            key = rest
        elif shape == 1:  # SET/APPEND/PREPEND <key> <value>
            if ksp <= 0:
                return False
            key = rest[:ksp]
        else:  # DELETE/DEL <key>
            if ksp >= 0 or not rest:
                return False
            key = rest
        try:
            pid = self._pmap.partition_for_key(key)
            up = self._get_upstream(conn.worker, pid)
        except (_Moved, _Unreachable):
            return False
        if cache is not None and shape != 0:
            cache.invalidate(key.decode("utf-8", "surrogateescape"))
        slot = _Slot()
        conn.slots.append(slot)
        up.send(line_b + b"\r\n", "fwd", 0, (conn, slot, line_b))
        return True

    def _fwd_error(
        self, conn: _ClientConn, slot: _Slot, req: bytes,
        raw: Optional[bytes],
    ) -> None:
        """A fast-lane forward hit the slow cases: upstream lost (raw is
        None) or an ERROR answer. Re-enter the healing ladder with the
        original request line — identical outcome to the str path."""
        if slot.done:
            return
        line = req.decode("utf-8", "surrogateescape")
        verb, _, rest = line.partition(" ")
        retry = lambda: self._route(conn, slot, verb, rest)  # noqa: E731
        if raw is None:
            self._heal_or_fail(conn, slot, "lost", retry,
                               _BUSY_UPSTREAM_LOST + "\r\n")
            return
        header = raw.decode("utf-8", "surrogateescape").rstrip("\r\n")
        if header.startswith("ERROR MOVED "):
            fields = header.split(" ")
            epoch = int(fields[3]) if len(fields) >= 4 else 0
            self._heal_or_fail(conn, slot, "moved", retry,
                               header + "\r\n", min_epoch=epoch)
            return
        if header.startswith("ERROR BUSY"):
            self._heal_or_fail(conn, slot, "busy", retry, header + "\r\n")
            return
        conn.complete(slot, raw)

    def _fail(self, conn: _ClientConn, slot: _Slot, msg: str) -> None:
        conn.complete(slot, self._enc(msg if msg.endswith("\r\n") else msg + "\r\n"))

    # -- healing -------------------------------------------------------------
    def _heal_or_fail(
        self,
        conn: _ClientConn,
        slot: _Slot,
        kind: str,
        retry: Callable,
        final: str,
        min_epoch: int = 0,
    ) -> None:
        """The bounded MOVED/BUSY/lost-upstream healing ladder, pooled
        edition: backoff on a worker timer (never a sleeping thread), a
        map refresh on the keeper when the condition implies a stale map,
        then the retry closure — until the PARTITION_MOVED budget is
        spent and ``final`` surfaces to the client."""
        worker = conn.worker
        attempts = PARTITION_MOVED.attempts or 1
        if slot.attempt + 1 >= attempts:
            self._fail(conn, slot, final)
            return
        delay = PARTITION_MOVED.backoff(slot.attempt)
        slot.attempt += 1
        m = get_metrics()
        if kind == "moved":
            m.inc("router.moved_refreshes")
        elif kind == "busy":
            m.inc("router.busy_retries")
        if kind in ("moved", "lost"):
            def after_refresh(ok: bool) -> None:
                worker.post(lambda: worker.add_timer(delay, retry))

            self.request_refresh(min_epoch, after_refresh)
        else:
            worker.add_timer(delay, retry)

    # -- routing -------------------------------------------------------------
    def _route(
        self, conn: _ClientConn, slot: _Slot, verb: str, rest: str
    ) -> None:
        try:
            self._route_inner(conn, slot, verb, rest)
        except _Moved as e:
            retry = lambda: self._route(conn, slot, verb, rest)  # noqa: E731
            self._heal_or_fail(
                conn, slot, "moved", retry,
                f"ERROR MOVED {e.pid} {e.epoch}\r\n", min_epoch=e.epoch,
            )
        except _Unreachable as e:
            get_metrics().inc("router.backend_errors")
            retry = lambda: self._route(conn, slot, verb, rest)  # noqa: E731
            self._heal_or_fail(
                conn, slot, "lost", retry,
                f"ERROR BUSY router: {e} (retry)\r\n",
            )
        except Exception as e:
            get_metrics().inc("router.backend_errors")
            self._fail(conn, slot, f"ERROR router: {e}\r\n")

    def _route_inner(
        self, conn: _ClientConn, slot: _Slot, verb: str, rest: str
    ) -> None:
        pmap = self._pmap
        slot.parts = None
        slot.outstanding = 0
        if verb == "GET":
            self._route_get(conn, slot, rest, pmap)
            return
        if verb in ("INC", "DEC"):
            key, _, amt_s = rest.strip().partition(" ")
            if not key:
                self._fail(conn, slot,
                           f"ERROR {verb} command requires a key\r\n")
                return
            if amt_s:
                try:
                    int(amt_s)
                except ValueError:
                    self._fail(
                        conn, slot,
                        f"ERROR {verb} command amount must be a valid "
                        "number\r\n",
                    )
                    return
            self._invalidate_write(key)
            self._forward_line(
                conn, slot, verb, rest, pmap.partition_for_key(key)
            )
            return
        if verb in _SINGLE_KEY:
            if _SINGLE_KEY[verb]:
                key, sep, _value = rest.partition(" ")
                if not sep or not key:
                    self._fail(
                        conn, slot,
                        f"ERROR {verb} command requires a key and value\r\n",
                    )
                    return
                self._invalidate_write(key)
            else:  # DEL / DELETE
                key = rest.strip()
                if not key or " " in key:
                    self._fail(conn, slot,
                               f"ERROR {verb} command requires a key\r\n")
                    return
                self._invalidate_write(key)
            self._forward_line(
                conn, slot, verb, rest, pmap.partition_for_key(key)
            )
            return
        if verb == "EXISTS":
            keys = rest.split()
            if not keys:
                self._fail(
                    conn, slot,
                    "ERROR EXISTS command requires at least one key\r\n",
                )
                return
            groups = self._group(keys, pmap)
            self._fan_out(
                conn, slot, verb, rest,
                [(pid, f"EXISTS {' '.join(sub)}", "line", 0)
                 for pid, sub in groups],
                lambda parts: self._merge_exists(parts),
            )
            return
        if verb == "MGET":
            keys = rest.split()
            if not keys:
                self._fail(
                    conn, slot,
                    "ERROR MGET command requires at least one key\r\n",
                )
                return
            groups = self._group(keys, pmap)
            self._fan_out(
                conn, slot, verb, rest,
                [(pid, f"MGET {' '.join(sub)}", "mget", len(sub))
                 for pid, sub in groups],
                lambda parts: self._merge_mget(parts, keys),
            )
            return
        if verb == "MSET":
            args = rest.split()
            if not args or len(args) % 2:
                self._fail(
                    conn, slot,
                    "ERROR MSET command requires an even number of "
                    "arguments (key-value pairs)\r\n",
                )
                return
            pairs = dict(zip(args[::2], args[1::2]))
            for k in pairs:
                self._invalidate_write(k)
            groups = self._group(list(pairs), pmap)
            reqs = []
            for pid, sub in groups:
                flat = " ".join(f"{k} {pairs[k]}" for k in sub)
                reqs.append((pid, f"MSET {flat}", "line", 0))
            self._fan_out(
                conn, slot, verb, rest, reqs,
                lambda parts: self._merge_ok(parts),
            )
            return
        if verb == "SCAN":
            prefix = rest.strip()
            cmd = f"SCAN {prefix}" if prefix else "SCAN"
            self._fan_out(
                conn, slot, verb, rest,
                [(pid, cmd, "keys", 0) for pid in range(pmap.count)],
                lambda parts: self._merge_scan(parts),
            )
            return
        if verb == "DBSIZE":
            self._fan_out(
                conn, slot, verb, rest,
                [(pid, "DBSIZE", "line", 0) for pid in range(pmap.count)],
                lambda parts: self._merge_dbsize(parts),
            )
            return
        self._fail(
            conn, slot,
            f"ERROR router: unsupported verb {verb} "
            "(connect to a node directly or use a partition-aware "
            "client)\r\n",
        )

    def _invalidate_write(self, key: str) -> None:
        """Write-through drop: read-your-writes THROUGH this router; the
        replication event is the authoritative invalidation for every
        other path."""
        if self.cache is not None:
            self.cache.invalidate(key)

    # -- GET + lease cache ---------------------------------------------------
    def _route_get(
        self, conn: _ClientConn, slot: _Slot, rest: str, pmap: PartitionMap
    ) -> None:
        toks = rest.split()
        stamp = False
        force = False
        if len(toks) == 2 and toks[1].startswith("vs="):
            key = toks[0]
            stamp = True
            force = toks[1] == "vs=03"
        elif len(toks) == 1:
            key = toks[0]
        else:
            self._fail(conn, slot, "ERROR GET command requires a key\r\n")
            return
        pid = pmap.partition_for_key(key)
        cache = self.cache
        if cache is None or force:
            if force and cache is not None:
                cache.invalidate(key)
            self._forward_get_plain(conn, slot, key, pid, stamp)
            return
        worker = conn.worker

        def waiter(value, age_ms, error) -> None:
            worker.post(
                lambda: self._finish_get(conn, slot, value, age_ms, error,
                                         stamp)
            )

        res = cache.begin_get(key, pid, waiter)
        if res is WAIT:
            return
        if res is not LEAD:
            value, age_ms = res
            self._finish_get(conn, slot, value, age_ms, None, stamp)
            return
        self._lease_fill(conn, slot, key, pid, stamp)

    def _lease_fill(
        self, conn: _ClientConn, slot: _Slot, key: str, pid: int, stamp: bool
    ) -> None:
        """The lease holder's fill: ONE upstream GET answers this slot and
        every waiter. Healing retries keep the lease; only the final
        failure releases it with an error."""
        cache = self.cache

        def settle(value, error) -> None:
            waiters = cache.finish_fill(key, value, pid, error=error)
            self._finish_get(conn, slot, value, 0.0, error, stamp)
            for w in waiters:
                w(value, 0.0, error)

        def retry() -> None:
            # Re-resolve the partition: the map may have flipped.
            self._lease_fill(
                conn, slot, key, self._pmap.partition_for_key(key), stamp
            )

        def cont(res) -> None:
            if res is None:
                self._heal_lease(conn, slot, "lost", retry, settle,
                                 _BUSY_UPSTREAM_LOST)
                return
            header = res[0]
            if header.startswith("ERROR MOVED "):
                fields = header.split(" ")
                epoch = int(fields[3]) if len(fields) >= 4 else 0
                self._heal_lease(conn, slot, "moved", retry, settle,
                                 header + "\r\n", min_epoch=epoch)
                return
            if header.startswith("ERROR BUSY"):
                self._heal_lease(conn, slot, "busy", retry, settle,
                                 header + "\r\n")
                return
            if header.startswith("ERROR"):
                settle(None, header + "\r\n")
                return
            if header.startswith("VALUE "):
                settle(header[6:], None)
            else:  # NOT_FOUND — a clean answer, not cached
                settle(None, None)

        try:
            up = self._get_upstream(conn.worker, pid)
        except _Moved as e:
            retry2 = retry
            self._heal_lease(
                conn, slot, "moved", retry2, settle,
                f"ERROR MOVED {e.pid} {e.epoch}\r\n", min_epoch=e.epoch,
            )
            return
        except _Unreachable as e:
            self._heal_lease(conn, slot, "lost", retry, settle,
                             f"ERROR BUSY router: {e} (retry)\r\n")
            return
        up.send(self._enc(f"GET {key}\r\n"), "line", 0, cont)

    def _heal_lease(
        self, conn, slot, kind, retry, settle, final, min_epoch=0
    ) -> None:
        """Healing for the lease holder: like _heal_or_fail, but the
        terminal failure must RELEASE the lease (settle with error) so
        waiters are never stranded."""
        worker = conn.worker
        attempts = PARTITION_MOVED.attempts or 1
        if slot.attempt + 1 >= attempts:
            settle(None, final)
            return
        delay = PARTITION_MOVED.backoff(slot.attempt)
        slot.attempt += 1
        m = get_metrics()
        if kind == "moved":
            m.inc("router.moved_refreshes")
        elif kind == "busy":
            m.inc("router.busy_retries")
        if kind in ("moved", "lost"):
            self.request_refresh(
                min_epoch,
                lambda ok: worker.post(
                    lambda: worker.add_timer(delay, retry)
                ),
            )
        else:
            worker.add_timer(delay, retry)

    def _finish_get(
        self, conn, slot, value, age_ms, error, stamp: bool
    ) -> None:
        if error is not None:
            self._fail(conn, slot, error)
            return
        if value is None:
            conn.complete(slot, b"NOT_FOUND\r\n")
            return
        if stamp:
            bound = int(self.cache.max_age_ms) if self.cache else 0
            conn.complete(
                slot,
                self._enc(f"VALUE vs={int(age_ms)}:{bound} {value}\r\n"),
            )
        else:
            conn.complete(slot, self._enc(f"VALUE {value}\r\n"))

    def _forward_get_plain(
        self, conn, slot, key: str, pid: int, stamp: bool
    ) -> None:
        def retry() -> None:
            self._forward_get_plain(
                conn, slot, key, self._pmap.partition_for_key(key), stamp
            )

        def cont(res) -> None:
            if res is None:
                self._heal_or_fail(conn, slot, "lost", retry,
                                   _BUSY_UPSTREAM_LOST + "\r\n")
                return
            header = res[0]
            if header.startswith("ERROR MOVED "):
                fields = header.split(" ")
                epoch = int(fields[3]) if len(fields) >= 4 else 0
                self._heal_or_fail(conn, slot, "moved", retry,
                                   header + "\r\n", min_epoch=epoch)
                return
            if header.startswith("ERROR BUSY"):
                self._heal_or_fail(conn, slot, "busy", retry,
                                   header + "\r\n")
                return
            if header.startswith("VALUE ") and stamp:
                bound = int(self.cache.max_age_ms) if self.cache else 0
                self._finish_get(conn, slot, header[6:], 0.0, None, True)
                return
            conn.complete(slot, self._enc(header + "\r\n"))

        try:
            up = self._get_upstream(conn.worker, pid)
        except (_Moved, _Unreachable):
            raise
        up.send(self._enc(f"GET {key}\r\n"), "line", 0, cont)

    # -- single-key forward --------------------------------------------------
    def _forward_line(
        self, conn: _ClientConn, slot: _Slot, verb: str, rest: str, pid: int
    ) -> None:
        def retry() -> None:
            self._route(conn, slot, verb, rest)

        def cont(res) -> None:
            if slot.done:
                return
            if res is None:
                self._heal_or_fail(conn, slot, "lost", retry,
                                   _BUSY_UPSTREAM_LOST + "\r\n")
                return
            header = res[0]
            if header.startswith("ERROR MOVED "):
                fields = header.split(" ")
                epoch = int(fields[3]) if len(fields) >= 4 else 0
                self._heal_or_fail(conn, slot, "moved", retry,
                                   header + "\r\n", min_epoch=epoch)
                return
            if header.startswith("ERROR BUSY"):
                self._heal_or_fail(conn, slot, "busy", retry,
                                   header + "\r\n")
                return
            conn.complete(slot, self._enc(header + "\r\n"))

        up = self._get_upstream(conn.worker, pid)
        up.send(self._enc(f"{verb} {rest}\r\n"), "line", 0, cont)

    # -- fan-out -------------------------------------------------------------
    def _fan_out(
        self,
        conn: _ClientConn,
        slot: _Slot,
        verb: str,
        rest: str,
        reqs: list[tuple[int, str, str, int]],
        merge: Callable[[dict], str],
    ) -> None:
        """Dispatch per-partition sub-requests concurrently (pipelined on
        each upstream), merge when the LAST answer lands. Any MOVED/BUSY/
        lost sub-answer retries the whole command under the healing
        budget — sub-results are cheap to re-ask, ordering is not."""
        slot.parts = {}
        slot.outstanding = len(reqs)
        worker = conn.worker
        get_metrics().inc("router.fanout_subrequests", len(reqs))

        def retry() -> None:
            self._route(conn, slot, verb, rest)

        def arrived(pid: int, res) -> None:
            if slot.done or slot.parts is None:
                return
            slot.parts[pid] = res
            slot.outstanding -= 1
            if slot.outstanding > 0:
                return
            parts, slot.parts = slot.parts, None
            self._settle_fan_out(conn, slot, parts, retry, merge)

        ups = {}
        try:
            for pid, _cmd, _kind, _n in reqs:
                if pid not in ups:
                    ups[pid] = self._get_upstream(worker, pid)
        except _Moved as e:
            self._heal_or_fail(
                conn, slot, "moved", retry,
                f"ERROR MOVED {e.pid} {e.epoch}\r\n", min_epoch=e.epoch,
            )
            return
        except _Unreachable as e:
            self._heal_or_fail(conn, slot, "lost", retry,
                               f"ERROR BUSY router: {e} (retry)\r\n")
            return
        for pid, cmd, kind, n in reqs:
            ups[pid].send(
                self._enc(cmd + "\r\n"), kind, n,
                lambda res, pid=pid: arrived(pid, res),
            )

    def _settle_fan_out(
        self, conn, slot, parts: dict, retry, merge
    ) -> None:
        moved_epoch = None
        busy = False
        lost = False
        other_error = None
        for res in parts.values():
            if res is None:
                lost = True
                continue
            header = res[0]
            if header.startswith("ERROR MOVED "):
                fields = header.split(" ")
                moved_epoch = max(
                    moved_epoch or 0,
                    int(fields[3]) if len(fields) >= 4 else 0,
                )
            elif header.startswith("ERROR BUSY"):
                busy = True
            elif header.startswith("ERROR"):
                other_error = header
        if moved_epoch is not None:
            self._heal_or_fail(
                conn, slot, "moved", retry,
                f"ERROR MOVED 0 {moved_epoch}\r\n", min_epoch=moved_epoch,
            )
            return
        if lost:
            self._heal_or_fail(conn, slot, "lost", retry,
                               _BUSY_UPSTREAM_LOST + "\r\n")
            return
        if busy:
            self._heal_or_fail(conn, slot, "busy", retry,
                               "ERROR BUSY router: partition busy\r\n")
            return
        if other_error is not None:
            self._fail(conn, slot, other_error + "\r\n")
            return
        try:
            conn.complete(slot, self._enc(merge(parts)))
        except Exception as e:
            get_metrics().inc("router.backend_errors")
            self._fail(conn, slot, f"ERROR router: {e}\r\n")

    # -- merges (byte-identical to the thin router's shapes) -----------------
    @staticmethod
    def _merge_exists(parts: dict) -> str:
        total = 0
        for res in parts.values():
            total += int(res[0][7:])  # "EXISTS <n>"
        return f"EXISTS {total}\r\n"

    @staticmethod
    def _merge_mget(parts: dict, keys: list[str]) -> str:
        merged: dict[str, Optional[str]] = {}
        for res in parts.values():
            header = res[0]
            if header == "NOT_FOUND":
                continue  # that group found nothing; rows absent
            for row in res[1:]:
                k, _, v = row.partition(" ")
                merged[k] = None if v == "NOT_FOUND" else v
        found = sum(1 for k in set(keys) if merged.get(k) is not None)
        if found == 0:
            return "NOT_FOUND\r\n"
        body = "".join(
            f"{k} {merged[k] if merged.get(k) is not None else 'NOT_FOUND'}"
            "\r\n"
            for k in keys
        )
        return f"VALUES {found}\r\n{body}"

    @staticmethod
    def _merge_ok(parts: dict) -> str:
        return "OK\r\n"

    @staticmethod
    def _merge_scan(parts: dict) -> str:
        keys: list[str] = []
        for res in parts.values():
            keys += res[1:]
        keys.sort()
        body = "".join(f"{k}\r\n" for k in keys)
        return f"KEYS {len(keys)}\r\n{body}"

    @staticmethod
    def _merge_dbsize(parts: dict) -> str:
        total = 0
        for res in parts.values():
            total += int(res[0][7:])  # "DBSIZE <n>"
        return f"DBSIZE {total}\r\n"

    @staticmethod
    def _group(
        keys: list[str], pmap: PartitionMap
    ) -> list[tuple[int, list[str]]]:
        groups: dict[int, list[str]] = {}
        for k in keys:
            groups.setdefault(pmap.partition_for_key(k), []).append(k)
        return sorted(groups.items())

    # -- upstream pool -------------------------------------------------------
    def _get_upstream(self, worker: _Worker, pid: int) -> _Upstream:
        up = worker.upstreams.get(pid)
        if up is not None and not up.closed:
            return up
        pmap = self._pmap
        if not 0 <= pid < pmap.count:
            # A refresh shrank the map between routing and dialing: heal
            # like a MOVED answer, never an IndexError.
            raise _Moved(pid, pmap.epoch)
        reps = list(pmap.replicas[pid])
        rot = worker.up_rr.get(pid, 0) % len(reps)
        order = reps[rot:] + reps[:rot]
        last: Optional[Exception] = None
        for i, addr in enumerate(order):
            host, _, port = addr.rpartition(":")
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=min(1.0, self.timeout)
                )
            except OSError as e:
                last = e
                continue
            up = _Upstream(worker, pid, addr, sock)
            try:
                worker.sel.register(up.fd, _R, ("up", up))
            except (ValueError, OSError) as e:
                sock.close()
                last = e
                continue
            worker.upstreams[pid] = up
            worker.up_rr[pid] = (rot + i) % len(reps)
            get_metrics().inc("router.upstream_dials")
            return up
        raise _Unreachable(f"partition {pid} unreachable: {last}")


def main(argv: list[str]) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="merklekv_tpu router")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7400)
    p.add_argument(
        "--seeds",
        required=True,
        help="comma-separated node addresses to bootstrap the partition "
        "map from (any cluster member)",
    )
    p.add_argument(
        "--workers", type=int, default=0,
        help="io worker pool width (0 = auto)",
    )
    p.add_argument("--timeout", type=float, default=5.0)
    p.add_argument(
        "--cache-mb", type=float, default=0.0,
        help="hot-key read cache budget in MiB (0 = caching off)",
    )
    p.add_argument(
        "--cache-max-age-ms", type=float, default=2000.0,
        help="hard staleness bound: a cached answer older than this is "
        "never served (the vs= stamp's bound field)",
    )
    p.add_argument(
        "--broker", default="",
        help="replication broker host for event-driven cache "
        "invalidation (the same fabric the replica groups publish on)",
    )
    p.add_argument("--broker-port", type=int, default=0)
    p.add_argument(
        "--transport", default="framed", choices=["framed", "mqtt"],
    )
    p.add_argument(
        "--topic-prefix", default="",
        help="replication topic prefix (must match the cluster's "
        "[replication] topic_prefix)",
    )
    p.add_argument(
        "--metrics-port", type=int,
        help="serve Prometheus /metrics (+/healthz) on this HTTP port "
        "(-1: ephemeral)",
    )
    p.add_argument(
        "--legacy-threads", action="store_true",
        help="run the old thread-per-connection thin router instead "
        "(the measured A/B baseline; no pipelining, no cache)",
    )
    args = p.parse_args(argv)
    seeds = [s.strip() for s in args.seeds.split(",") if s.strip()]
    if args.legacy_threads:
        from merklekv_tpu.cluster.router import PartitionRouter

        router = PartitionRouter(
            args.host, args.port, seeds, timeout=args.timeout
        ).start()
    else:
        router = RequestPlaneRouter(
            args.host,
            args.port,
            seeds,
            timeout=args.timeout,
            workers=args.workers,
            cache_bytes=int(args.cache_mb * (1 << 20)),
            cache_max_age_ms=args.cache_max_age_ms,
            broker=args.broker or None,
            broker_port=args.broker_port,
            transport_kind=args.transport,
            topic_prefix=args.topic_prefix,
            metrics_port=args.metrics_port,
        ).start()
    print(
        f"merklekv_tpu router listening on {args.host}:{router.port} "
        f"({router.map.count} partitions, epoch {router.map.epoch})",
        flush=True,
    )
    if getattr(router, "metrics_port", None) is not None:
        print(f"metrics: http://127.0.0.1:{router.metrics_port}/metrics",
              flush=True)
    try:
        while True:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

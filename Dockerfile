# merklekv_tpu server image: native C++ runtime + Python control plane.
# Build:  docker build -t merklekv-tpu .
# Run:    docker run -p 7379:7379 merklekv-tpu
FROM python:3.12-slim AS build
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /app
COPY merklekv_tpu/ merklekv_tpu/
RUN make -C merklekv_tpu/native -j

FROM python:3.12-slim
WORKDIR /app
COPY --from=build /app/merklekv_tpu/ merklekv_tpu/
COPY configs/config.toml ./config.toml
ENV PYTHONPATH=/app
EXPOSE 7379
# The control plane (replication / anti-entropy / TPU data plane) activates
# from the config; the bare server needs only the stdlib.
ENTRYPOINT ["python", "-m", "merklekv_tpu"]
CMD ["--config", "config.toml", "--host", "0.0.0.0"]

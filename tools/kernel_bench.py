"""Pallas kernel microbenchmarks (real TPU): one JSON line per kernel.

Times the SHA-256 kernels in isolation plus both node-hash formulations at
a wide tree level, so kernel regressions are attributable without rerunning
the full north-star bench:

- leaf_digests_pallas   [N, B, 16] blocks -> [N, 8]
- node_pairs_pallas     strided even/odd split + pair kernel (the cost the
                        level kernel exists to avoid)
- node_level_pallas     contiguous adjacent-pair level kernel
- scan baselines        the portable lax.scan formulation for both shapes

Timing follows bench.py's discipline for the tunneled backend: each rep's
input is salted with the previous rep's output (defeats backend result
caching) and synchronization is a single tiny row fetch, not a bulk copy
of the result (a [4M, 8] fetch would otherwise dominate the kernel time).

Off-TPU this prints the scan baselines only, at smoke sizes. Interpret-mode
Pallas is NOT exercised: lowering the 64 unrolled rounds through the
interpreter takes XLA tens of minutes to compile even at tiny sizes (the
same reason kernel tests are TPU-gated in tests/test_sha256_pallas.py).

Usage:
    python tools/kernel_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Runnable as `python tools/kernel_bench.py` from anywhere: the package
# lives at the repo root, one level up from this file.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_salted(make_step, reps: int | None = None) -> float:
    """Median wall seconds per call.

    ``make_step() -> (step, salt0)`` where ``step(salt) -> out`` is jitted,
    folds the salt into its input, and returns an array whose first row
    feeds the next rep's salt. Sync is the 1-row fetch of that output.
    """
    if reps is None:
        reps = int(os.environ.get("MKV_KB_REPS", "20"))
    step, salt = make_step()
    out = step(salt)
    np.asarray(out[:1])  # compile + sync
    times = []
    for _ in range(reps):
        salt = out[0]
        t0 = time.perf_counter()
        out = step(salt)
        np.asarray(out[:1])
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from merklekv_tpu.merkle.packing import pack_leaves
    from merklekv_tpu.ops import sha256_pallas as sp
    from merklekv_tpu.ops.sha256 import sha256_blocks, sha256_node_pairs

    on_tpu = jax.default_backend() == "tpu"
    n = (1 << 22) if on_tpu else (1 << 10)  # 4M leaves / 2M pairs on chip

    rows = []

    # Leaf hashing: [n, B, 16] blocks. Salt perturbs one message word of
    # block 0 (the digest changes; the valid-block masking is untouched).
    keys = [b"kb:%09d" % i for i in range(n)]
    values = [b"v-%d" % (i % 7919) for i in range(n)]
    packed = pack_leaves(keys, values)
    blocks = jax.device_put(packed.blocks)
    nblocks = jax.device_put(packed.nblocks)

    def leaf_maker(hash_fn):
        def make():
            @jax.jit
            def step(salt):
                b = blocks.at[0, 0, :8].set(blocks[0, 0, :8] ^ salt)
                return hash_fn(b, nblocks)

            return step, jnp.zeros(8, jnp.uint32)

        return make

    if on_tpu:
        dt = _time_salted(leaf_maker(sp.leaf_digests_pallas))
        rows.append({"kernel": "leaf_digests_pallas", "n": n,
                     "keys_per_s": round(n / dt, 1), "ms": round(dt * 1e3, 3)})
    dt = _time_salted(leaf_maker(sha256_blocks))
    rows.append({"kernel": "sha256_blocks_scan", "n": n,
                 "keys_per_s": round(n / dt, 1), "ms": round(dt * 1e3, 3)})

    # Node formulations at one wide level: [n, 8] -> [n//2, 8]. Salt
    # perturbs row 0, so every rep hashes fresh data.
    rng = np.random.RandomState(5)
    level = jax.device_put(
        rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)
    )
    pairs = n // 2

    def level_maker(level_fn):
        def make():
            @jax.jit
            def step(salt):
                c = level.at[0].set(level[0] ^ salt)
                return level_fn(c)

            return step, jnp.zeros(8, jnp.uint32)

        return make

    if on_tpu:
        dt = _time_salted(level_maker(sp.node_level_pallas))
        rows.append({"kernel": "node_level_pallas", "pairs": pairs,
                     "pairs_per_s": round(pairs / dt, 1), "ms": round(dt * 1e3, 3)})
        dt = _time_salted(
            level_maker(lambda c: sp.node_pairs_pallas(c[0::2], c[1::2]))
        )
        rows.append({"kernel": "node_pairs_pallas_strided", "pairs": pairs,
                     "pairs_per_s": round(pairs / dt, 1), "ms": round(dt * 1e3, 3)})
    dt = _time_salted(
        level_maker(lambda c: sha256_node_pairs(c[0::2], c[1::2]))
    )
    rows.append({"kernel": "sha256_node_pairs_scan", "pairs": pairs,
                 "pairs_per_s": round(pairs / dt, 1), "ms": round(dt * 1e3, 3)})

    # Full tree build through the production dispatch (root is [8]; the
    # final level IS the tiny fetch).
    from merklekv_tpu.ops.dispatch import build_levels

    leaves = (sp.leaf_digests_pallas(blocks, nblocks) if on_tpu
              else sha256_blocks(blocks, nblocks))
    leaves = jax.device_put(np.asarray(leaves))

    def build_maker():
        @jax.jit
        def step(salt):
            lv = leaves.at[0].set(leaves[0] ^ salt)
            return build_levels(lv)[-1]

        return step, jnp.zeros(8, jnp.uint32)

    dt = _time_salted(build_maker)
    rows.append({"kernel": "build_levels_dispatch", "n": n,
                 "leaves_per_s": round(n / dt, 1), "ms": round(dt * 1e3, 3)})

    for r in rows:
        r["backend"] = jax.default_backend()
        print(json.dumps(r))
    if not on_tpu:
        print("# off-TPU smoke run: scan baselines only", file=sys.stderr)


if __name__ == "__main__":
    main()

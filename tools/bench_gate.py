"""Bench-regression gate: fail loudly when a round regresses >20%.

``BENCH_rNN.json`` records (committed per driver round) carry the headline
metric under ``parsed`` and one JSON line per side scenario in the stderr
``tail``. This tool extracts every scenario's primary metric from the two
newest rounds that produced usable numbers and exits 1 when any common
scenario regressed beyond the threshold — so a perf-eating change can't
ride a green CI into main.

Direction matters: throughput units (``keys/s``, ``events/s``,
``ops/s`` — e.g. the ``many_conn_throughput`` and ``overload_goodput``
scenarios) must not DROP; latency/size/overhead units (``ms``, ``us``,
``bytes``, ``%``) must not RISE. Rounds that crashed (rc != 0, no scenarios, null values)
are skipped rather than compared — a broken round is the driver's failure
signal, not a baseline; with fewer than two usable rounds the gate warns
and passes. Failed rounds carrying the structured ``error_kind`` verdict
(shared classifier, merklekv_tpu/utils/errorkind.py) are skipped WITH the
reason: ``environment`` reads as driver weather (BENCH_r05's wedged
backend init), ``code`` as something to look at.

Usage: ``python tools/bench_gate.py [--dir .] [--threshold 0.2] [files..]``
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional

__all__ = ["extract_scenarios", "round_weather", "lower_is_better",
           "compare", "main"]


def extract_scenarios(record: dict) -> dict[str, dict]:
    """Scenario records ({'metric', 'value', 'unit', ...}) from one
    BENCH_rNN.json: the headline under ``parsed`` plus every JSON line in
    the stderr ``tail``. Truncated tail lines (the driver keeps only the
    last N bytes) and non-JSON chatter are skipped silently."""
    out: dict[str, dict] = {}
    tail = record.get("tail") or ""
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            out[str(obj["metric"])] = obj
    parsed = record.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        out[str(parsed["metric"])] = parsed
    # Only scenarios with a usable number can gate.
    return {
        m: s
        for m, s in out.items()
        if isinstance(s.get("value"), (int, float)) and s["value"] > 0
    }


def round_weather(record: dict) -> Optional[str]:
    """The structured ``error_kind`` of a failed round, or None.

    bench.py classifies every whole-run failure through the shared
    environment|code table (merklekv_tpu/utils/errorkind.py) and stamps
    the verdict on the error record — a BENCH_r05-shaped round (wedged
    backend init, dead tunnel) then skips as ``environment`` WEATHER with
    the reason printed, instead of an anonymous "no usable scenarios".
    A ``code``-kind failure also skips (a broken round is never a
    baseline) but the verdict says someone should look at it."""
    for obj in ([record.get("parsed")] if isinstance(record.get("parsed"),
                                                    dict) else []) + [
        record
    ]:
        if obj.get("error") and obj.get("error_kind"):
            return str(obj["error_kind"])
    tail = record.get("tail") or ""
    # Newest-first: a round can emit several error records (an early
    # environment-kind backend-probe record, then a terminal code-kind
    # crash record from main()) — the TERMINAL verdict is the round's
    # verdict, so the last error_kind line wins.
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and obj.get("error") and obj.get(
            "error_kind"
        ):
            return str(obj["error_kind"])
    return None


def lower_is_better(metric: str, unit: str) -> bool:
    """Regression direction for a scenario's primary metric."""
    u = (unit or "").lower()
    # Per-op cost units (allocs/op, copies/op) carry a "/..." that is NOT
    # a rate: check them before the throughput rule.
    if metric.endswith("_per_op") or "/op" in u:
        return True
    if "/s" in u:
        return False  # throughput: higher is better
    if metric.endswith(("_ms", "_us", "_pct", "_bytes")):
        return True
    return any(tok in u for tok in ("ms", "us", "byte", "%", "seconds"))


def compare(
    prev: dict[str, dict], cur: dict[str, dict], threshold: float = 0.20
) -> list[str]:
    """Human-readable regression lines for every common scenario whose
    primary metric moved past ``threshold`` in the bad direction."""
    regressions = []
    for metric in sorted(set(prev) & set(cur)):
        pv, cv = float(prev[metric]["value"]), float(cur[metric]["value"])
        unit = str(cur[metric].get("unit", ""))
        if lower_is_better(metric, unit):
            change = cv / pv - 1.0
            if change > threshold:
                regressions.append(
                    f"{metric}: {pv:g} -> {cv:g} {unit} "
                    f"(+{change * 100:.1f}%, lower is better)"
                )
        else:
            change = 1.0 - cv / pv
            if change > threshold:
                regressions.append(
                    f"{metric}: {pv:g} -> {cv:g} {unit} "
                    f"(-{change * 100:.1f}%, higher is better)"
                )
    return regressions


def _load(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"# {path}: unreadable ({e})", file=sys.stderr)
        return None


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="bench_gate",
        description="compare the two newest usable BENCH_r*.json rounds "
        "and fail on >threshold regression in any scenario",
    )
    p.add_argument("files", nargs="*", help="explicit round files (sorted "
                   "oldest->newest); default: <dir>/BENCH_r*.json")
    p.add_argument("--dir", default=".", help="repo root to glob in")
    p.add_argument("--threshold", type=float, default=0.20)
    args = p.parse_args(argv)

    paths = args.files or sorted(
        glob.glob(os.path.join(args.dir, "BENCH_r*.json"))
    )
    usable: list[tuple[str, dict[str, dict]]] = []
    for path in paths:
        record = _load(path)
        if record is None:
            continue
        scenarios = extract_scenarios(record)
        if not scenarios:
            kind = round_weather(record)
            why = (
                f"error_kind={kind}"
                if kind
                else f"rc={record.get('rc')}"
            )
            tag = " as weather" if kind == "environment" else ""
            print(f"# {path}: no usable scenarios ({why}); skipped{tag}",
                  file=sys.stderr)
            continue
        usable.append((path, scenarios))
    if len(usable) < 2:
        print("bench gate: fewer than 2 usable rounds; nothing to compare")
        return 0
    (prev_path, prev), (cur_path, cur) = usable[-2], usable[-1]
    common = sorted(set(prev) & set(cur))
    print(f"bench gate: {prev_path} -> {cur_path}; "
          f"{len(common)} common scenarios "
          f"(threshold {args.threshold * 100:.0f}%)")
    regressions = compare(prev, cur, args.threshold)
    for line in regressions:
        print(f"REGRESSION {line}")
    if regressions:
        print(f"bench gate: FAILED ({len(regressions)} regression(s))")
        return 1
    print("bench gate: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())

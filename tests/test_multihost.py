"""Multi-host (DCN) SPMD: two real processes, one global mesh.

Spawns 2 worker processes, each with 4 virtual CPU devices; they form an
8-device jax cluster (jax.distributed) via merklekv_tpu.parallel.multihost,
lift host-local keyspace shards into global arrays, and run the fused
anti-entropy step — the cross-process analog of the reference's multi-node
sync fabric (/root/reference/src/sync.rs:150-214). Both processes must
report the SAME root, equal to the single-process CPU golden root over the
full keyspace, and the psum'd divergence counts must match the seeded
divergence.
"""

import socket
import subprocess
import sys

import numpy as np
import pytest

N_GLOBAL = 64  # keyspace size; 8 leaves per device on the 8-device mesh
R = 3          # replicas in the diff
DIVERGED = 5   # seeded divergent keys on replica 1

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

from merklekv_tpu.parallel import multihost, sharded_anti_entropy_step

pid = int(os.environ["MKV_PROCESS_ID"])
multihost.initialize()
assert multihost.is_initialized() and multihost.process_count() == 2

import numpy as np
from merklekv_tpu.merkle.jax_engine import leaf_digests
from merklekv_tpu.merkle.packing import pack_leaves

N, R, DIVERGED = {n}, {r}, {diverged}
keys = [b"mh:%05d" % i for i in range(N)]
values = [b"val-%d" % i for i in range(N)]

# Global truth, built identically on both processes (cheap at this size);
# each process then keeps only its contiguous half as ITS host-local rows.
packed = pack_leaves(keys, values)
digests = np.tile(np.asarray(leaf_digests(keys, values))[None], (R, 1, 1))
present = np.ones((R, N), bool)
digests[1, :DIVERGED, 0] ^= 0xDEAD  # replica 1 diverges on DIVERGED keys

lo, hi = (0, N // 2) if pid == 0 else (N // 2, N)
mesh = multihost.global_key_mesh()
blocks_g, nblocks_g, digests_g, present_g = multihost.lift_local_shards(
    mesh,
    packed.blocks[lo:hi],
    packed.nblocks[lo:hi],
    digests[:, lo:hi],
    present[:, lo:hi],
)
root, masks, counts = sharded_anti_entropy_step(
    mesh, blocks_g, nblocks_g, digests_g, present_g
)
from merklekv_tpu.ops.sha256 import digest_to_bytes

print("ROOT", digest_to_bytes(np.asarray(root)).hex(), flush=True)
print("COUNTS", ",".join(map(str, np.asarray(counts))), flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.integration
def test_two_process_cluster_agrees_with_golden(tmp_path):
    # Formerly xfail("Multiprocess computations aren't implemented on the
    # CPU backend") — that XlaRuntimeError came from executing the
    # all_gather/psum collectives with no cross-process CPU collectives
    # implementation configured. multihost.initialize now selects gloo
    # (jax_cpu_collectives_implementation) before jax.distributed
    # initializes, and the 2-process SPMD step runs for real.
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "mh_worker.py"
    worker.write_text(
        _WORKER.format(repo=repo, n=N_GLOBAL, r=R, diverged=DIVERGED)
    )
    port = _free_port()
    procs = []
    env_base = {
        k: v
        for k, v in os.environ.items()
        # Workers pick their own device count; drop the suite's 8-device
        # flag and the pinned platform.
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    for pid in range(2):
        env = dict(
            env_base,
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            MKV_COORDINATOR=f"127.0.0.1:{port}",
            MKV_NUM_PROCESSES="2",
            MKV_PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        # A dead coordinator leaves the other worker blocked in
        # jax.distributed.initialize — never orphan it on a failure path.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)

    roots, counts = [], []
    for out in outs:
        lines = dict(
            line.split(" ", 1) for line in out.strip().splitlines()
            if line.startswith(("ROOT", "COUNTS"))
        )
        roots.append(lines["ROOT"])
        counts.append(lines["COUNTS"])

    # Same replicated root and counts on every host.
    assert roots[0] == roots[1]
    assert counts[0] == counts[1] == f"0,{DIVERGED},0"

    # Cross-check against the single-process golden root (CPU core).
    from merklekv_tpu.merkle.cpu import build_levels
    from merklekv_tpu.merkle.encoding import leaf_hash

    keys = [b"mh:%05d" % i for i in range(N_GLOBAL)]
    values = [b"val-%d" % i for i in range(N_GLOBAL)]
    golden = build_levels([leaf_hash(k, v) for k, v in zip(keys, values)])[-1][0]
    assert roots[0] == golden.hex()


def test_initialize_requires_full_topology(monkeypatch):
    """Coordinator without process count / rank must fail with a clear
    configuration error, not a raw KeyError."""
    from merklekv_tpu.parallel import multihost

    monkeypatch.delenv("MKV_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("MKV_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="MKV_NUM_PROCESSES"):
        multihost.initialize(coordinator="127.0.0.1:1")
    with pytest.raises(ValueError, match="MKV_PROCESS_ID"):
        multihost.initialize(coordinator="127.0.0.1:1", num_processes=2)

"""Anti-entropy sync manager: one-way convergence, batching, periodic loop.

Reference semantics (sync.rs:56-87): after sync_once the local store equals
the remote peer — overwrites, additions, AND deletion of local-only keys.
"""

import time

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.cluster.sync import SyncManager
from merklekv_tpu.native_bindings import NativeEngine, NativeServer


@pytest.fixture
def two_nodes():
    nodes = []
    for _ in range(2):
        eng = NativeEngine("mem")
        srv = NativeServer(eng, "127.0.0.1", 0)
        srv.start()
        nodes.append((eng, srv))
    yield nodes
    for eng, srv in nodes:
        srv.close()
        eng.close()


def fill(eng, items):
    for k, v in items.items():
        eng.set(k.encode(), v.encode())


def test_sync_once_converges(two_nodes):
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    fill(remote_eng, {"shared": "remote-version", "remote-only": "r"})
    fill(local_eng, {"shared": "local-version", "local-only": "l"})

    mgr = SyncManager(local_eng, device="cpu")
    report = mgr.sync_once("127.0.0.1", remote_srv.port)

    assert local_eng.snapshot() == remote_eng.snapshot()
    assert report.divergent == 3
    assert report.set_keys == 2  # shared overwritten + remote-only added
    assert report.deleted_keys == 1  # local-only removed
    assert local_eng.merkle_root() == remote_eng.merkle_root()


def test_sync_identical_is_noop(two_nodes):
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    items = {f"same{i}": f"v{i}" for i in range(40)}
    fill(local_eng, items)
    fill(remote_eng, items)
    report = SyncManager(local_eng, device="cpu").sync_once(
        "127.0.0.1", remote_srv.port
    )
    assert report.divergent == 0
    assert report.set_keys == report.deleted_keys == 0
    # Equal roots short-circuit before any snapshot transfer.
    assert report.details == ["roots equal; no transfer"]
    assert report.remote_keys == 0  # never fetched


def test_sync_empty_remote_clears_local(two_nodes):
    (local_eng, _), (_, remote_srv) = two_nodes
    fill(local_eng, {"a": "1", "b": "2"})
    SyncManager(local_eng, device="cpu").sync_once("127.0.0.1", remote_srv.port)
    assert local_eng.dbsize() == 0


def test_sync_large_keyspace_batched_mget(two_nodes):
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    items = {f"bulk{i:05d}": f"value-{i}" for i in range(1500)}
    fill(remote_eng, items)
    mgr = SyncManager(local_eng, device="cpu", mget_batch=128)
    report = mgr.sync_once("127.0.0.1", remote_srv.port)
    assert report.set_keys == 1500
    assert local_eng.merkle_root() == remote_eng.merkle_root()


def test_sync_device_path_matches_cpu(two_nodes):
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    fill(remote_eng, {f"dk{i}": f"dv{i}" for i in range(64)})
    fill(local_eng, {"dk1": "stale", "extra": "x"})
    report = SyncManager(local_eng, device="tpu").sync_once(
        "127.0.0.1", remote_srv.port
    )
    assert local_eng.snapshot() == remote_eng.snapshot()
    assert report.deleted_keys == 1


def test_sync_command_over_protocol(two_nodes):
    """SYNC via the text protocol, wired through the cluster callback."""
    from merklekv_tpu.cluster.node import ClusterNode
    from merklekv_tpu.config import Config

    (local_eng, local_srv), (remote_eng, remote_srv) = two_nodes
    fill(remote_eng, {"proto": "synced"})
    node = ClusterNode(Config(), local_eng, local_srv)
    node.start()
    try:
        with MerkleKVClient("127.0.0.1", local_srv.port) as c:
            assert c.sync_with("127.0.0.1", remote_srv.port)
            assert c.get("proto") == "synced"
            # Unreachable peer -> ERROR (flags parsed; reference drops them)
            import merklekv_tpu.client as mc

            with pytest.raises(mc.ProtocolError):
                c.sync_with("127.0.0.1", 1)
    finally:
        node.stop()


def test_hash_first_fetches_only_divergent_values(two_nodes):
    """The core fix over the reference: bandwidth ∝ divergence, not keyspace.

    Reference sync ships the entire remote keyspace as values whenever roots
    differ (/root/reference/src/sync.rs:150-214). Here 1% divergence must
    fetch ~1% of values.
    """
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    items = {f"hf{i:05d}": f"v{i}" for i in range(1000)}
    fill(remote_eng, items)
    fill(local_eng, items)
    # Diverge 10 of 1000 keys (1%): 5 stale, 3 local-only, 2 missing locally.
    for i in range(5):
        local_eng.set(f"hf{i:05d}".encode(), b"stale")
    for i in range(3):
        local_eng.set(f"local-only-{i}".encode(), b"x")
    for i in range(5, 7):
        local_eng.delete(f"hf{i:05d}".encode())

    mgr = SyncManager(local_eng, device="cpu")
    report = mgr.sync_once("127.0.0.1", remote_srv.port)

    assert report.mode == "hash-paged"
    assert report.divergent == 10
    assert report.values_fetched == 7  # ONLY divergent remote keys travel
    assert report.set_keys == 7 and report.deleted_keys == 3
    assert local_eng.snapshot() == remote_eng.snapshot()
    assert local_eng.merkle_root() == remote_eng.merkle_root()


def test_full_flag_forces_snapshot_transfer(two_nodes):
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    items = {f"ff{i}": f"v{i}" for i in range(100)}
    fill(remote_eng, items)
    local_eng.set(b"ff0", b"stale")
    fill(local_eng, {k: v for k, v in items.items() if k != "ff0"})

    report = SyncManager(local_eng, device="cpu").sync_once(
        "127.0.0.1", remote_srv.port, full=True
    )
    assert report.mode == "full"
    assert report.values_fetched == 100  # whole keyspace travelled
    assert report.divergent == 1
    assert local_eng.snapshot() == remote_eng.snapshot()


def test_verify_flag_rechecks_roots(two_nodes):
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    fill(remote_eng, {"vk": "v"})
    report = SyncManager(local_eng, device="cpu").sync_once(
        "127.0.0.1", remote_srv.port, verify=True
    )
    assert report.verified is True
    # noop path reports verified too
    report = SyncManager(local_eng, device="cpu").sync_once(
        "127.0.0.1", remote_srv.port, verify=True
    )
    assert report.mode == "noop" and report.verified is True


def test_verify_failure_raises(two_nodes):
    """A repair that does not converge must surface through --verify."""

    class DroppingEngine:
        """Engine proxy whose writes vanish — sync can't actually repair."""

        def __init__(self, eng):
            self._eng = eng

        def __getattr__(self, name):
            return getattr(self._eng, name)

        def set(self, k, v):
            return True  # dropped

        def set_with_ts(self, k, v, ts):
            return True  # dropped — the hash-first repair path writes here

        def delete(self, k):
            return False

    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    fill(remote_eng, {"only-remote": "v"})
    mgr = SyncManager(DroppingEngine(local_eng), device="cpu")
    with pytest.raises(RuntimeError, match="verify failed"):
        mgr.sync_once("127.0.0.1", remote_srv.port, verify=True)
    assert mgr.last_report.verified is False


def test_sync_flags_over_protocol(two_nodes):
    from merklekv_tpu.cluster.node import ClusterNode
    from merklekv_tpu.config import Config

    (local_eng, local_srv), (remote_eng, remote_srv) = two_nodes
    fill(remote_eng, {"flagged": "yes"})
    node = ClusterNode(Config(), local_eng, local_srv)
    node.start()
    try:
        with MerkleKVClient("127.0.0.1", local_srv.port) as c:
            assert c.sync_with("127.0.0.1", remote_srv.port, full=True,
                               verify=True)
            assert c.get("flagged") == "yes"
            assert node.sync_manager.last_report.mode == "full"
            assert node.sync_manager.last_report.verified is True
    finally:
        node.stop()


@pytest.fixture
def three_nodes():
    nodes = []
    for _ in range(3):
        eng = NativeEngine("mem")
        srv = NativeServer(eng, "127.0.0.1", 0)
        srv.start()
        nodes.append((eng, srv))
    yield nodes
    for eng, srv in nodes:
        srv.close()
        eng.close()


def test_sync_multi_lww_arbitration(three_nodes):
    """One fused [R,N] diff + per-key LWW repair across all peers."""
    (local_eng, _), (p1_eng, p1_srv), (p2_eng, p2_srv) = three_nodes
    base = {f"mk{i:03d}": f"v{i}" for i in range(50)}
    fill(local_eng, base)
    fill(p1_eng, base)
    fill(p2_eng, base)
    # Both peers later overwrote mk001: their write is newer -> local
    # takes it.
    p1_eng.set(b"mk001", b"newer")
    p2_eng.set(b"mk001", b"newer")
    # A fresh local-only write: absence never wins, so it must survive.
    local_eng.set(b"only-local", b"x")
    # Peers hold a key local lacks.
    p1_eng.set(b"peer-key", b"shared")
    p2_eng.set(b"peer-key", b"shared")
    # Three-way conflict written in sequence: the LAST writer (p2) wins.
    local_eng.set(b"split", b"va")
    p1_eng.set(b"split", b"vb")
    p2_eng.set(b"split", b"vc")

    mgr = SyncManager(local_eng, device="cpu")
    peers = [f"127.0.0.1:{p1_srv.port}", f"127.0.0.1:{p2_srv.port}"]
    report = mgr.sync_multi(peers)

    assert local_eng.get(b"mk001") == b"newer"
    assert local_eng.get(b"only-local") == b"x"  # fresh write survives
    assert local_eng.get(b"peer-key") == b"shared"
    assert local_eng.get(b"split") == b"vc"  # newest write wins
    # The winner's timestamp propagated with the value.
    assert local_eng.get_ts(b"split") == p2_eng.get_ts(b"split")
    assert set(report.per_peer_divergent) == set(peers)
    assert report.divergent_union >= 4
    # Targeted transfer: only winning values travelled, never the keyspace.
    assert report.values_fetched <= report.divergent_union


def test_sync_multi_fresh_local_update_survives(three_nodes):
    """An update of an EXISTING key made only locally (newest ts) must not
    be rolled back by stale majority peers — the LWW guarantee."""
    (local_eng, _), (p1_eng, p1_srv), (p2_eng, p2_srv) = three_nodes
    for eng in (local_eng, p1_eng, p2_eng):
        eng.set(b"shared", b"old")
    local_eng.set(b"shared", b"fresh-update")  # newest write, local only

    mgr = SyncManager(local_eng, device="cpu")
    report = mgr.sync_multi(
        [f"127.0.0.1:{p1_srv.port}", f"127.0.0.1:{p2_srv.port}"]
    )
    assert local_eng.get(b"shared") == b"fresh-update"
    assert report.set_keys == 0


def test_sync_multi_skips_dead_peer(three_nodes):
    (local_eng, _), (p1_eng, p1_srv), _ = three_nodes
    fill(p1_eng, {"live": "yes"})
    mgr = SyncManager(local_eng, device="cpu")
    report = mgr.sync_multi(["127.0.0.1:1", f"127.0.0.1:{p1_srv.port}"])
    assert local_eng.get(b"live") == b"yes"
    assert any("unreachable" in d for d in report.details)


def test_sync_multi_all_nodes_converge(three_nodes):
    """Every node running the same deterministic cycle reaches one root."""
    engines = [e for e, _ in three_nodes]
    servers = [s for _, s in three_nodes]
    import random

    rng = random.Random(7)
    for i in range(60):
        owner = rng.randrange(3)
        engines[owner].set(b"ck%03d" % i, b"v%d" % i)
    for round_ in range(3):  # a few cycles to propagate transitively
        for me in range(3):
            peers = [
                f"127.0.0.1:{servers[j].port}" for j in range(3) if j != me
            ]
            SyncManager(engines[me], device="cpu").sync_multi(peers)
    roots = {e.merkle_root() for e in engines}
    assert len(roots) == 1
    # Union semantics: every disjoint write survives on every node.
    assert engines[0].dbsize() == 60


def test_periodic_loop_repairs(two_nodes):
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    fill(remote_eng, {"auto": "repaired"})
    mgr = SyncManager(local_eng, device="cpu")
    mgr.start_loop([f"127.0.0.1:{remote_srv.port}"], interval_seconds=0.05)
    try:
        deadline = time.time() + 5
        while time.time() < deadline:
            if local_eng.get(b"auto") == b"repaired":
                break
            time.sleep(0.02)
        assert local_eng.get(b"auto") == b"repaired"
    finally:
        mgr.stop()


# ---------------------------------------------------- tombstones & deletions


def test_leafhashes_carries_tombstones(two_nodes):
    """Wire format: deleted keys ride along as 'key - <ts>' lines."""
    (_, _), (remote_eng, remote_srv) = two_nodes
    remote_eng.set(b"live", b"v")
    remote_eng.set(b"dead", b"v")
    remote_eng.delete(b"dead")
    with MerkleKVClient("127.0.0.1", remote_srv.port) as c:
        hashes = c.leaf_hashes_ts()
    assert hashes["live"][0] is not None
    assert hashes["dead"][0] is None  # tombstone marker
    assert hashes["dead"][1] == remote_eng.tombstone_ts(b"dead")
    # leaf_hashes() (live view) filters tombstones out.
    with MerkleKVClient("127.0.0.1", remote_srv.port) as c:
        assert set(c.leaf_hashes()) == {"live"}


def test_dropped_delete_survives_multi_peer_sync(three_nodes):
    """THE tombstone scenario (reference can't do this — sync.rs:74-83
    resurrects any deletion a peer hasn't heard about): node A deletes a
    key but the DEL replication event is lost; multi-peer anti-entropy
    still converges the cluster to 'deleted', not back to the old value."""
    (a_eng, a_srv), (b_eng, b_srv), (c_eng, c_srv) = three_nodes
    engines = [a_eng, b_eng, c_eng]
    servers = [a_srv, b_srv, c_srv]
    base = {f"tk{i:02d}": f"v{i}" for i in range(20)}
    for e in engines:
        fill(e, base)
    time.sleep(0.002)  # ensure the deletion ts is strictly newer
    a_eng.delete(b"tk05")  # DEL event "dropped": B and C never hear of it

    for _ in range(3):
        for me in range(3):
            peers = [
                f"127.0.0.1:{servers[j].port}" for j in range(3) if j != me
            ]
            SyncManager(engines[me], device="cpu").sync_multi(peers)

    for e in engines:
        assert e.get(b"tk05") is None, "deletion was resurrected"
        assert e.tombstone_ts(b"tk05") is not None
    assert len({e.merkle_root() for e in engines}) == 1


def test_newer_write_beats_older_tombstone_multi(three_nodes):
    """A deletion only wins keys it is NEWER than: a later write to the
    same key must overturn an earlier tombstone."""
    (a_eng, a_srv), (b_eng, b_srv), (c_eng, c_srv) = three_nodes
    engines = [a_eng, b_eng, c_eng]
    servers = [a_srv, b_srv, c_srv]
    for e in engines:
        fill(e, {"wk": "old"})
    time.sleep(0.002)
    a_eng.delete(b"wk")  # tombstone at t1
    time.sleep(0.002)
    b_eng.set(b"wk", b"resurrected-on-purpose")  # newer write at t2 > t1

    for _ in range(3):
        for me in range(3):
            peers = [
                f"127.0.0.1:{servers[j].port}" for j in range(3) if j != me
            ]
            SyncManager(engines[me], device="cpu").sync_multi(peers)

    for e in engines:
        assert e.get(b"wk") == b"resurrected-on-purpose"
    assert len({e.merkle_root() for e in engines}) == 1


def test_pairwise_sync_adopts_remote_tombstone_ts(two_nodes):
    """Pairwise repair deletion adopts the PEER's tombstone timestamp, so
    the copied deletion keeps its LWW position instead of being stamped
    'now'."""
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    fill(local_eng, {"dk": "v", "keep": "x"})
    fill(remote_eng, {"keep": "x", "dk": "v"})
    remote_eng.delete(b"dk")
    remote_ts = remote_eng.tombstone_ts(b"dk")

    SyncManager(local_eng, device="cpu").sync_once("127.0.0.1", remote_srv.port)
    assert local_eng.get(b"dk") is None
    assert local_eng.tombstone_ts(b"dk") == remote_ts


def test_pairwise_mirror_delete_leaves_no_tombstone(two_nodes):
    """Deleting a local-only key because the peer merely LACKS it is a
    mirror copy, not a deletion event — no tombstone may be fabricated."""
    (local_eng, _), (remote_eng, remote_srv) = two_nodes
    fill(local_eng, {"only-local": "v", "shared": "x"})
    fill(remote_eng, {"shared": "x"})
    SyncManager(local_eng, device="cpu").sync_once("127.0.0.1", remote_srv.port)
    assert local_eng.get(b"only-local") is None
    assert local_eng.tombstone_ts(b"only-local") is None


def test_sync_multi_full_snapshot_fallback_peer(three_nodes):
    """A reachable peer whose LEAFHASHES fails still joins the cycle via
    the full-snapshot fallback (ts 0: contributes missing keys, never
    overwrites fresher state)."""
    from merklekv_tpu.client import MerkleKVClient as RealClient

    (local_eng, _), (p1_eng, p1_srv), _ = three_nodes
    fill(p1_eng, {"fb": "from-fallback"})
    local_eng.set(b"fresh", b"mine")

    mgr = SyncManager(local_eng, device="cpu")
    orig = RealClient.leaf_hashes_ts

    def broken(self, prefix=""):
        raise RuntimeError("LEAFHASHES unsupported")

    RealClient.leaf_hashes_ts = broken
    try:
        report = mgr.sync_multi([f"127.0.0.1:{p1_srv.port}"])
    finally:
        RealClient.leaf_hashes_ts = orig
    assert local_eng.get(b"fb") == b"from-fallback"  # union still grows
    assert local_eng.get(b"fresh") == b"mine"  # fallback never overwrites
    assert any("full snapshot" in d for d in report.details)


def test_sync_multi_randomized_converges_to_lww_merge(three_nodes):
    """Randomized stress of the vectorized arbitration: three engines with
    interleaved writes, deletions, and tombstones at explicit timestamps.
    After every node runs sync_multi against the others, all three must
    hold the same keyspace, and it must equal the brute-force
    (ts, liveness, digest) merge computed independently in Python."""
    import random

    from merklekv_tpu.merkle.encoding import leaf_hash

    engines = [e for e, _ in three_nodes]
    servers = [s for _, s in three_nodes]
    rng = random.Random(42)
    n_keys = 200
    # expected[key] = best (ts, live, digest, value) candidate
    expected: dict[bytes, tuple] = {}
    for i in range(n_keys):
        key = b"rz%04d" % i
        for slot, eng in enumerate(engines):
            roll = rng.random()
            ts = rng.randrange(1, 10**6)
            if roll < 0.55:
                val = b"v%d-%d" % (slot, rng.randrange(1000))
                eng.set_with_ts(key, val, ts)
                cand = (ts, 1, leaf_hash(key, val), val)
            elif roll < 0.75:
                eng.delete_with_ts(key, ts)
                cand = (ts, 0, b"", None)
            else:
                continue  # this replica never saw the key
            best = expected.get(key)
            if best is None or cand[:3] > best[:3]:
                expected[key] = cand

    addrs = [f"127.0.0.1:{srv.port}" for srv in servers]
    # Two rounds so second-hand state propagates everywhere.
    for _round in range(2):
        for me in range(3):
            peers = [addrs[p] for p in range(3) if p != me]
            SyncManager(engines[me], device="cpu").sync_multi(peers)

    want_live = {
        k: c[3] for k, c in expected.items() if c[1] == 1
    }
    for slot, eng in enumerate(engines):
        got = {k: v for k, v in eng.snapshot()}
        assert got == want_live, f"node {slot} diverged from LWW merge"


def test_sync_multi_corrupt_clock_tombstone_does_not_wedge(three_nodes):
    """A tombstone with ts >= 2^63 (corrupt clock) must lose gracefully in
    arbitration, not abort every cycle with OverflowError."""
    engines = [e for e, _ in three_nodes]
    servers = [s for _, s in three_nodes]
    huge = (1 << 63) + 5
    engines[0].delete_with_ts(b"wedge", huge)  # corrupt local tombstone
    engines[1].set_with_ts(b"wedge", b"sane", 1000)
    peers = [f"127.0.0.1:{servers[1].port}"]
    report = SyncManager(engines[0], device="cpu").sync_multi(peers)
    assert report.union_keys >= 1  # the cycle completed
    # The clamped tombstone (int64 max) still out-ranks the sane write.
    assert engines[0].get(b"wedge") is None

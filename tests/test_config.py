"""Config parsing: TOML schema, env-first secrets, interval wiring.

Reference analog: /root/reference/src/config.rs:171-227 (load test) plus the
top-level sync_interval_seconds semantics (config.rs:48-74).
"""

from merklekv_tpu.config import Config


def test_defaults():
    cfg = Config()
    assert cfg.port == 7379
    assert cfg.engine == "mem"
    assert cfg.anti_entropy.interval_seconds == 60.0


def test_top_level_sync_interval_seeds_anti_entropy():
    cfg = Config.from_dict({"sync_interval_seconds": 12})
    assert cfg.sync_interval_seconds == 12.0
    # Reference semantics: the top-level interval IS the sync cadence.
    assert cfg.anti_entropy.interval_seconds == 12.0


def test_explicit_anti_entropy_interval_wins():
    cfg = Config.from_dict(
        {
            "sync_interval_seconds": 12,
            "anti_entropy": {"interval_seconds": 3},
        }
    )
    assert cfg.sync_interval_seconds == 12.0
    assert cfg.anti_entropy.interval_seconds == 3.0


def test_full_table_parse(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text(
        """
host = "0.0.0.0"
port = 7380
engine = "log"
storage_path = "/tmp/x"
sync_interval_seconds = 30

[replication]
enabled = true
mqtt_broker = "broker.example"
mqtt_port = 1884
topic_prefix = "t"
client_id = "n1"
peer_list = ["a", "b"]

[anti_entropy]
enabled = true
peers = ["h:1", "h:2"]
multi_peer = true
"""
    )
    cfg = Config.load(str(p))
    assert cfg.host == "0.0.0.0"
    assert cfg.port == 7380
    assert cfg.engine == "log"
    assert cfg.replication.enabled
    assert cfg.replication.mqtt_port == 1884
    assert cfg.replication.peer_list == ["a", "b"]
    assert cfg.anti_entropy.enabled
    assert cfg.anti_entropy.peers == ["h:1", "h:2"]
    assert cfg.anti_entropy.multi_peer
    # No explicit [anti_entropy].interval_seconds: top-level seeds it.
    assert cfg.anti_entropy.interval_seconds == 30.0


def test_env_first_secrets(monkeypatch):
    monkeypatch.setenv("CLIENT_ID", "env-id")
    monkeypatch.setenv("CLIENT_PASSWORD", "env-pw")
    cfg = Config.from_dict({"replication": {"client_id": "file-id"}})
    assert cfg.replication.client_id == "env-id"
    assert cfg.replication.password == "env-pw"


def test_storage_defaults_off():
    cfg = Config()
    assert not cfg.storage.enabled
    assert cfg.storage.fsync == "interval"
    assert cfg.storage.verify == "repair"
    assert cfg.storage.snapshots_retained == 2


def test_storage_section_parse(tmp_path):
    p = tmp_path / "s.toml"
    p.write_text(
        """
storage_path = "./data"

[storage]
enabled = true
fsync = "always"
fsync_interval_seconds = 0.2
segment_bytes = 65536
compact_trigger_bytes = 1048576
snapshots_retained = 3
verify = "strict"
merkle_engine = "cpu"
snapshot_on_shutdown = false
"""
    )
    cfg = Config.load(str(p))
    assert cfg.storage.enabled
    assert cfg.storage.fsync == "always"
    assert cfg.storage.fsync_interval_seconds == 0.2
    assert cfg.storage.segment_bytes == 65536
    assert cfg.storage.compact_trigger_bytes == 1048576
    assert cfg.storage.snapshots_retained == 3
    assert cfg.storage.verify == "strict"
    assert cfg.storage.merkle_engine == "cpu"
    assert not cfg.storage.snapshot_on_shutdown


def test_storage_rejects_bad_enums():
    import pytest

    with pytest.raises(ValueError, match="fsync"):
        Config.from_dict({"storage": {"fsync": "sometimes"}})
    with pytest.raises(ValueError, match="verify"):
        Config.from_dict({"storage": {"verify": "hope"}})


def test_storage_rejects_bad_merkle_engine():
    import pytest

    with pytest.raises(ValueError, match="merkle_engine"):
        Config.from_dict({"storage": {"merkle_engine": "device"}})


def test_server_overload_section_parse(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text(
        """
[server]
io_threads = 6
max_connections = 4096
max_pipeline = 256
memory_soft_bytes = 1073741824
memory_hard_bytes = 2147483648
recovery_ratio = 0.9
watermark_interval_seconds = 0.5

[replication]
max_skew_ms = 60000

[storage]
disk_free_soft_bytes = 268435456
disk_free_hard_bytes = 67108864
"""
    )
    cfg = Config.load(str(p))
    assert cfg.server.io_threads == 6
    assert cfg.server.max_connections == 4096
    assert cfg.server.max_pipeline == 256
    assert cfg.server.memory_soft_bytes == 1 << 30
    assert cfg.server.memory_hard_bytes == 2 << 30
    assert cfg.server.recovery_ratio == 0.9
    assert cfg.server.watermark_interval_seconds == 0.5
    assert cfg.replication.max_skew_ms == 60000
    assert cfg.storage.disk_free_soft_bytes == 256 << 20
    assert cfg.storage.disk_free_hard_bytes == 64 << 20


def test_server_overload_defaults_off():
    cfg = Config.from_dict({})
    assert cfg.server.io_threads == 0  # 0 = hardware concurrency
    assert cfg.server.max_connections == 0
    assert cfg.server.memory_soft_bytes == 0
    assert cfg.server.memory_hard_bytes == 0
    assert cfg.storage.disk_free_soft_bytes == 0
    assert cfg.storage.disk_free_hard_bytes == 0
    assert cfg.replication.max_skew_ms == 300_000  # skew guard defaults ON


def test_server_overload_validation():
    import pytest

    with pytest.raises(ValueError, match="max_connections"):
        Config.from_dict({"server": {"max_connections": -1}})
    with pytest.raises(ValueError, match="io_threads"):
        Config.from_dict({"server": {"io_threads": -1}})
    with pytest.raises(ValueError, match="memory_soft_bytes"):
        # soft above hard: shedding could never precede read-only.
        Config.from_dict(
            {"server": {"memory_soft_bytes": 100, "memory_hard_bytes": 50}}
        )
    with pytest.raises(ValueError, match="recovery_ratio"):
        Config.from_dict({"server": {"recovery_ratio": 1.5}})
    with pytest.raises(ValueError, match="watermark_interval_seconds"):
        Config.from_dict({"server": {"watermark_interval_seconds": 0}})
    with pytest.raises(ValueError, match="max_skew_ms"):
        Config.from_dict({"replication": {"max_skew_ms": -5}})
    with pytest.raises(ValueError, match="disk_free_soft_bytes"):
        # soft is the EARLIER (higher free-bytes) warning.
        Config.from_dict(
            {
                "storage": {
                    "disk_free_soft_bytes": 10,
                    "disk_free_hard_bytes": 100,
                }
            }
        )

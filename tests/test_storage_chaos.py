"""Crash chaos for the durable subsystem: SIGKILL mid-write-burst, restart
from disk, verify the recovered root against the on-disk truth, and let
anti-entropy re-converge a cluster around the crash.

The acceptance shape from the ISSUE: PeerProcessKiller kills a node whose
WAL is mid-burst; the node restarts from snapshot+WAL; the recovered root
hash equals what `walcheck` computes offline from the surviving bytes; and
a 2-node cluster converges again without manual intervention.

Fast fixed cases stay in tier-1; the repeated kill/restart soak is `slow`.
"""

import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from merklekv_tpu.client import MerkleKVClient
from merklekv_tpu.storage import node_data_dir
from merklekv_tpu.storage import wal as walmod
from merklekv_tpu.storage.walcheck import check_dir, replay_root_hex
from merklekv_tpu.testing.faults import PeerProcessKiller

pytestmark = pytest.mark.integration

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(args):
    env = dict(os.environ, PYTHONPATH=REPO, MERKLEKV_JAX_PLATFORM="cpu")
    return subprocess.Popen(
        [sys.executable, *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        sk = socket.socket()
        sk.bind(("127.0.0.1", 0))
        ports.append(sk.getsockname()[1])
        socks.append(sk)
    for sk in socks:
        sk.close()
    return ports


def _await_ready(proc, port, timeout=20):
    line = proc.stdout.readline()
    assert "listening on" in line, f"unexpected startup line: {line!r}"
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"port {port} never came up")


def _reap(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()


def _storage_toml(path, port, data_dir, extra=""):
    path.write_text(
        f"""
host = "127.0.0.1"
port = {port}
engine = "mem"
storage_path = "{data_dir}"

[storage]
enabled = true
fsync = "always"
merkle_engine = "cpu"
{extra}
"""
    )
    return str(path)


def _wal_payload_bytes(node_dir):
    """Bytes of framed records on disk (beyond per-segment magic)."""
    total = 0
    for _, p in walmod.list_segments(node_dir):
        total += max(0, os.path.getsize(p) - len(walmod.SEGMENT_MAGIC))
    return total


def _burst_writer(port, key_fmt, stop_on_error=True):
    """Background writer hammering SET on one connection; returns a dict
    whose 'acked' field grows with every acknowledged write."""
    state = {"acked": 0, "done": threading.Event()}

    def run():
        try:
            with MerkleKVClient("127.0.0.1", port) as c:
                for i in range(200_000):
                    c.set(key_fmt % i, f"val-{i}")
                    state["acked"] += 1
        except Exception:
            pass  # the connection dies at the kill — expected
        finally:
            state["done"].set()

    threading.Thread(target=run, daemon=True).start()
    return state


def test_kill9_midburst_recovered_root_matches_disk(tmp_path):
    """Tier-1 acceptance core: SIGKILL mid-burst, walcheck the surviving
    bytes offline, restart, and the served HASH equals the offline root —
    recovery restored exactly the durable prefix, verified via the stamped
    snapshot + WAL replay, nothing invented and nothing lost."""
    (port,) = _free_ports(1)
    data = tmp_path / "data"
    cfg = _storage_toml(tmp_path / "node.toml", port, data)
    node_dir = node_data_dir(str(data), port)

    p = _spawn(["-m", "merklekv_tpu", "--config", cfg])
    try:
        _await_ready(p, port)
        state = _burst_writer(port, "cr:%06d")
        killer = PeerProcessKiller(p)
        # Kill only once a healthy chunk of the burst is framed on disk, so
        # the recovery below demonstrably restores a non-trivial prefix.
        killed = killer.kill_when(
            lambda: state["acked"] >= 200 and _wal_payload_bytes(node_dir) > 2048,
            timeout=30,
        )
        state["done"].wait(timeout=10)
        assert killed, f"only {state['acked']} acks before deadline"
        acked = state["acked"]
    finally:
        _reap([p])

    # Offline truth from the surviving bytes (torn tail allowed: that is
    # the crash signature, not corruption).
    report = check_dir(node_dir)
    assert not report["errors"], report["errors"]
    expected_root = report["replay_root"]
    durable_keys = report["live_keys"]
    assert durable_keys > 0

    p2 = _spawn(["-m", "merklekv_tpu", "--config", cfg])
    try:
        _await_ready(p2, port)
        with MerkleKVClient("127.0.0.1", port) as c:
            assert c.hash() == expected_root
            keys = c.scan("cr:")
            assert len(keys) == durable_keys
            # Write-order contiguity: the WAL drains the event queue in seq
            # order, so the durable set is exactly a prefix of the burst.
            idxs = sorted(int(k.split(":")[1]) for k in keys)
            assert idxs == list(range(len(idxs)))
            assert len(idxs) <= acked + 1
            # The recovered node keeps serving writes durably.
            c.set("post-recovery", "alive")
            assert c.get("post-recovery") == "alive"
    finally:
        _reap([p2])


def test_kill9_recovery_then_anti_entropy_reconverges(tmp_path):
    """The full acceptance loop: kill -9 one node of a 2-node anti-entropy
    pair mid-burst, restart it from disk, and the cluster converges to one
    root without manual intervention — the durable prefix survives the
    crash locally, the lost tail plus the peer's writes arrive via sync."""
    port_a, port_b = _free_ports(2)
    data = tmp_path / "data"
    # multi_peer: the fused LWW arbitration mode — pairwise mode is strict
    # local := peer and would discard whichever side's disjoint writes the
    # last cycle overwrote.
    ae = """
[anti_entropy]
enabled = true
interval_seconds = 0.3
engine = "cpu"
multi_peer = true
peers = ["127.0.0.1:%d"]
"""
    cfg_a = _storage_toml(
        tmp_path / "a.toml", port_a, data, extra=ae % port_b
    )
    cfg_b = _storage_toml(
        tmp_path / "b.toml", port_b, data, extra=ae % port_a
    )

    pa = _spawn(["-m", "merklekv_tpu", "--config", cfg_a])
    pb = _spawn(["-m", "merklekv_tpu", "--config", cfg_b])
    pa2 = None
    try:
        _await_ready(pa, port_a)
        _await_ready(pb, port_b)

        state = _burst_writer(port_a, "burst:%06d")
        killer = PeerProcessKiller(pa)
        node_a_dir = node_data_dir(str(data), port_a)
        killed = killer.kill_when(
            lambda: state["acked"] >= 150
            and _wal_payload_bytes(node_a_dir) > 1024,
            timeout=30,
        )
        state["done"].wait(timeout=10)
        assert killed

        # Disjoint writes land on B while A is down.
        with MerkleKVClient("127.0.0.1", port_b) as cb:
            for i in range(20):
                cb.set(f"bonly:{i:03d}", f"bv-{i}")

        pa2 = _spawn(["-m", "merklekv_tpu", "--config", cfg_a])
        _await_ready(pa2, port_a)

        with MerkleKVClient("127.0.0.1", port_a) as ca, MerkleKVClient(
            "127.0.0.1", port_b
        ) as cb:
            # A restarted from disk with a verified prefix of the burst.
            recovered = len(ca.scan("burst:"))
            assert recovered > 0
            deadline = time.time() + 30
            while time.time() < deadline:
                if (
                    ca.hash() == cb.hash()
                    and ca.get("bonly:000") is not None
                ):
                    break
                time.sleep(0.2)
            assert ca.hash() == cb.hash(), "cluster failed to re-converge"
            # Both directions repaired: B holds A's durable burst prefix,
            # A holds B's solo writes.
            assert ca.get("bonly:019") == "bv-19"
            assert len(cb.scan("burst:")) >= recovered
    finally:
        _reap([p for p in (pa, pb, pa2) if p is not None])


@pytest.mark.slow
def test_soak_repeated_kill_restart_cycles(tmp_path):
    """Crash-recovery soak: several kill -9 / restart cycles against one
    data dir, each mid-burst. Every recovery must verify (no walcheck
    errors) and serve exactly the on-disk root, with the keyspace growing
    monotonically across cycles."""
    (port,) = _free_ports(1)
    data = tmp_path / "data"
    cfg = _storage_toml(
        tmp_path / "node.toml",
        port,
        data,
        # Tighter segments + trigger so the soak exercises rotation and
        # background compaction under crash pressure too.
        extra="segment_bytes = 8192\ncompact_trigger_bytes = 32768\n",
    )
    node_dir = node_data_dir(str(data), port)

    prev_keys = 0
    for cycle in range(4):
        p = _spawn(["-m", "merklekv_tpu", "--config", cfg])
        try:
            _await_ready(p, port)
            state = _burst_writer(port, f"c{cycle}:%06d")
            killer = PeerProcessKiller(p)
            baseline = _wal_payload_bytes(node_dir)
            killed = killer.kill_when(
                lambda: state["acked"] >= 150
                and _wal_payload_bytes(node_dir) > baseline + 1024,
                timeout=30,
            )
            state["done"].wait(timeout=10)
            assert killed, f"cycle {cycle}: no kill"
        finally:
            _reap([p])

        report = check_dir(node_dir)
        assert not report["errors"], (cycle, report["errors"])
        assert report["live_keys"] > prev_keys
        prev_keys = report["live_keys"]
        expected_root = report["replay_root"]

        p2 = _spawn(["-m", "merklekv_tpu", "--config", cfg])
        try:
            _await_ready(p2, port)
            with MerkleKVClient("127.0.0.1", port) as c:
                assert c.hash() == expected_root, f"cycle {cycle}"
                assert c.dbsize() == prev_keys
        finally:
            _reap([p2])
    assert replay_root_hex(node_dir) is not None

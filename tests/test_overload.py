"""Overload protection & graceful degradation (ISSUE 8).

The degradation ladder (live -> shedding -> read_only -> draining), native
admission control, memory/disk watermarks, the WAL errno-injection seam,
the LWW clock-skew guard, typed client errors, and the overload chaos
acceptance paths: a connection flood answers BUSY while established
connections keep serving; a disk-full write burst degrades the node to
read-only and recovers bit-identically once space returns; a future-ts
poison frame is clamped and repaired.
"""

import socket
import statistics
import threading
import time

import pytest

from merklekv_tpu.client import (
    MerkleKVClient,
    ConnectionError as MKVConnectionError,
    ProtocolError,
    ReadOnlyError,
    ServerBusyError,
)
from merklekv_tpu.cluster.overload import (
    DRAINING,
    LIVE,
    READ_ONLY,
    SHEDDING,
    DegradationLadder,
    OverloadMonitor,
)
from merklekv_tpu.config import Config, ServerConfig, StorageConfig
from merklekv_tpu.native_bindings import NativeEngine, NativeServer
from merklekv_tpu.utils.tracing import get_metrics


@pytest.fixture
def server():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0)
    srv.start()
    yield eng, srv
    srv.close()
    eng.close()


def _counter(name: str) -> int:
    return int(get_metrics().snapshot()["counters"].get(name, 0))


# ------------------------------------------------------- admission control

def _ping_p50_s(client: MerkleKVClient, n: int = 30) -> float:
    samples = []
    for _ in range(n):
        t0 = time.perf_counter()
        client.ping()
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def test_connection_flood_answers_busy_within_one_rtt(server):
    """Past max_connections every accept is answered BUSY and closed in
    the accept loop itself (no handler thread), and established
    connections' latency stays within 2x their pre-flood baseline."""
    eng, srv = server
    srv.set_limits(max_connections=2)
    a = MerkleKVClient("127.0.0.1", srv.port, timeout=5).connect()
    b = MerkleKVClient("127.0.0.1", srv.port, timeout=5).connect()
    try:
        assert a.ping().startswith("PONG")
        assert b.ping().startswith("PONG")
        base_p50 = _ping_p50_s(a)

        # Flood: every excess connect is answered within one RTT.
        for _ in range(20):
            t0 = time.perf_counter()
            c = MerkleKVClient("127.0.0.1", srv.port, timeout=2).connect()
            try:
                line = c._read_line()
                assert line.startswith("ERROR BUSY connections"), line
            finally:
                c.close()
            assert time.perf_counter() - t0 < 2.0

        # The typed path: sending a request on a flooded connection reads
        # the unsolicited BUSY answer as the response -> ServerBusyError,
        # and the socket is already closed server-side.
        c = MerkleKVClient("127.0.0.1", srv.port, timeout=2).connect()
        with pytest.raises(ServerBusyError):
            c.ping()
        with pytest.raises(MKVConnectionError):
            c.ping()
        c.close()

        # Established connections kept serving through the flood.
        flood_stop = threading.Event()

        def flood() -> None:
            while not flood_stop.is_set():
                try:
                    s = socket.create_connection(
                        ("127.0.0.1", srv.port), timeout=1
                    )
                    s.recv(64)
                    s.close()
                except OSError:
                    pass

        t = threading.Thread(target=flood, daemon=True)
        t.start()
        try:
            during_p50 = _ping_p50_s(a)
        finally:
            flood_stop.set()
            t.join(timeout=5)
        assert during_p50 <= max(2 * base_p50, 0.010), (
            f"p50 {during_p50 * 1e6:.0f}us vs baseline "
            f"{base_p50 * 1e6:.0f}us under flood"
        )
        stats = a.stats()
        assert int(stats["busy_rejected_connections"]) >= 21
    finally:
        a.close()
        b.close()


def test_pipeline_budget_closes_hostile_pipeliner(server):
    """A connection buffering more unanswered pipelined commands than its
    in-flight budget is answered BUSY and closed."""
    eng, srv = server
    srv.set_limits(max_connections=0, max_pipeline=8)
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
    try:
        s.sendall(b"PING\r\n" * 50)
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
        assert b"ERROR BUSY pipeline" in data
    finally:
        s.close()
    # A polite pipeliner under the budget is untouched.
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        assert c.pipeline(["PING"] * 8) == ["PONG "] * 8
        assert int(c.stats()["pipeline_rejected"]) >= 1


# --------------------------------------------------- degradation ladder

def test_degradation_gate_sheds_writes_keeps_reads(server):
    """shedding: writes BUSY (retryable), reads open; read_only: writes
    READONLY; management plane (STATS/PING) open throughout; counters on
    STATS; back to live serves everything."""
    eng, srv = server
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.set("k", "v")
        srv.set_degradation(1, 1)  # shedding / memory
        with pytest.raises(ServerBusyError):
            c.set("k2", "v")
        with pytest.raises(ServerBusyError):
            c.delete("k")
        assert c.get("k") == "v"  # reads open
        assert c.ping().startswith("PONG")
        srv.set_degradation(2, 2)  # read_only / disk
        with pytest.raises(ReadOnlyError):
            c.set("k3", "v")
        assert c.get("k") == "v"
        stats = c.stats()
        assert int(stats["shed_commands"]) >= 2
        assert int(stats["readonly_commands"]) >= 1
        assert stats["degradation"] == "2"
        srv.set_degradation(0, 0)
        c.set("k4", "v4")
        assert c.get("k4") == "v4"


def test_draining_refuses_new_connections(server):
    eng, srv = server
    keep = MerkleKVClient("127.0.0.1", srv.port).connect()
    try:
        # Round-trip BEFORE draining: connect() only completes the kernel
        # handshake — without this the accept loop can process the socket
        # after the rung flips and refuse it as a NEW connection.
        assert keep.ping().startswith("PONG")
        srv.set_degradation(3, 3)  # draining
        c = MerkleKVClient("127.0.0.1", srv.port, timeout=2).connect()
        assert c._read_line().startswith("ERROR BUSY draining")
        c.close()
        # Established connection: reads still served while draining.
        assert keep.get("nope") is None
        with pytest.raises(ReadOnlyError):
            keep.set("x", "y")
        srv.set_degradation(0, 0)
    finally:
        keep.close()


def test_ladder_folds_max_of_sources():
    ladder = DegradationLadder()
    assert ladder.state() == (LIVE, "")
    ladder.set_source("memory", SHEDDING, "memory")
    assert ladder.state() == (SHEDDING, "memory")
    ladder.set_source("disk", READ_ONLY, "disk")
    assert ladder.state() == (READ_ONLY, "disk")
    ladder.set_source("disk", LIVE)
    assert ladder.state() == (SHEDDING, "memory")
    ladder.set_source("memory", LIVE)
    assert ladder.state() == (LIVE, "")
    assert ladder.name() == "live"


# ------------------------------------------------------ memory watermarks

def test_memory_watermark_shedding_readonly_and_hysteresis(server):
    """The monitor walks the node up the ladder as engine bytes cross the
    soft then hard watermark, and back down only past the hysteresis
    band (watermark * recovery_ratio)."""
    eng, srv = server
    base = eng.memory_usage()
    cfg = ServerConfig(
        memory_soft_bytes=base + 4096,
        memory_hard_bytes=base + 8192,
        recovery_ratio=0.5,
    )
    mon = OverloadMonitor(DegradationLadder(), eng, srv, cfg)
    # Not started: poll_once() drives it deterministically.
    assert mon.poll_once() == LIVE
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        c.set("small", "x")
        assert mon.poll_once() == LIVE
        # Cross the soft watermark.
        for i in range(5):
            c.set(f"soft:{i}", "y" * 1024)
        assert mon.poll_once() == SHEDDING
        with pytest.raises(ServerBusyError) as ei:
            c.set("shed", "v")
        assert "memory" in str(ei.value)
        assert c.get("small") == "x"
        # Cross the hard watermark (engine-direct: the server sheds
        # client writes, exactly why runaway growth must come from
        # elsewhere — replication applies, repairs).
        for i in range(5):
            eng.set(f"hard:{i}".encode(), b"z" * 1024)
        assert mon.poll_once() == READ_ONLY
        with pytest.raises(ReadOnlyError):
            c.set("ro", "v")
        # Recovery with hysteresis: dropping just below hard is NOT
        # enough (recovery_ratio 0.5 -> must fall below half).
        eng.delete_quiet(b"hard:0")
        assert mon.poll_once() == READ_ONLY
        for i in range(1, 5):
            eng.delete_quiet(f"hard:{i}".encode())
        for i in range(3):
            eng.delete_quiet(f"soft:{i}".encode())
        # Now ~2 KiB over base: below hard*0.5 (4 KiB over base)? hard/2
        # relative math: usage must be < (base+8192)*0.5 in absolute
        # terms only if base tiny — with base ~0 these bounds hold.
        level = mon.poll_once()
        assert level in (SHEDDING, LIVE)
        for i in range(3, 5):
            eng.delete_quiet(f"soft:{i}".encode())
        eng.delete_quiet(b"small")
        eng.delete_quiet(b"shed")
        assert mon.poll_once() == LIVE
        c.set("after", "v")
        assert c.get("after") == "v"


def test_memory_watermark_env_hook(server, monkeypatch):
    """MKV_MAX_ENGINE_BYTES forces the hard watermark (soft = half) —
    the chaos suite's deterministic memory-fault hook."""
    eng, srv = server
    monkeypatch.setenv("MKV_MAX_ENGINE_BYTES", "2048")
    mon = OverloadMonitor(
        DegradationLadder(), eng, srv, ServerConfig()
    )
    assert mon.poll_once() == LIVE
    for i in range(3):
        eng.set(f"b:{i}".encode(), b"x" * 1024)
    assert mon.poll_once() == READ_ONLY
    eng.truncate()
    assert mon.poll_once() == LIVE


# --------------------------------------------------------- typed errors

class _CannedServer:
    """One-shot TCP server answering every request line with a fixed
    response — the degraded-server double for client typing tests."""

    def __init__(self, responses: list[bytes]) -> None:
        self._responses = list(responses)
        self._sock = socket.socket()
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(4)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        try:
            conn, _ = self._sock.accept()
        except OSError:
            return
        buf = b""
        while self._responses:
            try:
                data = conn.recv(65536)
            except OSError:
                break
            if not data:
                break
            buf += data
            while b"\n" in buf and self._responses:
                _, _, buf = buf.partition(b"\n")
                conn.sendall(self._responses.pop(0))
        conn.close()

    def close(self) -> None:
        self._sock.close()
        self._thread.join(timeout=5)


def test_sync_client_raises_typed_busy_and_readonly():
    srv = _CannedServer(
        [b"ERROR BUSY memory retry\r\n", b"ERROR READONLY disk\r\n"]
    )
    try:
        with MerkleKVClient("127.0.0.1", srv.port) as c:
            with pytest.raises(ServerBusyError) as busy:
                c.set("k", "v")
            with pytest.raises(ReadOnlyError) as ro:
                c.set("k", "v")
        # Both stay ProtocolError subclasses: existing handlers keep
        # working, new callers get the retryability signal.
        assert isinstance(busy.value, ProtocolError)
        assert isinstance(ro.value, ProtocolError)
        assert not isinstance(ro.value, ServerBusyError)
    finally:
        srv.close()


def test_async_client_raises_typed_busy_and_readonly():
    import asyncio

    from merklekv_tpu.client import AsyncMerkleKVClient

    srv = _CannedServer(
        [b"ERROR BUSY connections retry\r\n", b"ERROR READONLY draining\r\n"]
    )

    async def run() -> None:
        async with AsyncMerkleKVClient("127.0.0.1", srv.port) as c:
            with pytest.raises(ServerBusyError):
                await c.set("k", "v")
            with pytest.raises(ReadOnlyError):
                await c.set("k", "v")

    try:
        asyncio.run(run())
    finally:
        srv.close()


def test_retry_policy_treats_busy_as_retryable():
    from merklekv_tpu.cluster.retry import RETRYABLE_ERRORS, SERVER_BUSY

    assert ServerBusyError in RETRYABLE_ERRORS
    assert ReadOnlyError not in RETRYABLE_ERRORS
    calls = {"n": 0}

    def flaky() -> str:
        calls["n"] += 1
        if calls["n"] < 3:
            raise ServerBusyError("BUSY memory retry")
        return "ok"

    fast = SERVER_BUSY.with_overrides(first_delay=0.001, max_delay=0.002)
    assert fast.run(flaky, retry_on=RETRYABLE_ERRORS) == "ok"
    assert calls["n"] == 3


# ------------------------------------------------ WAL errno injection seam

def test_wal_errno_injector_write_and_fsync(tmp_path):
    import errno

    from merklekv_tpu.storage.wal import (
        OP_SET,
        StorageFullError,
        WalRecord,
        WalWriter,
    )
    from merklekv_tpu.testing.faults import WalErrnoInjector

    w = WalWriter(str(tmp_path), 0, fsync_policy="always")
    w.append(WalRecord(OP_SET, b"pre", b"1", 1))
    inj = WalErrnoInjector(fail_write_at=2).install()
    try:
        # Injector counts from install: write 1 ok, write 2 on fails.
        w.append(WalRecord(OP_SET, b"ok", b"2", 2))
        with pytest.raises(StorageFullError) as ei:
            w.append(WalRecord(OP_SET, b"boom", b"3", 3))
        assert ei.value.errno == errno.ENOSPC
        with pytest.raises(StorageFullError):
            w.append(WalRecord(OP_SET, b"boom2", b"4", 4))
        inj.heal()
        w.append(WalRecord(OP_SET, b"post", b"5", 5))
    finally:
        inj.uninstall()
        w.close()
    # fsync-side injection, EIO flavor, exactly-once. The writer is
    # created BEFORE install (segment creation fsyncs too — a real full
    # disk fails there as well, but this case targets the steady state).
    w2 = WalWriter(str(tmp_path / "b"), 0, fsync_policy="interval")
    inj2 = WalErrnoInjector(
        fail_fsync_at=1, errno_=errno.EIO, fail_count=1
    ).install()
    try:
        w2.append(WalRecord(OP_SET, b"k", b"v", 1))
        with pytest.raises(StorageFullError):
            w2.fsync()
        w2.append(WalRecord(OP_SET, b"k2", b"v", 2))
        assert w2.fsync() is True  # fail_count=1: a transient blip
        w2.close()
    finally:
        inj2.uninstall()


def test_store_survives_disk_full_and_recovers(tmp_path):
    """ENOSPC mid-burst: the drain path swallows the typed error (no dead
    threads), the store reports read-only to the overload monitor, and
    after the disk heals the probe recovers it and a re-anchor snapshot
    restores durability of what the engine kept."""
    from merklekv_tpu.storage.store import DurableStore
    from merklekv_tpu.testing.faults import WalErrnoInjector

    eng = NativeEngine("mem")
    st = DurableStore(eng, StorageConfig(), str(tmp_path))
    st.recover()
    drops0 = _counter("storage.records_dropped")
    # Mirror every record into the engine too (the real flows do: the
    # engine is written first, then journaled) — the re-anchor snapshot
    # captures ENGINE state, so only engine-resident keys can recover.
    eng.set_with_ts(b"pre", b"1", 1)
    st.record_set(b"pre", b"1", 1)
    inj = WalErrnoInjector(fail_write_at=2).install()
    try:
        eng.set_with_ts(b"ok", b"2", 2)
        st.record_set(b"ok", b"2", 2)  # write 1 since install
        eng.set_with_ts(b"lost", b"3", 3)
        st.record_set(b"lost", b"3", 3)  # fails inside; must NOT raise
        assert st.storage_full
        assert st.overload_level() == (2, "disk")
        assert _counter("storage.records_dropped") > drops0
        # Still full: the recovery probe fails through the same seam.
        st._check_disk()
        assert st.storage_full
        inj.heal()
        st._check_disk()
        assert not st.storage_full
        assert st.overload_level() == (0, "")
        assert st._snapshot_requested  # re-anchor pending
        # The re-anchor snapshot captures the engine state the journal
        # missed: recovery from disk now restores the dropped record.
        st.snapshot_now()
        st._snapshot_requested = False
    finally:
        inj.uninstall()
    st.stop()
    eng2 = NativeEngine("mem")
    st2 = DurableStore(eng2, StorageConfig(), str(tmp_path))
    st2.recover()
    assert eng2.get(b"lost") == b"3"
    assert eng2.get(b"pre") == b"1"
    st2.stop()
    eng2.close()
    eng.close()


# ------------------------------------------------- disk-full chaos (node)

def test_disk_full_degrades_node_then_reconverges(tmp_path):
    """The acceptance loop, in process: a disk-full write burst degrades
    the node to read-only with /healthz reflecting it; after space
    returns the node goes back to live and an anti-entropy pass
    converges both nodes' roots bit-identically — zero crashes."""
    from merklekv_tpu.cluster.node import ClusterNode
    from merklekv_tpu.storage.store import DurableStore
    from merklekv_tpu.testing.faults import WalErrnoInjector

    eng_a = NativeEngine("mem")
    srv_a = NativeServer(eng_a, "127.0.0.1", 0)
    srv_a.start()
    eng_b = NativeEngine("mem")
    srv_b = NativeServer(eng_b, "127.0.0.1", 0)
    srv_b.start()

    cfg_a = Config()
    cfg_a.server.watermark_interval_seconds = 0.02
    cfg_a.storage.fsync_interval_seconds = 0.01
    store = DurableStore(eng_a, cfg_a.storage, str(tmp_path / "a"))
    store.recover()
    node_a = ClusterNode(cfg_a, eng_a, srv_a, storage=store)
    node_b = ClusterNode(Config(), eng_b, srv_b)
    node_a.start()
    node_b.start()
    store.start()  # ticker: fsync + disk checks + recovery probe
    inj = WalErrnoInjector(fail_write_at=5).install()
    try:
        with MerkleKVClient("127.0.0.1", srv_a.port) as ca:
            # Burst until the node flips read-only (drain hits ENOSPC,
            # monitor reacts within ~20ms).
            deadline = time.time() + 10
            flipped = False
            i = 0
            while time.time() < deadline and not flipped:
                try:
                    ca.set(f"burst:{i:05d}", f"v-{i}")
                except (ServerBusyError, ReadOnlyError):
                    flipped = True
                    break
                i += 1
                if srv_a.degradation >= READ_ONLY:
                    flipped = True
            assert flipped or srv_a.degradation >= READ_ONLY, (
                "node never degraded under injected ENOSPC"
            )
            assert node_a._health_payload()["degradation"] == "read_only"
            assert node_a._health_payload()["status"] == "degraded"
            # Reads keep serving while read-only.
            assert ca.get("burst:00000") == "v-0"

            # Space returns: the probe recovers the store, the monitor
            # steps the node back to live.
            inj.heal()
            deadline = time.time() + 10
            while time.time() < deadline and srv_a.degradation != LIVE:
                time.sleep(0.02)
            assert srv_a.degradation == LIVE
            assert node_a._health_payload()["degradation"] == "live"
            ca.set("after:0", "v")  # writes accepted again

        # Anti-entropy pass: B := A (pairwise mirror) converges roots
        # bit-identically, repairing the divergence the shed window left.
        node_b.sync_manager.sync_once("127.0.0.1", srv_a.port)
        with MerkleKVClient("127.0.0.1", srv_a.port) as ca, MerkleKVClient(
            "127.0.0.1", srv_b.port
        ) as cb:
            assert ca.hash() == cb.hash()
            assert cb.get("after:0") == "v"
        assert _counter("storage.full_recoveries") >= 1
    finally:
        inj.uninstall()
        node_a.stop()
        node_b.stop()
        store.stop()
        srv_a.close()
        srv_b.close()
        eng_a.close()
        eng_b.close()


# ------------------------------------------------------- clock-skew guard

class _NullTransport:
    def publish(self, topic, payload):
        pass

    def subscribe(self, topic_prefix, callback):
        pass

    def unsubscribe(self, callback):
        pass

    def close(self):
        pass


def test_future_ts_poison_frame_clamped_and_repaired(server):
    """A frame stamped an hour in the future is clamped to now+skew
    (counted, per-peer attributed) BEFORE journal/apply, so the key is
    fenced for at most the skew window instead of forever."""
    from merklekv_tpu.cluster.change_event import (
        ChangeEvent,
        OpKind,
        encode_batch_cbor,
    )
    from merklekv_tpu.cluster.replicator import Replicator

    eng, srv = server
    rep = Replicator(
        eng, srv, _NullTransport(), node_id="me", max_skew_ms=100
    )
    poison_ts = time.time_ns() + 3_600_000_000_000  # +1h
    ev = ChangeEvent(
        op=OpKind.SET, key="poisoned", val=b"evil", ts=poison_ts, src="liar"
    )
    payload = encode_batch_cbor(
        [ev], "liar", hwm_seq=1, hwm_ts=time.time_ns()
    )
    before = _counter("replicator.skew_clamped")
    rep._on_message("t/events", payload)
    assert eng.get(b"poisoned") == b"evil"
    installed_ts = eng.get_ts(b"poisoned")
    assert installed_ts is not None
    assert installed_ts <= time.time_ns() + 150_000_000  # ~now + skew
    assert rep.skew_clamped == 1
    assert _counter("replicator.skew_clamped") == before + 1
    assert _counter("replicator.skew_clamped.liar") >= 1
    # Repaired: once the skew window passes, an honest write wins LWW.
    time.sleep(0.15)
    assert eng.set_if_newer(b"poisoned", b"honest", time.time_ns())
    assert eng.get(b"poisoned") == b"honest"
    # Disabled guard (max_skew_ms=0) leaves timestamps untouched.
    rep0 = Replicator(
        eng, srv, _NullTransport(), node_id="me", max_skew_ms=0
    )
    ev2 = ChangeEvent(
        op=OpKind.SET, key="raw", val=b"x", ts=poison_ts, src="liar"
    )
    rep0._on_message(
        "t/events",
        encode_batch_cbor([ev2], "liar", hwm_seq=1, hwm_ts=time.time_ns()),
    )
    assert eng.get_ts(b"raw") == poison_ts


def test_anti_entropy_repair_clamps_poisoned_peer_ts(server):
    """The skew guard also gates the repair-install boundary: a walk
    against the poisoning peer (which still holds the raw future ts in
    its engine) must not re-import what the replication clamp refused."""
    from merklekv_tpu.cluster.sync import SyncManager

    eng, srv = server  # the "local" node
    peer_eng = NativeEngine("mem")
    peer_srv = NativeServer(peer_eng, "127.0.0.1", 0)
    peer_srv.start()
    try:
        poison_ts = time.time_ns() + 3_600_000_000_000  # +1h on the peer
        peer_eng.set_with_ts(b"poisoned", b"evil", poison_ts)
        mgr = SyncManager(eng, device="cpu", max_skew_ms=100)
        before = _counter("anti_entropy.skew_clamped")
        mgr.sync_once("127.0.0.1", peer_srv.port)
        assert eng.get(b"poisoned") == b"evil"  # value adopted...
        ts = eng.get_ts(b"poisoned")
        assert ts is not None and ts <= time.time_ns() + 150_000_000
        assert _counter("anti_entropy.skew_clamped") > before
        # ...and an honest write wins once the skew window passes.
        time.sleep(0.15)
        assert eng.set_if_newer(b"poisoned", b"honest", time.time_ns())
    finally:
        peer_srv.close()
        peer_eng.close()


def test_full_disk_probe_backs_off_after_flapped_recovery(tmp_path):
    """A probe that succeeds while the re-anchor snapshot still cannot
    fit must not flap latch->recover->latch every tick: re-latching
    right after a recovery arms an escalating probe backoff, reset only
    by a snapshot that actually completes."""
    from merklekv_tpu.storage.store import DurableStore
    from merklekv_tpu.testing.faults import WalErrnoInjector

    eng = NativeEngine("mem")
    st = DurableStore(eng, StorageConfig(), str(tmp_path))
    st.recover()
    inj = WalErrnoInjector(fail_write_at=1).install()
    try:
        st.record_set(b"k", b"v", 1)
        assert st.storage_full
        inj.heal()
        st._check_disk()  # probe succeeds -> recovered
        assert not st.storage_full
        # The "re-anchor" write fails again (disk refilled instantly).
        inj2 = WalErrnoInjector(fail_write_at=1).install()
        st.record_set(b"k2", b"v", 2)
        assert st.storage_full
        assert st._probe_backoff_s >= 2.0  # flap detected: backoff armed
        inj2.heal()
        st._check_disk()
        assert st.storage_full  # still latched: probe deferred by backoff
        st._next_probe_m = 0.0  # (simulate the backoff elapsing)
        st._check_disk()
        assert not st.storage_full
        st.snapshot_now()  # a COMPLETED snapshot resets the backoff
        assert st._probe_backoff_s == 0.0
    finally:
        inj.uninstall()
        st.stop()
        eng.close()


# ------------------------------------------- event-queue observability

def test_event_queue_depth_and_drops_observable(server):
    """events.queue_depth / events.dropped travel on STATS, bridge into
    /metrics with catalog metadata, and move with the queue."""
    from merklekv_tpu.obs.catalog import CATALOG
    from merklekv_tpu.obs.exporter import render_prometheus

    eng, srv = server
    srv.enable_events(True)
    with MerkleKVClient("127.0.0.1", srv.port) as c:
        for i in range(5):
            c.set(f"q:{i}", "v")
        stats = c.stats()
        assert int(stats["events_queue_depth"]) == 5
        assert stats["events_dropped"].isdigit()
        assert srv.events_depth() == 5
        srv.drain_events()
        assert int(c.stats()["events_queue_depth"]) == 0
    page = render_prometheus(get_metrics(), srv.stats_text())
    assert "mkv_native_events_queue_depth" in page
    assert "mkv_native_events_dropped" in page
    assert "# TYPE mkv_native_events_dropped counter" in page
    assert "# TYPE mkv_native_events_queue_depth gauge" in page
    assert "native.events_queue_depth" in CATALOG
    assert "native.events_dropped" in CATALOG


# ------------------------------------------------ background-work yielding

def test_sync_loop_defers_cycles_under_overload(server):
    from merklekv_tpu.cluster.sync import SyncManager

    eng, srv = server
    mgr = SyncManager(eng, device="cpu")
    before_skips = _counter("anti_entropy.overload_skips")
    before_errors = _counter("anti_entropy.loop_errors")
    mgr.start_loop(
        ["127.0.0.1:1"],  # a dead peer: a RUN cycle would error loudly
        0.02,
        pause_when=lambda: True,
    )
    try:
        time.sleep(0.3)
    finally:
        mgr.stop()
    assert _counter("anti_entropy.overload_skips") >= before_skips + 3
    assert _counter("anti_entropy.loop_errors") == before_errors


def test_compaction_defers_under_memory_pressure(tmp_path):
    from merklekv_tpu.storage.store import DurableStore

    eng = NativeEngine("mem")
    st = DurableStore(
        eng,
        StorageConfig(
            fsync_interval_seconds=0.01, compact_trigger_bytes=64
        ),
        str(tmp_path),
    )
    st.recover()
    gate = {"pressure": True}
    st.set_defer_compaction(lambda: gate["pressure"])
    st.start()
    before = _counter("storage.compactions_deferred")
    try:
        for i in range(10):
            st.record_set(f"k:{i}".encode(), b"v" * 64, i + 1)
        deadline = time.time() + 5
        while (
            time.time() < deadline
            and _counter("storage.compactions_deferred") == before
        ):
            time.sleep(0.02)
        assert _counter("storage.compactions_deferred") > before
        snaps_before = _counter("storage.snapshots")
        gate["pressure"] = False  # pressure released: trigger still fires
        deadline = time.time() + 5
        while (
            time.time() < deadline
            and _counter("storage.snapshots") == snaps_before
        ):
            time.sleep(0.02)
        assert _counter("storage.snapshots") > snaps_before
    finally:
        st.stop()
        eng.close()


# ----------------------------------------------------- METRICS / healthz

def test_node_metrics_lines_and_gauge(server):
    from merklekv_tpu.cluster.node import ClusterNode

    eng, srv = server
    node = ClusterNode(Config(), eng, srv)
    node.start()
    try:
        with MerkleKVClient("127.0.0.1", srv.port) as c:
            m = c.metrics()
            assert m.get("node.degradation") == "0"
            assert "node.shed_total" in m
            assert "node.readonly_rejected" in m
            # All values stay integer text (the METRICS block contract).
            assert all(v.lstrip("-").isdigit() for v in m.values()), m
            srv.set_degradation(1, 1)
            with pytest.raises(ServerBusyError):
                c.set("x", "y")
            m = c.metrics()
            assert int(m["node.shed_total"]) >= 1
            payload = node._health_payload()
            assert payload["degradation"] == "live"  # ladder, not admin push
    finally:
        srv.set_degradation(0, 0)
        node.stop()


def test_bench_gate_direction_for_overload_goodput():
    import sys
    import os

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    from bench_gate import lower_is_better

    # Goodput is throughput: DROPPING it is the regression.
    assert lower_is_better(
        "overload_goodput", "ops/s (accepted under ~2x offered load)"
    ) is False


# ------------------------------------------------------------- slow soak

@pytest.mark.slow
def test_soak_repeated_disk_full_cycles(tmp_path):
    """Inject-heal ENOSPC repeatedly; every cycle must degrade, recover,
    and keep the store's journal consistent with the engine."""
    from merklekv_tpu.storage.store import DurableStore
    from merklekv_tpu.testing.faults import WalErrnoInjector

    eng = NativeEngine("mem")
    st = DurableStore(eng, StorageConfig(), str(tmp_path))
    st.recover()
    try:
        for cycle in range(5):
            inj = WalErrnoInjector(fail_write_at=1).install()
            try:
                for i in range(20):
                    ts = cycle * 1000 + i + 1
                    eng.set_with_ts(f"c{cycle}:{i}".encode(), b"v", ts)
                    st.record_set(f"c{cycle}:{i}".encode(), b"v", ts)
                assert st.storage_full
                inj.heal()
                st._check_disk()
                assert not st.storage_full
                st.snapshot_now()
                st._snapshot_requested = False
            finally:
                inj.uninstall()
        st.stop()
        eng2 = NativeEngine("mem")
        st2 = DurableStore(eng2, StorageConfig(), str(tmp_path))
        st2.recover()
        for cycle in range(5):
            for i in range(20):
                assert eng2.get(f"c{cycle}:{i}".encode()) == b"v", (cycle, i)
        st2.stop()
        eng2.close()
    finally:
        eng.close()


@pytest.mark.slow
def test_soak_connection_flood_cycles(server):
    """Repeated flood rounds: the server neither leaks handler threads
    nor stops serving its established connections."""
    eng, srv = server
    srv.set_limits(max_connections=2)
    a = MerkleKVClient("127.0.0.1", srv.port).connect()
    b = MerkleKVClient("127.0.0.1", srv.port).connect()
    try:
        assert a.ping().startswith("PONG")
        assert b.ping().startswith("PONG")  # both slots occupied
        for _ in range(5):
            for _ in range(50):
                try:
                    s = socket.create_connection(
                        ("127.0.0.1", srv.port), timeout=1
                    )
                    s.recv(64)
                    s.close()
                except OSError:
                    pass
            assert a.ping().startswith("PONG")
        assert int(a.stats()["busy_rejected_connections"]) >= 250
        assert int(a.stats()["active_connections"]) <= 3
    finally:
        a.close()
        b.close()

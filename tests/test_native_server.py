"""Embedded native TCP server: protocol conformance + client SDK.

Response shapes and error strings must match the reference surface
(SURVEY.md §2.2; /root/reference/src/server.rs:547-924, protocol.rs:237-774).
Runs the server in-process via ctypes on an ephemeral port.
"""

import socket
import threading

import pytest

from merklekv_tpu.client import MerkleKVClient, ProtocolError
from merklekv_tpu.merkle import MerkleTree
from merklekv_tpu.native_bindings import (
    OP_DEL,
    OP_INCR,
    OP_SET,
    NativeEngine,
    NativeServer,
)


@pytest.fixture
def server():
    eng = NativeEngine("mem")
    srv = NativeServer(eng, "127.0.0.1", 0, version="0.1.0")
    srv.start()
    yield srv
    srv.close()
    eng.close()


@pytest.fixture
def client(server):
    c = MerkleKVClient("127.0.0.1", server.port).connect()
    yield c
    c.close()


def test_leafhashes_parity_and_prefix(client):
    from merklekv_tpu.merkle.encoding import leaf_hash

    assert client.leaf_hashes() == {}
    client.mset({"a:1": "v1", "a:2": "v2", "b:1": "v3"})
    hashes = client.leaf_hashes()
    assert sorted(hashes) == ["a:1", "a:2", "b:1"]
    for k, hx in hashes.items():
        assert hx == leaf_hash(k.encode(), client.get(k).encode()).hex()
    assert sorted(client.leaf_hashes("a:")) == ["a:1", "a:2"]
    assert client.leaf_hashes("zz") == {}


def test_leafhashes_rejects_extra_args(client):
    with pytest.raises(ProtocolError, match="only one argument"):
        client.leaf_hashes("a b")


def test_stats_info_end_terminated(server):
    out = raw(server, b"STATS\r\nINFO\r\n")[0].decode()
    stats_block, info_block = out.split("INFO\r\n", 1)
    assert stats_block.startswith("STATS\r\n")
    assert stats_block.rstrip("\r\n").endswith("END")
    assert info_block.rstrip("\r\n").endswith("END")


def raw(server, *lines) -> list[bytes]:
    """Send raw lines on a fresh socket, return full response bytes."""
    s = socket.create_connection(("127.0.0.1", server.port), timeout=5)
    out = []
    for line in lines:
        s.sendall(line)
        chunks = b""
        s.settimeout(0.5)
        try:
            while True:
                d = s.recv(65536)
                if not d:
                    break
                chunks += d
        except socket.timeout:
            pass
        out.append(chunks)
    s.close()
    return out


# ------------------------------------------------------------ basic ops

def test_set_get_delete(client):
    assert client.set("k", "v")
    assert client.get("k") == "v"
    assert client.delete("k") is True
    assert client.delete("k") is False
    assert client.get("k") is None


def test_values_with_spaces(client):
    client.set("k", "a value with spaces")
    assert client.get("k") == "a value with spaces"


def test_empty_value_rejected_by_framing(client):
    # "SET k " trims to "SET k" (reference input.trim(), protocol.rs:238):
    # an empty value cannot be expressed on the wire.
    with pytest.raises(ProtocolError, match="requires a key and value"):
        client.set("k", "")


def test_numeric(client):
    assert client.increment("n") == 1
    assert client.increment("n", 10) == 11
    assert client.decrement("n", 5) == 6
    assert client.decrement("new") == -1
    client.set("s", "xyz")
    with pytest.raises(ProtocolError, match="not a valid number"):
        client.increment("s")


def test_append_prepend(client):
    assert client.append("greet", "world") == "world"
    # Trailing whitespace in a value is trimmed by framing; inner is kept.
    assert client.prepend("greet", "hello") == "helloworld"
    assert client.append("greet", "and more") == "helloworldand more"
    # An empty value is unexpressible on the wire (trimmed away), so the
    # server.rs:772-779 empty-value branch surfaces as a parse error.
    with pytest.raises(ProtocolError, match="requires a key and value"):
        client.append("nope", "")


def test_bulk(client):
    client.mset({"a": "1", "b": "2", "c": "3"})
    got = client.mget(["a", "b", "missing"])
    assert got == {"a": "1", "b": "2", "missing": None}
    assert client.mget(["m1", "m2"]) == {"m1": None, "m2": None}
    assert client.truncate()
    assert client.dbsize() == 0


def test_query(client):
    client.mset({"user:1": "a", "user:2": "b", "other": "c"})
    assert client.exists("user:1", "other", "nope") == 2
    assert client.scan("user:") == ["user:1", "user:2"]
    assert client.scan() == ["other", "user:1", "user:2"]
    assert client.dbsize() == 3


def test_hash_parity_with_python_merkle(client):
    items = [(f"hk{i}", f"hv{i * 3}") for i in range(23)]
    for k, v in items:
        client.set(k, v)
    assert client.hash() == MerkleTree.from_items(items).root_hex()
    # Prefix pattern
    sub = [(k, v) for k, v in items if k.startswith("hk1")]
    assert client.hash("hk1") == MerkleTree.from_items(sub).root_hex()
    # '*' = all keys
    assert client.hash("*") == client.hash()


def test_hash_empty_is_64_zeros(client):
    assert client.hash() == "0" * 64


def test_admin(client):
    assert client.ping() == "PONG "
    assert client.ping("hello") == "PONG hello"
    assert client.echo("hi there") == "hi there"
    assert client.version() == "0.1.0"
    client.set("k", "v")
    assert client.memory() == 2
    info = client.info()
    assert info["version"] == "0.1.0"
    assert info["db_keys"] == "1"
    stats = client.stats()
    assert int(stats["set_commands"]) >= 1
    assert int(stats["total_commands"]) >= 1
    assert "used_memory_kb" in stats
    rows = client.client_list()
    assert len(rows) == 1 and "addr" in rows[0]
    assert client.flushdb()
    assert client.dbsize() == 0


def test_stats_counter_mapping(client):
    client.ping()
    client.flushdb()
    stats = client.stats()
    # Reference quirk parity: FLUSHDB counts as management (server.rs:255-262).
    assert stats["flushdb_commands"] == "0"
    assert int(stats["management_commands"]) >= 1
    assert int(stats["ping_commands"]) >= 1


def test_replicate_defaults(client):
    assert client.replicate("status") == "REPLICATION disabled"
    with pytest.raises(ProtocolError, match="replication not configured"):
        client.replicate("enable")


# ------------------------------------------------------------ raw protocol

@pytest.mark.parametrize(
    "line,expect",
    [
        (b"GET\r\n", b"ERROR GET command requires arguments\r\n"),
        (b"GET a b\r\n", b"ERROR GET command accepts only one argument\r\n"),
        (b"SET k\r\n", b"ERROR SET command requires a key and value\r\n"),
        (b"SET  v\r\n", b"ERROR SET command key cannot be empty\r\n"),
        (b"DEL\r\n", b"ERROR DEL command requires arguments\r\n"),
        (b"DBSIZE x\r\n", b"ERROR DBSIZE command does not accept any arguments\r\n"),
        (b"ECHO\r\n", b"ERROR ECHO command requires arguments\r\n"),
        (b"INC 5\r\n", b"ERROR INC command requires a key\r\n"),
        (b"INC k abc\r\n", b"ERROR INC command amount must be a valid number\r\n"),
        (b"MSET a\r\n",
         b"ERROR MSET command requires an even number of arguments (key-value pairs)\r\n"),
        (b"GET k\tx\r\n",
         b"ERROR Invalid character: tab character not allowed in key\r\n"),
        (b"NOSUCH\r\n", b"ERROR Unknown command: NOSUCH\r\n"),
        (b"NOSUCH args\r\n", b"ERROR Unknown command: NOSUCH\r\n"),
        (b"REPLICATE bogus\r\n", b"ERROR Unknown REPLICATE action: bogus\r\n"),
        (b"SYNC h notaport\r\n",
         b"ERROR Invalid port: must be an integer in 0..=65535\r\n"),
        (b"CLIENT FOO\r\n", b"ERROR Unknown CLIENT subcommand\r\n"),
        (b"\r\n", b"ERROR Empty command\r\n"),
        (b"get lowercase_missing\r\n", b"NOT_FOUND\r\n"),
    ],
)
def test_error_messages(server, line, expect):
    assert raw(server, line)[0] == expect


def test_set_preserves_inner_spaces(server):
    out = raw(server, b"SET k  leading\r\n", b"GET k\r\n")
    assert out[0] == b"OK\r\n"
    assert out[1] == b"VALUE  leading\r\n"  # value is " leading"


def test_tab_allowed_in_value(server):
    out = raw(server, b"SET k a\tb\r\n", b"GET k\r\n")
    assert out[0] == b"OK\r\n"
    assert out[1] == b"VALUE a\tb\r\n"


def test_line_too_long_closes_connection(server):
    big = b"SET k " + b"x" * (1024 * 1024 + 16) + b"\r\n"
    out = raw(server, big)
    assert out[0] == b"ERROR line too long\r\n"


def test_large_value_roundtrip(server):
    v = b"y" * (512 * 1024)
    out = raw(server, b"SET big " + v + b"\r\n", b"GET big\r\n")
    assert out[0] == b"OK\r\n"
    assert out[1] == b"VALUE " + v + b"\r\n"


def test_pipelined_commands_one_packet(server):
    out = raw(server, b"SET a 1\r\nSET b 2\r\nGET a\r\nGET b\r\n")
    assert out[0] == b"OK\r\nOK\r\nVALUE 1\r\nVALUE 2\r\n"


# ------------------------------------------------------------ events

def test_change_events_drained(server, client):
    # Staging is opt-in: writes before enable_events are not staged.
    client.set("pre-enable", "x")
    server.enable_events(True)
    assert server.drain_events() == []
    client.set("k1", "v1")
    client.increment("n", 2)
    client.delete("k1")
    evs = server.drain_events()
    assert [(e.op, e.key) for e in evs] == [
        (OP_SET, b"k1"),
        (OP_INCR, b"n"),
        (OP_DEL, b"k1"),
    ]
    assert evs[1].value == b"2"  # post-op value
    assert not evs[2].has_value
    assert evs[0].seq < evs[1].seq < evs[2].seq
    assert server.drain_events() == []


# ------------------------------------------------------------ concurrency

def test_many_concurrent_clients(server):
    errors = []

    def worker(tid):
        try:
            with MerkleKVClient("127.0.0.1", server.port) as c:
                for i in range(50):
                    c.set(f"c{tid}:{i}", str(i))
                    assert c.get(f"c{tid}:{i}") == str(i)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(20)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    with MerkleKVClient("127.0.0.1", server.port) as c:
        assert c.dbsize() == 20 * 50


def test_cluster_callback_routes_sync(server):
    seen = []

    def handler(line):
        seen.append(line)
        return "OK\r\n" if line.startswith("SYNC") else None

    server.set_cluster_handler(handler)
    with MerkleKVClient("127.0.0.1", server.port) as c:
        assert c.sync_with("peer.example", 7380, full=True)
    assert seen == ["SYNC peer.example 7380 --full"]


def test_shutdown_stops_embedded_server(server):
    with MerkleKVClient("127.0.0.1", server.port) as c:
        c.shutdown()
    import time

    for _ in range(100):
        if server.stopping:
            break
        time.sleep(0.01)
    assert server.stopping


# ------------------------------------------------------------ async client

def test_async_client(server):
    import asyncio

    from merklekv_tpu.client import AsyncMerkleKVClient

    async def go():
        async with AsyncMerkleKVClient("127.0.0.1", server.port) as c:
            await c.set("ak", "av")
            assert await c.get("ak") == "av"
            assert await c.increment("an", 4) == 4
            assert await c.scan("a") == ["ak", "an"]
            assert await c.health_check()
            assert await c.pipeline(["SET p 1", "GET p"]) == ["OK", "VALUE 1"]

    asyncio.run(go())


def test_protocol_fuzz_survives_garbage(server):
    """Seeded fuzz: random byte soup, malformed verbs, pathological
    framings. The server must never die, never hang, and must still answer
    a clean PING/SET/GET on a fresh connection afterwards."""
    import random
    import socket as socket_mod

    rng = random.Random(0xFABC)
    verbs = [b"GET", b"SET", b"DEL", b"INC", b"MGET", b"MSET", b"SCAN",
             b"HASH", b"LEAFHASHES", b"STATS", b"EXISTS", b"SYNC", b"PEERS",
             b"CLIENT", b"REPLICATE", b"XYZZY", b""]

    def rand_line() -> bytes:
        kind = rng.randrange(5)
        if kind == 0:  # pure byte soup (no LF — appended below)
            return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 80))
                         ).replace(b"\n", b"x")
        if kind == 1:  # verb + junk args
            parts = [rng.choice(verbs)]
            parts += [bytes(rng.randrange(33, 127) for _ in
                            range(rng.randrange(0, 20)))
                      for _ in range(rng.randrange(0, 5))]
            return b" ".join(parts)
        if kind == 2:  # embedded tabs / control chars in odd places
            return rng.choice(verbs) + b"\t" + b"\x01\x02 key \tval"
        if kind == 3:  # whitespace-only / bare CR
            return rng.choice([b"", b" ", b"   ", b"\r", b" \t "])
        # almost-valid commands with wrong arity
        return rng.choice([b"SET onlykey", b"INC", b"MSET a", b"DEL",
                           b"EXISTS", b"HASH a b c", b"GET a b"])

    for conn_round in range(8):
        s = socket_mod.create_connection(("127.0.0.1", server.port), timeout=5)
        s.settimeout(5)
        try:
            try:
                for _ in range(50):
                    s.sendall(rand_line() + b"\r\n")
            except (BrokenPipeError, ConnectionResetError, OSError):
                continue  # server closed on us mid-round: acceptable
            # Drain whatever came back; the server may also have closed on
            # us (line-too-long rule) — both are acceptable, crashing isn't.
            s.setblocking(False)
            try:
                while s.recv(65536):
                    pass
            except (BlockingIOError, ConnectionResetError, OSError):
                pass
        finally:
            s.close()

    # The server is still healthy for a well-behaved client.
    c = MerkleKVClient("127.0.0.1", server.port).connect()
    try:
        c.set("fuzz:alive", "yes")
        assert c.get("fuzz:alive") == "yes"
        assert len(c.hash()) == 64
    finally:
        c.close()


def test_rapid_connect_disconnect_churn(server):
    """Connection lifecycle stress: 150 connects, a third dropped with a
    half-written line, a third closed immediately, a third doing one real
    command — then the server must still serve and its CLIENT LIST must
    not leak dead connections."""
    import socket as socket_mod

    for i in range(150):
        s = socket_mod.create_connection(("127.0.0.1", server.port), timeout=5)
        mode = i % 3
        if mode == 0:
            s.close()  # immediate drop
        elif mode == 1:
            s.sendall(b"SET half:key half-a-line-with-no-termina")
            s.close()  # torn mid-line
        else:
            s.sendall(b"PING\r\n")
            s.settimeout(5)
            assert s.recv(64).startswith(b"PONG")
            s.close()

    c = MerkleKVClient("127.0.0.1", server.port).connect()
    try:
        c.set("churn:alive", "yes")
        assert c.get("churn:alive") == "yes"
        # No half-written SET may have committed.
        assert c.get("half:key") is None
        # Handler threads reaped: the live-connection table holds only this
        # client (plus possibly a raced, not-yet-reaped drop or two).
        lines = c.client_list()
        assert len(lines) <= 5, lines
    finally:
        c.close()


def test_unicode_keys_and_values_roundtrip(client):
    """UTF-8 text protocol: multibyte keys and values round-trip exactly
    and feed HASH/LEAFHASHES without error (reference parity:
    tests/integration/test_error_handling.py unicode cases)."""
    pairs = {
        "uni:café": "crème brûlée",
        "uni:日本語": "値-こんにちは",
        "uni:emoji": "🚀 0x1F680 🎉",
        "uni:mixed": "Ωμέγα Ω tail",
    }
    for k, v in pairs.items():
        client.set(k, v)
    for k, v in pairs.items():
        assert client.get(k) == v
    assert client.exists(*pairs.keys()) == len(pairs)
    assert sorted(client.scan("uni:")) == sorted(pairs.keys())
    root = client.hash()
    assert len(root) == 64
    client.set("uni:café", "changed")
    assert client.hash() != root

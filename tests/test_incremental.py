"""Incremental device Merkle state vs the golden CPU tree."""

import numpy as np
import pytest

from merklekv_tpu.merkle.cpu import MerkleTree
from merklekv_tpu.merkle.incremental import DeviceMerkleState


def cpu_root(values: dict[bytes, bytes]):
    t = MerkleTree()
    for k, v in values.items():
        t.insert(k.decode(), v.decode())
    return t.root_hash()


@pytest.mark.parametrize("n", [1, 2, 3, 37, 64, 100])
def test_initial_build_matches_cpu(n):
    items = {b"ik%04d" % i: b"iv%d" % (i * 3) for i in range(n)}
    st = DeviceMerkleState.from_items(items.items())
    assert st.root_hash() == cpu_root(items)


def test_empty_state():
    st = DeviceMerkleState()
    assert st.root_hash() is None
    assert st.root_hex() == "0" * 64


def test_value_updates_are_incremental():
    items = {b"uk%04d" % i: b"v%d" % i for i in range(53)}
    st = DeviceMerkleState.from_items(items.items())
    st.root_hash()  # initial build
    assert st.full_rebuilds == 1

    # Several rounds of in-place value updates: no further rebuilds.
    rng = np.random.RandomState(5)
    for round_ in range(4):
        ks = [b"uk%04d" % i for i in rng.choice(53, size=7, replace=False)]
        changes = [(k, b"new-%d-%d" % (round_, i)) for i, k in enumerate(ks)]
        for k, v in changes:
            items[k] = v
        st.apply(changes)
        assert st.root_hash() == cpu_root(items)
    assert st.full_rebuilds == 1
    assert st.incremental_batches == 4


def test_single_key_update():
    items = {b"a": b"1", b"b": b"2", b"c": b"3"}
    st = DeviceMerkleState.from_items(items.items())
    st.root_hash()
    items[b"b"] = b"changed"
    st.apply([(b"b", b"changed")])
    assert st.root_hash() == cpu_root(items)
    assert st.full_rebuilds == 1


def test_insert_is_structural_not_full_rebuild():
    items = {b"a": b"1", b"b": b"2"}
    st = DeviceMerkleState.from_items(items.items())
    st.root_hash()
    items[b"aa"] = b"between"  # shifts sorted positions
    st.apply([(b"aa", b"between")])
    assert st.root_hash() == cpu_root(items)
    # Survivor digests were gathered on device — no host re-hash of the
    # whole keyspace.
    assert st.full_rebuilds == 1
    assert st.structural_batches == 1


def test_delete_is_structural_not_full_rebuild():
    items = {b"a": b"1", b"b": b"2", b"c": b"3"}
    st = DeviceMerkleState.from_items(items.items())
    st.root_hash()
    del items[b"b"]
    st.apply([(b"b", None)])
    assert st.root_hash() == cpu_root(items)
    assert st.full_rebuilds == 1
    assert st.structural_batches == 1


def test_mixed_batch_update_then_insert():
    items = {b"mk%03d" % i: b"v%d" % i for i in range(20)}
    st = DeviceMerkleState.from_items(items.items())
    st.root_hash()
    # Batch mixing in-place updates with an insert: correctness first.
    changes = [(b"mk005", b"x5"), (b"zz-new", b"nv"), (b"mk011", b"x11")]
    items[b"mk005"] = b"x5"
    items[b"zz-new"] = b"nv"
    items[b"mk011"] = b"x11"
    st.apply(changes)
    assert st.root_hash() == cpu_root(items)


def test_update_missing_key_is_insert():
    st = DeviceMerkleState.from_items([(b"k", b"v")])
    st.root_hash()
    st.apply([(b"new", b"nv")])
    assert st.root_hash() == cpu_root({b"k": b"v", b"new": b"nv"})


def test_capacity_padding_at_non_pow2_counts():
    # n just below / at / above powers of two exercises the promotion walk.
    for n in (31, 32, 33, 63, 65):
        items = {b"pk%04d" % i: b"pv%d" % i for i in range(n)}
        st = DeviceMerkleState.from_items(items.items())
        assert st.root_hash() == cpu_root(items), n
        # and after an in-place update
        items[b"pk%04d" % (n // 2)] = b"mut"
        st.apply([(b"pk%04d" % (n // 2), b"mut")])
        assert st.root_hash() == cpu_root(items), n


def test_structural_fuzz_matches_cpu():
    """Random mixed batches (insert/update/delete) against the golden tree.

    This is the honesty check for the gather-restructure path: after every
    batch the device root must equal the CPU reference root of the evolved
    keyspace, across capacity growth and shrink."""
    rng = np.random.RandomState(11)
    items = {b"fz%04d" % i: b"v%d" % i for i in range(40)}
    st = DeviceMerkleState.from_items(items.items())
    st.root_hash()
    universe = [b"fz%04d" % i for i in range(80)]
    for round_ in range(12):
        batch = []
        for _ in range(rng.randint(1, 9)):
            k = universe[rng.randint(len(universe))]
            if rng.rand() < 0.3 and k in items:
                del items[k]
                batch.append((k, None))
            else:
                v = b"r%d-%d" % (round_, rng.randint(1000))
                items[k] = v
                batch.append((k, v))
        st.apply(batch)
        assert st.root_hash() == cpu_root(items), f"round {round_}"
        assert len(st) == len(items)
    assert st.full_rebuilds == 1  # never re-hashed the surviving keyspace


def test_delete_all_then_refill():
    items = {b"da%02d" % i: b"v" for i in range(5)}
    st = DeviceMerkleState.from_items(items.items())
    st.root_hash()
    st.apply([(k, None) for k in items])
    assert st.root_hash() is None
    assert st.root_hex() == "0" * 64
    st.apply([(b"fresh", b"start")])
    assert st.root_hash() == cpu_root({b"fresh": b"start"})


def test_capacity_growth_and_shrink():
    items = {b"cg%03d" % i: b"v%d" % i for i in range(30)}
    st = DeviceMerkleState.from_items(items.items())  # capacity 32
    st.root_hash()
    adds = {b"cg9%02d" % i: b"n%d" % i for i in range(10)}  # -> capacity 64
    items.update(adds)
    st.apply(list(adds.items()))
    assert st.root_hash() == cpu_root(items)
    drops = list(items)[:35]  # -> 5 keys, capacity shrinks
    for k in drops:
        del items[k]
    st.apply([(k, None) for k in drops])
    assert st.root_hash() == cpu_root(items)
    assert st.full_rebuilds == 1


def test_batch_coalesces_same_key():
    items = {b"a": b"1"}
    st = DeviceMerkleState.from_items(items.items())
    st.root_hash()
    # Same key written twice then deleted within one batch: last wins.
    st.apply([(b"b", b"x"), (b"b", b"y"), (b"a", None), (b"a", b"back")])
    assert st.root_hash() == cpu_root({b"a": b"back", b"b": b"y"})


def test_single_key_applies_amortize_into_one_batch():
    """A stream of per-write apply() calls (the mirror's remote-apply shape)
    must coalesce into ONE device batch at the next root query — per-write
    O(n) restructures would collapse remote-apply throughput."""
    items = {b"am%03d" % i: b"v" for i in range(50)}
    st = DeviceMerkleState.from_items(items.items())
    st.root_hash()
    for i in range(30):  # 30 separate single-key inserts
        k = b"zz%03d" % i
        items[k] = b"n"
        st.apply([(k, b"n")])
    assert st.structural_batches == 0  # nothing flushed yet
    assert st.root_hash() == cpu_root(items)
    assert st.structural_batches == 1  # all 30 in one batch
    assert st.full_rebuilds == 1


def test_leaf_digest_view():
    from merklekv_tpu.merkle.encoding import leaf_hash

    st = DeviceMerkleState.from_items([(b"k1", b"v1"), (b"k2", b"v2")])
    st.root_hash()
    assert st.leaf_digest(b"k1") == leaf_hash(b"k1", b"v1")
    assert st.leaf_digest(b"missing") is None
